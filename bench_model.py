#!/usr/bin/env python3
"""Model-performance benchmark: the framework's OWN workload numbers on the
local accelerator (VERDICT r1 #2 — "fast" must be measured, not asserted).

Measures, on whatever chip JAX sees (designed for one TPU v5e):

1. training throughput — full train step (fwd+bwd+adamw) of the flagship
   decoder transformer, bf16 + flash attention + remat, seq >= 2k:
   tokens/sec, step time, and achieved MFU vs the chip's bf16 peak;
2. flash-vs-dense attention speedup — Pallas flash attention core vs the
   XLA dense softmax core at growing sequence lengths;
3. decode throughput — KV-cached autoregressive generation tokens/sec,
   MHA vs grouped-query (n_kv_heads=4) at the same model size;
4. mixed-load serving — a long prompt arriving mid-decode: decode
   tokens/s during the admission window, the long request's TTFT, and
   p50/p99 inter-token latency, monolithic prefill vs the chunked
   token-budget scheduler (`prefill_budget` + the overlapped host loop).

All timings use the two-point marginal method (profiling.marginal_ms): N
iterations inside one jitted computation with a live data dependency,
forced scalar fetch, slope between two N values — the only honest
measurement on the tunneled axon backend, whose block_until_ready returns
before the device finishes (naive timings there "beat" the chip's
physical peak by 20x).

Prints one JSON line per measurement; --out FILE also writes them to a
checked-in artifact (BENCH_MODEL.json). --smoke runs a tiny config (CI /
CPU-mesh sanity; numbers are meaningless there, structure is identical).

    python bench_model.py [--smoke] [--steps N] [--out BENCH_MODEL.json]
"""

import argparse
import json
import sys

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp


# bf16 peak TFLOP/s per chip by TPU generation (public spec sheets).
# Ordered: device_kind strings are e.g. "TPU v5 lite" (v5e), "TPU v5p",
# "TPU v4" — match the most specific marker first.
PEAK_BF16 = [
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v5p", 459e12), ("v4", 275e12),
]


def flagship_cfg(smoke: bool):
    from kubetpu.jobs import ModelConfig

    if smoke:
        return ModelConfig(vocab=256, d_model=128, n_layers=2, n_heads=4,
                           d_ff=256, max_seq=512, dtype=jnp.bfloat16, remat=True)
    # ~0.75B params: fits one v5e (16 GiB) with adamw + remat at seq 2048
    return ModelConfig(vocab=32000, d_model=2048, n_layers=12, n_heads=16,
                       d_ff=5632, max_seq=4096, dtype=jnp.bfloat16, remat=True)


def param_count(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def chip_peak_flops():
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    for key, peak in PEAK_BF16:
        if key in kind:
            return peak
    return None


def train_throughput(cfg, batch, seq, steps, attention, remat_policy="full",
                     loss_chunk=0, block_q=128, block_k=128):
    import dataclasses

    from kubetpu.jobs import init_state, make_mesh, make_train_step
    from kubetpu.jobs.profiling import marginal_ms

    cfg = dataclasses.replace(cfg, remat_policy=remat_policy,
                              loss_chunk=loss_chunk)
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1}, devices=jax.devices()[:1])
    state, opt = init_state(jax.random.PRNGKey(0), cfg, mesh)
    n_params = param_count(state.params)
    raw_step = make_train_step(cfg, mesh, optimizer=opt, attention=attention,
                               jit=False, block_q=block_q, block_k=block_k)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab,
                                jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)

    # Marginal-cost timing: n chained steps inside ONE jitted fori_loop,
    # fetched through the final loss — see profiling.marginal_ms for why
    # (the tunneled backend's block_until_ready is advisory).
    def make_run(n):
        @jax.jit
        def run(st):
            def body(_, carry):
                st, _ = carry
                return raw_step(st, tokens, targets)

            _, loss = jax.lax.fori_loop(0, n, body, (st, jnp.zeros(())))
            return loss

        return lambda: run(state)

    n1 = max(1, steps // 4)
    dt = marginal_ms(make_run, n1, n1 + steps, reps=2) / 1e3
    tokens_per_s = batch * seq / dt
    # FLOPs/token for fwd+bwd: 6*P (matmul params) + 12*L*D*S (causal
    # attention scores+values, fwd 4*L*D*S and bwd 2x) — the PaLM appendix
    # accounting. Remat re-computes the fwd once more: +50% of the fwd
    # third, i.e. x(8/6) on the model term when counting HARDWARE flops;
    # MFU convention counts MODEL flops, so remat overhead shows up as
    # lower MFU, which is what we want to observe.
    flops_per_token = 6 * n_params + 12 * cfg.n_layers * cfg.d_model * seq
    peak = chip_peak_flops()
    mfu = tokens_per_s * flops_per_token / peak if peak else None
    del state
    return {
        "metric": "train_tokens_per_s",
        "value": round(tokens_per_s, 1),
        "unit": "tokens/s",
        "step_ms": round(dt * 1e3, 2),
        "batch": batch,
        "seq": seq,
        "params": n_params,
        "attention": attention,
        "remat": remat_policy,
        "loss_chunk": loss_chunk,
        "block_q": block_q,
        "block_k": block_k,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "device": getattr(jax.devices()[0], "device_kind", str(jax.devices()[0])),
    }


def flash_vs_dense(cfg, seqs):
    """Yields one result per seq (a generator, so --out sees partial
    progress even if a later, bigger seq OOMs or times out)."""
    from kubetpu.jobs.model import dense_causal_attention

    if jax.default_backend() == "cpu":
        return  # Pallas TPU kernels don't run on the CPU backend
    from kubetpu.ops import flash_attention

    from kubetpu.jobs.profiling import marginal_ms

    b, h, d = (2, cfg.n_heads, cfg.head_dim)
    for seq in seqs:
        q, k, v = (
            jax.random.normal(jax.random.PRNGKey(i), (b, seq, h, d), jnp.bfloat16)
            for i in range(3)
        )

        def timeit(attn):
            # eps is a TRACED zero: `q + eps*r` keeps a live inter-iteration
            # dependency XLA cannot CSE away, without changing the values.
            # The chain is UNROLLED (python loop in the trace): wrapping the
            # Pallas kernel in lax.fori_loop/while stalls the tunnel
            # backend's compiler for minutes (observed >9 min vs seconds
            # unrolled). k/v ride as ARGUMENTS, not closure constants —
            # closed-over device arrays get baked into the compile as
            # literals (tens of MB at seq 8k).
            def make_run(n):
                @jax.jit
                def run(q0, k, v, eps):
                    qq = q0
                    for _ in range(n):
                        r = attn(qq, k, v)
                        qq = qq + eps * r.astype(qq.dtype)
                    return qq[0, 0, 0, 0].astype(jnp.float32)

                return lambda: run(q, k, v, jnp.zeros((), q.dtype))

            return marginal_ms(make_run, 2, 8, reps=2)

        fms = timeit(lambda q, k, v: flash_attention(q, k, v))
        try:
            dms = timeit(dense_causal_attention)
        except Exception:  # noqa: BLE001 — dense OOMs first at long seq
            dms = None
        yield {
            "metric": "flash_vs_dense_speedup",
            "seq": seq,
            "flash_ms": round(fms, 3),
            "dense_ms": round(dms, 3) if dms else None,
            "value": round(dms / fms, 2) if dms else None,
            "unit": "x",
        }
        if seq >= 4096:
            # sliding window at long seq: per-position work is O(window),
            # so the kernel's block skip should show ~seq/(2*window)-ish
            # gains over full causal flash
            W = 1024
            wms = timeit(
                lambda q, k, v: flash_attention(q, k, v, window=W)
            )
            yield {
                "metric": "flash_window_speedup",
                "seq": seq,
                "window": W,
                "window_ms": round(wms, 3),
                "full_ms": round(fms, 3),
                "value": round(fms / wms, 2),
                "unit": "x",
            }


def decode_throughput(cfg, batch, prompt_len, gen_steps, n_kv_heads,
                      int8: bool = False, kv_int8: bool = False):
    import dataclasses

    from kubetpu.jobs import init_params
    from kubetpu.jobs.decode import make_generate

    dcfg = dataclasses.replace(cfg, n_kv_heads=n_kv_heads, remat=False)
    params = init_params(jax.random.PRNGKey(0), dcfg)
    if int8:
        from kubetpu.jobs.quant import quantize_params

        params = quantize_params(params)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len), 0,
                                dcfg.vocab, jnp.int32)
    from kubetpu.jobs.profiling import marginal_ms

    gen = make_generate(dcfg, kv_int8=kv_int8)

    # Marginal per decode step across two generation lengths — the scan is
    # already inside one jitted call; the fetch of a generated token forces
    # completion (block_until_ready is advisory on the tunneled backend).
    def make_run(n):
        return lambda: gen(params, prompt, jax.random.PRNGKey(3), n)[0, -1]

    n1 = max(8, gen_steps // 8)
    step_ms = marginal_ms(make_run, n1, n1 + gen_steps, reps=2)
    dt = gen_steps * step_ms / 1e3
    del params
    return {
        "metric": "decode_tokens_per_s",
        "value": round(batch * gen_steps / dt, 1),
        "unit": "tokens/s",
        "step_ms": round(step_ms, 3),
        "batch": batch,
        "prompt_len": prompt_len,
        "gen_steps": gen_steps,
        "n_kv_heads": n_kv_heads or cfg.n_heads,
        "weights": "int8" if int8 else "bf16",
        "kv_cache": "int8" if kv_int8 else "bf16",
    }


def speculative_throughput(cfg, batch, prompt_len, gen_steps, gamma,
                           self_draft=False):
    """With random (untrained) weights a quarter-size draft almost never
    agrees with the target, so acceptance sits at the ~1 token/round floor —
    the honest LOWER bound (pure speculation overhead). *self_draft* uses the
    target as its own draft: greedy agreement is total, acceptance hits the
    gamma+1 ceiling — the UPPER bound. Trained pairs land in between."""
    import dataclasses

    from kubetpu.jobs import init_params
    from kubetpu.jobs.speculative import make_speculative_generate

    tcfg = dataclasses.replace(cfg, remat=False)
    # draft: a quarter-depth, quarter-width shrink of the target
    dcfg = tcfg if self_draft else dataclasses.replace(
        tcfg,
        d_model=max(64, cfg.d_model // 4),
        n_layers=max(1, cfg.n_layers // 4),
        n_heads=max(1, cfg.n_heads // 4),
        d_ff=max(128, cfg.d_ff // 4),
    )
    t_params = init_params(jax.random.PRNGKey(0), tcfg)
    d_params = t_params if self_draft else init_params(jax.random.PRNGKey(7), dcfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, prompt_len), 0,
                                tcfg.vocab, jnp.int32)
    from kubetpu.jobs.profiling import marginal_ms

    gen = make_speculative_generate(tcfg, dcfg, gamma)

    def make_run(n):
        return lambda: gen(t_params, d_params, prompt, n)[0][0, -1]

    n1 = max(8, gen_steps // 8)
    step_ms = marginal_ms(make_run, n1, n1 + gen_steps, reps=2)
    dt = gen_steps * step_ms / 1e3
    # acceptance stat from the n1 variant marginal_ms already compiled —
    # a full-length extra generation would cost one more tunnel compile
    _, accept = gen(t_params, d_params, prompt, n1)
    del t_params, d_params
    return {
        "metric": "speculative_decode_tokens_per_s",
        "value": round(batch * gen_steps / dt, 1),
        "unit": "tokens/s",
        "step_ms": round(step_ms, 3),
        "batch": batch,
        "gen_steps": gen_steps,
        "gamma": gamma,
        "draft": "self" if self_draft else "quarter",
        "mean_tokens_per_round": round(float(accept), 2),
    }


_TRAINED_PAIR_CACHE: dict = {}


def _train_spec_pair(small: bool):
    """A TRAINED draft/target pair: target trained on the skewed
    synthetic corpus, draft distilled against it (tests/test_distill.py's
    recipe at bench scale). Returns ``(tcfg, dcfg, t_params, d_params,
    data, agreement)`` — memoized per size so a full bench run training
    the pair for the spec section doesn't retrain it for the serving
    storm. Training cost is bounded (a few hundred small-model steps) and
    runs on-device."""
    import dataclasses

    from kubetpu.jobs import ModelConfig, init_state, make_mesh, make_train_step
    from kubetpu.jobs.data import SyntheticCorpus
    from kubetpu.jobs.distill import (
        agreement_rate,
        init_draft_state,
        make_distill_step,
    )

    if small in _TRAINED_PAIR_CACHE:
        return _TRAINED_PAIR_CACHE[small]
    if small:  # CPU smoke: same recipe, toy sizes
        tcfg = ModelConfig(vocab=64, d_model=64, n_layers=2, n_heads=4,
                           d_ff=128, max_seq=256)
        dcfg = dataclasses.replace(tcfg, d_model=32, n_layers=1, d_ff=64)
        t_steps, d_steps = 200, 300
    else:
        tcfg = ModelConfig(vocab=512, d_model=512, n_layers=8, n_heads=8,
                           d_ff=2048, max_seq=2048, dtype=jnp.bfloat16)
        dcfg = dataclasses.replace(tcfg, d_model=128, n_layers=2, d_ff=512)
        t_steps, d_steps = 300, 400
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1})
    corpus = SyntheticCorpus(tcfg.vocab, seed=3,
                             skew=[0.85, 0.05, 0.05, 0.05])
    # ONE generator, 8 distinct batches (a fresh corpus.batches(...) per
    # list element restarts the stream, so every "batch" was the same
    # first batch and the tokens/round headline was inflated by
    # single-batch memorization); the 9th draw is a HELD-OUT batch the
    # agreement metric is measured on
    batches = corpus.batches(8, 64, seed=5)
    data = [next(batches) for _ in range(8)]
    held_out = next(batches)
    state, opt = init_state(jax.random.PRNGKey(0), tcfg, mesh)
    step = make_train_step(tcfg, mesh, optimizer=opt, use_ring=False)
    for i in range(t_steps):
        tokens, targets = data[i % len(data)]
        state, _tl = step(state, tokens, targets)
    dstep, dopt = make_distill_step(tcfg, dcfg, temperature=2.0)
    dstate = init_draft_state(jax.random.PRNGKey(1), dcfg, dopt)
    for i in range(d_steps):
        tokens, targets = data[i % len(data)]
        dstate, _dl = dstep(dstate, state.params, tokens, targets)
    agree = agreement_rate(tcfg, dcfg, state.params, dstate.params,
                           held_out[0])
    # strip the training mesh's committed shardings: serving-side jits
    # would otherwise recompile every leg once more at serve time (the
    # warmed entries were keyed on differently-committed pool inputs)
    unshard = lambda p: jax.tree.map(  # noqa: E731 — local one-liner
        lambda x: jax.device_put(jax.device_get(x)), p)
    out = (tcfg, dcfg, unshard(state.params), unshard(dstate.params),
           data, agree)
    _TRAINED_PAIR_CACHE[small] = out
    return out


def speculative_trained_pair(prompt_len, gen_steps, gamma, small=False):
    """The number that decides whether speculation is a CAPABILITY: a
    TRAINED draft/target pair (``_train_spec_pair``) measured against
    PLAIN greedy decode of the SAME target. Reports tokens/s for both,
    the ratio, and the realized tokens/round — apples-to-apples because
    both paths decode the identical trained target."""
    from kubetpu.jobs.decode import make_generate
    from kubetpu.jobs.profiling import marginal_ms
    from kubetpu.jobs.speculative import make_speculative_generate

    tcfg, dcfg, t_params, d_params, data, agree = _train_spec_pair(small)

    batch = 4
    prompt = jnp.asarray(data[0][0][:batch, :prompt_len])
    spec = make_speculative_generate(tcfg, dcfg, gamma)
    plain = make_generate(tcfg)

    def spec_run(n):
        return lambda: spec(t_params, d_params, prompt, n)[0][0, -1]

    def plain_run(n):
        return lambda: plain(t_params, prompt, jax.random.PRNGKey(3), n)[0, -1]

    n1 = max(8, gen_steps // 8)
    spec_ms = marginal_ms(spec_run, n1, n1 + gen_steps, reps=2)
    plain_ms = marginal_ms(plain_run, n1, n1 + gen_steps, reps=2)
    _, tpr = spec(t_params, d_params, prompt, n1)
    spec_tps = batch * gen_steps / (gen_steps * spec_ms / 1e3)
    plain_tps = batch * gen_steps / (gen_steps * plain_ms / 1e3)
    return {
        "metric": "speculative_decode_tokens_per_s",
        "value": round(spec_tps, 1),
        "unit": "tokens/s",
        "step_ms": round(spec_ms, 3),
        "batch": batch,
        "gen_steps": gen_steps,
        "gamma": gamma,
        "draft": "trained",
        "mean_tokens_per_round": round(float(tpr), 2),
        "teacher_forced_agreement": round(agree, 3),
        "plain_decode_tokens_per_s": round(plain_tps, 1),
        "speedup_vs_plain": round(spec_tps / plain_tps, 2),
    }


def _result_key(r: dict) -> tuple:
    """Identity of a measurement variant — used to merge re-runs of a
    subset of sections (--only) into an existing artifact."""
    weights = r.get("weights")
    if weights is None and r.get("metric") == "decode_tokens_per_s":
        weights = "bf16"  # backfill: rows written before the int8 variant
    remat = r.get("remat")
    if remat is None and r.get("metric") == "train_tokens_per_s":
        remat = "full"  # backfill: rows written before the policy knob
    draft = r.get("draft")
    if draft is None and r.get("metric") == "speculative_decode_tokens_per_s":
        draft = "quarter"  # backfill: rows written before the self-draft leg
    return (r.get("metric"), r.get("seq"), r.get("n_kv_heads"), r.get("gamma"),
            weights, remat, draft, r.get("batch"), r.get("loss_chunk", 0),
            r.get("kv_cache", "bf16"), r.get("block_q", 128),
            r.get("block_k", 128), r.get("variant"),
            # prefix_reuse_storm rows: one line per reuse arm, re-runs
            # with the same arm replace cleanly across rounds; ditto
            # router_storm's routing-policy arms and pagedtune's
            # (pool dtype, pages_per_block) sweep points
            r.get("reuse"), r.get("policy"),
            r.get("pool"), r.get("pages_per_block"))


def _merge_out(path: str, new: list) -> None:
    """Replace same-variant lines in *path*, keep the rest, append new."""
    old = []
    try:
        with open(path) as f:
            old = [json.loads(line) for line in f if line.strip()]
    except FileNotFoundError:
        pass
    new_keys = {_result_key(r) for r in new}
    merged = [r for r in old if _result_key(r) not in new_keys] + new
    with open(path, "w") as f:
        for r in merged:
            f.write(json.dumps(r) + "\n")


def serving_throughput(cfg, n_slots, prompt_len, rounds):
    """Continuous batching under churn: steady decode with an enqueue every
    few steps; reports decode step p50 and ADMISSION STALL p50/p99 (the
    wall cost a step pays to take a request — VERDICT r2 weak #3)."""
    import dataclasses

    from kubetpu.jobs import init_params
    from kubetpu.jobs.serving import DecodeServer

    dcfg = dataclasses.replace(cfg, remat=False)
    params = init_params(jax.random.PRNGKey(0), dcfg)
    server = DecodeServer(dcfg, params, n_slots=n_slots,
                          max_seq=min(cfg.max_seq, 1024),
                          max_new_tokens=32)
    server.warmup()
    rng = __import__("random").Random(0)
    emitted = 0
    for r in range(rounds):
        if r % 4 == 0:  # steady request arrival while decoding
            server.enqueue([rng.randrange(1, dcfg.vocab) for _ in range(prompt_len)])
        emitted += sum(len(v) for v in server.step().values())
    server.drain()
    stats = server.metrics_summary()
    return {
        "metric": "serving_admission_stall",
        "unit": "ms",
        "value": round(stats["admission_stall"]["p50_ms"], 3),
        "p99_ms": round(stats["admission_stall"]["p99_ms"], 3),
        "admissions": stats["admission_stall"]["count"],
        "decode_step_p50_ms": round(stats["step"]["p50_ms"], 3),
        "n_slots": n_slots,
        "tokens_emitted": emitted,
    }


def mixed_load_serving(cfg, n_slots, long_len, prefill_budget, smoke):
    """Head-of-line blocking under a LONG admission: n_slots-1 short
    requests decode steadily, then a long prompt arrives mid-decode.
    Reports decode throughput DURING the admission window (enqueue ->
    the long request's first token), the long request's TTFT, and the
    p50/p99 inter-token latency of the decode streams — for the
    monolithic baseline (whole-prompt prefill freezes every stream) and
    the chunked server (prefill_budget tokens/step + the double-buffered
    host loop). Host wall timing: inter-token latency and TTFT are
    host-observable quantities by definition, so the marginal method
    does not apply here."""
    import dataclasses
    import time as _time

    from kubetpu.jobs import init_params
    from kubetpu.jobs.serving import DecodeServer

    dcfg = dataclasses.replace(cfg, remat=False)
    params = init_params(jax.random.PRNGKey(0), dcfg)
    max_new = 24 if smoke else 64
    max_seq = long_len + max_new + 2
    rng = __import__("random").Random(0)
    shorts = [[rng.randrange(1, dcfg.vocab) for _ in range(8)]
              for _ in range(n_slots - 1)]
    long_prompt = [rng.randrange(1, dcfg.vocab) for _ in range(long_len)]

    def run(budget, overlap):
        server = DecodeServer(dcfg, params, n_slots=n_slots, max_seq=max_seq,
                              max_new_tokens=max_new,
                              prefill_budget=budget, overlap=overlap)
        server.warmup()
        rids = [server.submit(p) for p in shorts]
        arrivals = {r: [] for r in rids}

        def step_once():
            out = server.step()
            now = _time.perf_counter()
            first = None
            for rid, toks in out.items():
                if rid in arrivals:
                    arrivals[rid].extend([now] * len(toks))
                elif toks:
                    first = now          # the long request's first token
            return first

        for _ in range(6):               # steady decode before the arrival
            step_once()
        t_enq = _time.perf_counter()
        server.enqueue(long_prompt)
        t_first = None
        for _ in range(long_len // max(budget, 1) + max_new + 8):
            t_first = step_once()
            if t_first is not None:
                break
        window = (t_first or _time.perf_counter()) - t_enq
        t_hi = t_first or float("inf")
        decode_tokens = sum(sum(1 for t in ts if t_enq <= t <= t_hi)
                            for ts in arrivals.values())
        itls = sorted(b - a for ts in arrivals.values()
                      for a, b in zip(ts, ts[1:]))

        def pct(p):
            if not itls:
                return 0.0
            return itls[min(len(itls) - 1, int(round(p / 100 * (len(itls) - 1))))]

        return {
            "metric": "serving_mixed_load",
            "variant": "chunked" if budget else "monolithic",
            "value": round(decode_tokens / window, 1) if window > 0 else None,
            "unit": "decode tokens/s during prefill",
            "ttft_ms": round(window * 1e3, 2) if t_first else None,
            "itl_p50_ms": round(pct(50) * 1e3, 3),
            "itl_p99_ms": round(pct(99) * 1e3, 3),
            "long_prompt": long_len,
            "prefill_budget": budget,
            "overlap": overlap,
            "n_slots": n_slots,
            "decode_tokens_in_window": decode_tokens,
            # the server's OWN recorded histograms (Round-8 obs): the same
            # quantities, measured by the instrumentation under test
            "server_metrics": {
                k: v for k, v in server.metrics_summary().items()
                if k in ("ttft", "itl", "queue_wait", "admission_stall")
            },
        }

    return run(0, False), run(prefill_budget, True)


def mixed_load_storm(cfg, params=None, n_slots=4, long_len=56, short_len=8,
                     n_shorts=3, prefill_budget=24, max_new=4, rounds=3,
                     max_seq=64, seed=0):
    """Long-prompt admission STORM, measured by the SERVER's Round-8
    histograms: each round enqueues one long prompt with *n_shorts* short
    prompts right behind it, then drains. Monolithic admission prefills
    the whole backlog inside one step — every short's first token waits
    behind the long's full prefill; the chunked scheduler spends
    ``prefill_budget`` tokens/step, so shorts finish with leftover budget
    while the long trickles. Returns (monolithic, chunked) dicts carrying
    ``metrics_summary()``'s ttft/itl/queue_wait — chunked TTFT p50
    strictly below monolithic is the ordering the obs test pins."""
    import dataclasses
    import random as _random

    from kubetpu.jobs import init_params
    from kubetpu.jobs.serving import DecodeServer

    dcfg = dataclasses.replace(cfg, remat=False)
    if params is None:
        params = init_params(jax.random.PRNGKey(0), dcfg)
    rng = _random.Random(seed)
    longs = [[rng.randrange(1, dcfg.vocab) for _ in range(long_len)]
             for _ in range(rounds)]
    shorts = [[rng.randrange(1, dcfg.vocab) for _ in range(short_len)]
              for _ in range(rounds * n_shorts)]

    def run(budget):
        from kubetpu.obs.slo import serving_slos

        server = DecodeServer(dcfg, params, n_slots=n_slots, max_seq=max_seq,
                              max_new_tokens=max_new, prefill_budget=budget)
        if budget:
            # Round-11 signal layer rides the chunked arm: sampled
            # profiler (enabled pre-warmup so the compile storm is
            # attributed per leg) + a declared TTFT/ITL SLO judged from
            # the server's own histograms
            server.enable_profiler(sample_every=4)
            server.declare_slos(serving_slos(
                ttft_p95_s=0.25, itl_p99_s=0.05), eval_interval=0.05)
        server.warmup()
        for r in range(rounds):
            server.enqueue(longs[r])
            for s in range(n_shorts):
                server.enqueue(shorts[r * n_shorts + s])
            server.drain()
        stats = server.metrics_summary()
        row = {
            "metric": "serving_storm",
            "variant": "chunked" if budget else "monolithic",
            "value": round(stats["ttft"]["p50_ms"], 3),
            "unit": "server-recorded ttft p50 ms",
            "ttft": stats["ttft"],
            "itl": stats.get("itl"),
            "queue_wait": stats.get("queue_wait"),
            "prefill_budget": budget,
            "n_slots": n_slots,
            "requests": rounds * (1 + n_shorts),
        }
        if budget:
            prof = server.profile_summary()
            row["profile"] = {
                "coverage": prof["coverage"],
                "sampled_steps": prof["sampled_steps"],
                "phases": {k: v["frac"] for k, v in prof["phases"].items()},
                "recompiles": {k: v["recompiles"]
                               for k, v in prof["recompiles"].items()},
            }
            row["slo"] = {
                name: {"ok": res["ok"],
                       "burn_fast": round(res["burn_fast"], 2)}
                for name, res in server.slo.results().items()
            }
            row["events"] = server.events.counts()
        return row

    return run(0), run(prefill_budget)


def prefix_reuse_storm(cfg, n_slots=4, sys_len=192, tail_len=8,
                       n_requests=12, max_new=8, page_size=16,
                       prefill_budget=64, cache_pages=64):
    """Shared-system-prompt STORM through the paged server: every request
    carries the same *sys_len*-token preamble plus a unique tail — the
    fleet workload prefix reuse exists for. One cold request populates
    the radix tree, then the storm arrives; with reuse each admission
    maps the cached prefix pages and prefills only the tail, so TTFT and
    prefill tokens computed collapse. Reports the server's OWN Round-8
    ttft histogram (p50/p99), prefill tokens computed, tokens saved and
    hit rate — reuse off (prefix_cache_pages=0) vs on. Host wall timing:
    TTFT is a host-observable quantity by definition."""
    import dataclasses
    import random as _random

    from kubetpu.jobs import init_params
    from kubetpu.jobs.paged import PagedDecodeServer

    dcfg = dataclasses.replace(cfg, remat=False)
    params = init_params(jax.random.PRNGKey(0), dcfg)
    rng = _random.Random(0)
    sys_prompt = [rng.randrange(1, dcfg.vocab) for _ in range(sys_len)]
    tails = [[rng.randrange(1, dcfg.vocab) for _ in range(tail_len)]
             for _ in range(n_requests)]
    # page-aligned max_seq: the paged warmup's bucket grid assumes it
    max_seq = -(-(sys_len + tail_len + max_new + 2) // page_size) * page_size
    total_prompt_tokens = n_requests * (sys_len + tail_len)
    # pool sized so neither arm ever parks on pages (the tree's budget
    # rides ON TOP of the slots' worst case): the comparison isolates
    # prefill work, not pool-pressure scheduling
    n_pages = (n_slots * ((max_seq + page_size - 1) // page_size)
               + cache_pages)

    def run(reuse_pages):
        server = PagedDecodeServer(
            dcfg, params, n_slots=n_slots, max_seq=max_seq,
            max_new_tokens=max_new, page_size=page_size,
            n_pages=n_pages, prefill_budget=prefill_budget,
            prefix_cache_pages=reuse_pages,
        )
        server.warmup()
        # cold seeding request: populates the tree (a no-op when reuse is
        # off) so the storm below measures steady-state hit behavior
        rid = server.enqueue(sys_prompt + tails[0])
        server.drain()
        server.pop_result(rid)
        for tail in tails[1:]:
            server.enqueue(sys_prompt + tail)
        server.drain()
        if reuse_pages:
            server.check_invariants()   # the pool oracle rides the bench
        stats = server.metrics_summary()
        reuse = server.prefix_cache_stats()
        saved = reuse.get("prefill_tokens_saved", 0)
        return {
            "metric": "prefix_reuse_storm",
            "reuse": "on" if reuse_pages else "off",
            "value": round(stats["ttft"]["p50_ms"], 3),
            "unit": "server-recorded ttft p50 ms",
            "ttft_p99_ms": round(stats["ttft"]["p99_ms"], 3),
            "prefill_tokens_total": total_prompt_tokens,
            "prefill_tokens_computed": total_prompt_tokens - saved,
            "prefill_tokens_saved": saved,
            "hit_rate": round(reuse.get("hit_rate", 0.0), 3),
            "prefix_cache_pages": reuse_pages,
            "sys_len": sys_len,
            "tail_len": tail_len,
            "n_requests": n_requests,
            "n_slots": n_slots,
            "prefill_budget": prefill_budget,
        }

    return run(0), run(cache_pages)


def tiering_storm(cfg, n_families=4, sys_len=96, tail_len=8, rounds=3,
                  max_new=6, page_size=16, prefill_budget=32, n_slots=2,
                  host_budget=64 << 20,
                  arms=("no_tier", "host", "host_peer")):
    """Round-19 headline: a shared-prefix WORKING SET four times the HBM
    prefix-tree budget — *n_families* system prompts round-robined for
    *rounds*, with an HBM tree sized for ONE family. Without the tier
    every arrival finds its family evicted and cold-prefills; with the
    host tier LRU victims spill to host DRAM and fill back on return,
    so steady-state arrivals prefill only their tail; the ``host_peer``
    arm starts a COLD replica next to a warm one and pulls each
    family's first span over ``/prefix_fetch`` (router-hinted peer
    tier) before falling into the same host/HBM rhythm. Reports the
    server's OWN ttft histogram (the peer arm's pre-admission fetch
    rides outside it — its row carries the fetch ledger instead), hit
    rate, and per-tier spill/fill/savings counts. Requests drive
    serially so TTFT isolates prefill work, not slot scheduling."""
    import dataclasses
    import random as _random

    from kubetpu.jobs import init_params
    from kubetpu.jobs.paged import PagedDecodeServer
    from kubetpu.router import ReplicaServer
    from kubetpu.wire.httpcommon import request_json

    dcfg = dataclasses.replace(cfg, remat=False)
    params = init_params(jax.random.PRNGKey(0), dcfg)
    rng = _random.Random(0)
    families = [[rng.randrange(1, dcfg.vocab) for _ in range(sys_len)]
                for _ in range(n_families)]
    prompts = []
    for _ in range(rounds):
        for fam in families:
            prompts.append(fam + [rng.randrange(1, dcfg.vocab)
                                  for _ in range(tail_len)])
    cache_pages = sys_len // page_size      # ONE family fits; the set doesn't
    max_seq = -(-(sys_len + tail_len + max_new + 2)
                // page_size) * page_size
    n_pages = (n_slots * ((max_seq + page_size - 1) // page_size)
               + cache_pages)

    def make_server(host_bytes):
        return PagedDecodeServer(
            dcfg, params, n_slots=n_slots, max_seq=max_seq,
            max_new_tokens=max_new, page_size=page_size, n_pages=n_pages,
            prefill_budget=prefill_budget, prefix_cache_pages=cache_pages,
            host_tier_bytes=host_bytes)

    def row(arm, server, extra=None):
        stats = server.metrics_summary()
        reuse = server.prefix_cache_stats()
        tier = server.tier_stats()
        server.check_invariants()   # the pool oracle rides the bench
        out = {
            "metric": "tiering_storm",
            "arm": arm,
            "value": round(stats["ttft"]["p50_ms"], 3),
            "unit": "server-recorded ttft p50 ms",
            "ttft_p99_ms": round(stats["ttft"]["p99_ms"], 3),
            "hit_rate": round(reuse.get("hit_rate", 0.0), 3),
            "prefill_tokens_saved": reuse.get("prefill_tokens_saved", 0),
            "working_set_pages": n_families * cache_pages,
            "cache_pages": cache_pages,
            "n_families": n_families,
            "rounds": rounds,
            "requests": len(prompts),
        }
        if tier.get("enabled"):
            out["tier_spills"] = tier["spills"]
            out["tier_fills"] = tier["fills"]
            out["tier_tokens_saved"] = tier["tokens_saved"]
        if extra:
            out.update(extra)
        return out

    out_rows = []
    for arm in arms:
        if arm in ("no_tier", "host"):
            server = make_server(0 if arm == "no_tier" else host_budget)
            server.warmup()
            for p in prompts:
                rid = server.enqueue(p)
                server.drain()
                server.pop_result(rid)
            out_rows.append(row(arm, server))
            continue
        # host_peer: a cold replica next to a warm one; every family's
        # FIRST arrival pulls its span over the wire instead of cold-
        # prefilling, later arrivals ride the local host/HBM tiers
        warm_srv = make_server(host_budget)
        cold_srv = make_server(host_budget)
        warm_srv.warmup()
        cold_srv.warmup()
        ra = ReplicaServer(warm_srv, "tier-warm", idle_wait=0.002)
        rb = ReplicaServer(cold_srv, "tier-cold", idle_wait=0.002)
        ua = ra.start()
        rb.start()
        try:
            for i, fam in enumerate(families):
                request_json(ua + "/generate",
                             {"prompt": fam + [1], "timeout": 120.0},
                             idempotency_key=f"tiering-warm-{i}",
                             timeout=120.0)
            for i, p in enumerate(prompts):
                request_json(rb.address + "/generate",
                             {"prompt": p, "prefix_peer": ua,
                              "timeout": 120.0},
                             idempotency_key=f"tiering-peer-{i}",
                             timeout=120.0)
            fetches = {
                result: int(cold_srv.obs.counter(
                    "kubetpu_peer_prefix_fetch_total",
                    result=result).value)
                for result in ("hit", "miss", "degraded")}
            out_rows.append(row(arm, cold_srv,
                                extra={"peer_fetches": fetches}))
        finally:
            ra.shutdown(graceful=False)
            rb.shutdown(graceful=False)
    return tuple(out_rows)


def _pooled_latency_ms(servers, op, pct):
    """Percentile over EVERY server's raw latency reservoir for *op*
    (exact below cap) — the fleet-wide number the router and migration
    storms both report."""
    import numpy as np

    vals = []
    for srv in servers:
        for name, labels, kind, inst in srv.obs.snapshot():
            if (name == "kubetpu_serving_latency_seconds"
                    and kind == "summary"
                    and dict(labels).get("op") == op):
                vals.extend(inst.tail()[1])
    if not vals:
        return 0.0
    return float(np.percentile(np.asarray(vals), pct)) * 1e3


def router_storm(cfg, n_replicas=2, n_families=3, sys_len=96, tail_len=8,
                 requests_per_family=4, max_new=6, page_size=16,
                 prefill_budget=32, cache_pages=32, concurrency=4,
                 n_slots=2, policies=("random", "affinity")):
    """N-replica storm through the Round-14 data plane: *n_families*
    shared-prefix prompt families interleaved through a router in front
    of *n_replicas* paged replicas (prefix cache on), AFFINITY routing
    vs the seeded RANDOM baseline. Affinity consistent-hashes each
    family's prefix head onto one replica, so every family member after
    the first hits a warm radix tree; random routing gives each replica
    per-replica luck. Reports the CLUSTER-wide prefix hit rate plus
    TTFT p50 / ITL p99 pooled over every replica's raw reservoir (exact
    below cap) — the numbers the bench gate rides. *policies* selects
    the arms (the gate runs only "affinity"; the comparison row runs
    both)."""
    import dataclasses
    import random as _random
    import time
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    from kubetpu.jobs import init_params
    from kubetpu.jobs.paged import PagedDecodeServer
    from kubetpu.router import ReplicaServer, RouterServer
    from kubetpu.wire.httpcommon import request_json

    dcfg = dataclasses.replace(cfg, remat=False)
    params = init_params(jax.random.PRNGKey(0), dcfg)
    rng = _random.Random(0)
    families = [[rng.randrange(1, dcfg.vocab) for _ in range(sys_len)]
                for _ in range(n_families)]
    # interleave families so the random baseline's first-landing luck is
    # realistic (a family-sorted order would gift it warm trees)
    prompts = []
    for _ in range(requests_per_family):
        for fam in families:
            prompts.append(fam + [rng.randrange(1, dcfg.vocab)
                                  for _ in range(tail_len)])
    max_seq = -(-(sys_len + tail_len + max_new + 2)
                // page_size) * page_size
    n_pages = (n_slots * ((max_seq + page_size - 1) // page_size)
               + cache_pages)

    def make_server():
        return PagedDecodeServer(
            dcfg, params, n_slots=n_slots, max_seq=max_seq,
            max_new_tokens=max_new, page_size=page_size, n_pages=n_pages,
            prefill_budget=prefill_budget,
            prefix_cache_pages=cache_pages)

    # pre-compile the storm's leg shapes once (shared _LEG_CACHE), so
    # neither arm's TTFT carries the other's compile bill
    pre = make_server()
    for p in (prompts[0], prompts[-1]):
        rid = pre.enqueue(p)
        pre.drain()
        pre.pop_result(rid)

    def run(policy):
        servers = [make_server() for _ in range(n_replicas)]
        replicas = [ReplicaServer(srv, f"bench{i}", idle_wait=0.002)
                    for i, srv in enumerate(servers)]
        router = RouterServer(policy=policy, load_refresh_s=0.1)
        try:
            router.start()
            for rep in replicas:
                rep.start()
                router.register_replica(rep.address)

            def one(item):
                i, prompt = item
                return request_json(
                    router.address + "/generate",
                    {"prompt": prompt, "timeout": 120.0},
                    idempotency_key=f"router-storm-{policy}-{i}",
                    timeout=120.0)

            t0 = time.perf_counter()
            with ThreadPoolExecutor(max_workers=concurrency) as ex:
                bodies = list(ex.map(one, enumerate(prompts)))
            wall = time.perf_counter() - t0
            emitted = sum(len(b["emitted"]) for b in bodies)
            reuse = [srv.prefix_cache_stats() for srv in servers]
            hits = sum(r["requests_hit"] for r in reuse)
            total = hits + sum(r["requests_miss"] for r in reuse)
            for srv in servers:
                srv.check_invariants()   # the pool oracle rides the bench
            return {
                "metric": "router_storm",
                "policy": policy,
                "value": round(hits / total, 3) if total else 0.0,
                "unit": "cluster-wide prefix hit rate",
                "ttft_p50_ms": round(
                    _pooled_latency_ms(servers, "ttft", 50), 3),
                "itl_p99_ms": round(
                    _pooled_latency_ms(servers, "itl", 99), 3),
                "decode_tok_s": round(emitted / wall, 1) if wall else 0.0,
                "prefill_tokens_saved": sum(
                    r["prefill_tokens_saved"] for r in reuse),
                "fallbacks": int(router._c_fallback.value),
                "requests": len(prompts),
                "n_replicas": n_replicas,
                "n_families": n_families,
                "concurrency": concurrency,
            }
        finally:
            router.shutdown()
            for rep in replicas:
                rep.shutdown(graceful=False)

    return tuple(run(p) for p in policies)


def migration_storm(cfg, n_replicas=2, n_streams=4, prompt_len=24,
                    max_new=48, page_size=16, n_slots=4,
                    arms=("wait", "migrate")):
    """Round-16 headline: drain a loaded replica with LIVE MIGRATION vs
    waiting out natural stream end. Boots a router + *n_replicas* paged
    replicas, launches *n_streams* long decode streams through keyed
    router POSTs, then drains the most-loaded replica — the ``wait``
    arm drains the classic way (scale-down blocked until every stream
    finishes), the ``migrate`` arm hands the streams to a survivor
    token-exactly and completes as fast as the wire. Reports
    drain-complete latency per arm (the ``migration_drain_s`` gate
    metric), streams preserved (parity vs a quiet unmigrated run), the
    pooled ITL p99 (the handoff blip shows here), and committed
    handoffs."""
    import dataclasses
    import random as _random
    import time
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    from kubetpu.jobs import init_params
    from kubetpu.jobs.paged import PagedDecodeServer
    from kubetpu.router import ReplicaServer, RouterServer
    from kubetpu.wire.httpcommon import request_json

    dcfg = dataclasses.replace(cfg, remat=False)
    params = init_params(jax.random.PRNGKey(0), dcfg)
    rng = _random.Random(0)
    prompts = [[rng.randrange(1, dcfg.vocab) for _ in range(prompt_len)]
               for _ in range(n_streams)]
    max_seq = -(-(prompt_len + max_new + 2) // page_size) * page_size

    def make_server():
        return PagedDecodeServer(
            dcfg, params, n_slots=n_slots, max_seq=max_seq,
            max_new_tokens=max_new, page_size=page_size)

    # quiet oracle + leg pre-compile in one pass (shared _LEG_CACHE)
    quiet = make_server()
    expected = []
    for p in prompts:
        rid = quiet.enqueue(p)
        quiet.drain()
        expected.append(quiet.pop_result(rid))

    def run(arm):
        servers = [make_server() for _ in range(n_replicas)]
        replicas = [ReplicaServer(srv, f"mig{i}", idle_wait=0.002)
                    for i, srv in enumerate(servers)]
        router = RouterServer(load_refresh_s=0.05)
        try:
            router.start()
            for rep in replicas:
                rep.start()
                router.register_replica(rep.address)

            def one(item):
                i, prompt = item
                return request_json(
                    router.address + "/generate",
                    {"prompt": prompt, "timeout": 120.0},
                    idempotency_key=f"mig-storm-{arm}-{i}",
                    timeout=120.0)

            ex = ThreadPoolExecutor(max_workers=n_streams)
            futs = [ex.submit(one, (i, p)) for i, p in enumerate(prompts)]
            # pick the drain victim once it actually holds streams
            victim = None
            deadline = time.monotonic() + 20.0
            while victim is None and time.monotonic() < deadline:
                loads = []
                for rep in replicas:
                    with rep._cv:
                        loads.append(len(rep.server.migratable_rids()))
                if max(loads) > 0:
                    victim = replicas[loads.index(max(loads))]
                else:
                    time.sleep(0.002)
            if victim is None:      # streams finished before the drain
                victim = replicas[0]
            survivor = next(r for r in replicas if r is not victim)
            t0 = time.perf_counter()
            router.pool.drain(
                victim.name,
                migrate_to=(survivor.address if arm == "migrate"
                            else None),
                reason=arm)
            while not router.pool.drained(victim.name):
                router.pool.refresh(0.0)
                time.sleep(0.005)
            drain_s = time.perf_counter() - t0
            bodies = [f.result() for f in futs]
            ex.shutdown()
            preserved = sum(1 for b, want in zip(bodies, expected)
                            if b.get("tokens") == want)
            migrations = sum(
                len(srv.events.events(kind="migrate_in"))
                for srv in servers)
            for srv in servers:
                srv.check_invariants()   # the pool oracle rides the bench
            return {
                "metric": "migration_storm",
                "arm": arm,
                "value": round(drain_s, 4),
                "unit": "drain-complete seconds",
                "itl_p99_ms": round(
                    _pooled_latency_ms(servers, "itl", 99), 3),
                "streams_preserved": preserved,
                "requests": n_streams,
                "migrations": migrations,
                "n_replicas": n_replicas,
                "max_new": max_new,
            }
        finally:
            router.shutdown()
            for rep in replicas:
                rep.shutdown(graceful=False)

    return tuple(run(a) for a in arms)


def crash_storm(cfg, n_replicas=2, n_streams=3, prompt_len=16,
                max_new=48, page_size=16, n_slots=2):
    """Round-20 headline: SIGKILL a loaded replica mid-storm and measure
    TIME-TO-RECOVER. Boots a router + *n_replicas* paged replicas,
    launches *n_streams* long keyed decode streams, hard-kills the
    most-loaded replica (no drain, no goodbye — its KV cache and slot
    table vanish), then boots a SAME-NAME replacement at a new URL: the
    fresh boot nonce makes the pool take the handle over and walk it
    through probation. Reports ``crash_recovery_s`` — kill to the
    replacement ROUTABLE again — plus streams preserved (every keyed
    request must finish token-exact against a quiet run: in-flight work
    on the victim re-drives on the survivor under the same idempotency
    keys) and whether the victim actually held streams when it died
    (an unloaded kill is a vacuous draw the gate retries)."""
    import dataclasses
    import random as _random
    import time
    from concurrent.futures import ThreadPoolExecutor

    from kubetpu.jobs import init_params
    from kubetpu.jobs.paged import PagedDecodeServer
    from kubetpu.router import ReplicaServer, RouterServer
    from kubetpu.wire.httpcommon import request_json

    dcfg = dataclasses.replace(cfg, remat=False)
    params = init_params(jax.random.PRNGKey(0), dcfg)
    rng = _random.Random(0)
    prompts = [[rng.randrange(1, dcfg.vocab) for _ in range(prompt_len)]
               for _ in range(n_streams)]
    max_seq = -(-(prompt_len + max_new + 2) // page_size) * page_size

    def make_server():
        return PagedDecodeServer(
            dcfg, params, n_slots=n_slots, max_seq=max_seq,
            max_new_tokens=max_new, page_size=page_size)

    quiet = make_server()
    expected = []
    for p in prompts:
        rid = quiet.enqueue(p)
        quiet.drain()
        expected.append(quiet.pop_result(rid))

    replicas = [ReplicaServer(make_server(), f"crash{i}", idle_wait=0.002)
                for i in range(n_replicas)]
    router = RouterServer(load_refresh_s=0.05)
    replacement = None
    try:
        router.start()
        for rep in replicas:
            rep.start()
            router.register_replica(rep.address)

        def one(item):
            i, prompt = item
            return request_json(
                router.address + "/generate",
                {"prompt": prompt, "timeout": 120.0},
                idempotency_key=f"crash-storm-{i}", timeout=120.0)

        ex = ThreadPoolExecutor(max_workers=n_streams)
        futs = [ex.submit(one, (i, p)) for i, p in enumerate(prompts)]
        victim = None
        deadline = time.monotonic() + 20.0
        while victim is None and time.monotonic() < deadline:
            loads = []
            for rep in replicas:
                with rep._cv:
                    loads.append(len(rep.server.migratable_rids()))
            if max(loads) > 0:
                victim = replicas[loads.index(max(loads))]
            else:
                time.sleep(0.002)
        loaded = victim is not None
        if victim is None:          # streams finished before the kill
            victim = replicas[0]
        victim.shutdown(graceful=False)
        t0 = time.perf_counter()
        replacement = ReplicaServer(make_server(), victim.name,
                                    idle_wait=0.002)
        replacement.start()
        router.register_replica(replacement.address)
        while victim.name not in router.pool.routable():
            router.pool.refresh(0.0)
            time.sleep(0.002)
        recovery_s = time.perf_counter() - t0
        bodies = [f.result() for f in futs]
        ex.shutdown()
        preserved = sum(1 for b, want in zip(bodies, expected)
                        if b.get("tokens") == want)
        takeovers = len(router.events.events(kind="replica_takeover"))
        for rep in replicas:
            if rep is not victim:
                rep.server.check_invariants()
        replacement.server.check_invariants()
        return ({
            "metric": "crash_storm",
            "arm": "crash_replace",
            "value": round(recovery_s, 4),
            "unit": "kill-to-routable seconds",
            "streams_preserved": preserved,
            "requests": n_streams,
            "takeovers": takeovers,
            "loaded": loaded,
            "n_replicas": n_replicas,
            "max_new": max_new,
        },)
    finally:
        router.shutdown()
        for rep in replicas:
            rep.shutdown(graceful=False)
        if replacement is not None:
            replacement.shutdown(graceful=False)


def disagg_storm(cfg, n_long=2, long_len=96, n_short=6, short_len=8,
                 max_new=24, page_size=16, prefill_budget=16, n_slots=8,
                 n_prefill=1, n_decode=2, disagg_prefill_budget=None,
                 arms=("colocated", "disagg")):
    """Round-17 headline: DISAGGREGATED prefill/decode vs colocated
    serving over the mixed long-prompt/short-decode storm. Both arms
    run the SAME replica count (``n_prefill + n_decode``) behind the
    router under identical concurrent traffic (long prompts that chew
    prefill + short prompts that decode long); the ``colocated`` arm
    is all-``both`` replicas — every decode stream shares steps with
    its neighbors' prefill chunks — the ``disagg`` arm splits them
    into ``n_prefill`` PREFILL + ``n_decode`` DECODE workers: prompts
    admit and chunk-prefill on the prefill pool (streaming completed
    KV spans over the wire while later chunks compute) and every token
    is emitted by the decode pool, whose steps never carry anyone's
    prompt. Reports pooled ITL p99 (the number disaggregation exists
    to protect — the ``disagg_itl_p99_ms`` gate metric), decode tok/s
    (``disagg_decode_toks_s``), source-side TTFT p50 (recorded when
    the first token materializes at the prefill replica — the handoff
    hop shows in the router's route latency, not here), token parity
    vs a quiet serial run, and the
    pipelining stats (committed handoffs, pages streamed mid-prefill,
    overlap fraction)."""
    import dataclasses
    import random as _random
    import time
    from concurrent.futures import ThreadPoolExecutor

    from kubetpu.jobs import init_params
    from kubetpu.jobs.paged import PagedDecodeServer
    from kubetpu.router import ReplicaServer, RouterServer
    from kubetpu.wire.httpcommon import request_json

    dcfg = dataclasses.replace(cfg, remat=False)
    params = init_params(jax.random.PRNGKey(0), dcfg)
    rng = _random.Random(0)
    prompts = [[rng.randrange(1, dcfg.vocab) for _ in range(long_len)]
               for _ in range(n_long)]
    prompts += [[rng.randrange(1, dcfg.vocab) for _ in range(short_len)]
                for _ in range(n_short)]
    max_seq = -(-(long_len + max_new + 2) // page_size) * page_size
    n_pages = n_slots * ((max_seq + page_size - 1) // page_size) + 8

    def make_server(budget=None):
        return PagedDecodeServer(
            dcfg, params, n_slots=n_slots, max_seq=max_seq,
            max_new_tokens=max_new, page_size=page_size,
            n_pages=n_pages,
            prefill_budget=(prefill_budget if budget is None else budget))

    # quiet oracle + leg pre-compile in one pass (shared _LEG_CACHE)
    quiet = make_server()
    expected = []
    for p in prompts:
        rid = quiet.enqueue(p)
        quiet.drain()
        expected.append(quiet.pop_result(rid))

    def run(arm):
        # SAME replica count per arm — the comparison is topology, not
        # hardware: colocated = every node serves both phases, disagg =
        # a prefill pool streaming KV to a decode pool
        roles = (("both",) * (n_prefill + n_decode)
                 if arm == "colocated"
                 else ("prefill",) * n_prefill + ("decode",) * n_decode)
        # the asymmetric-budget dividend of disaggregation: a dedicated
        # prefill node has no decode neighbors to protect, so it runs a
        # much larger chunk budget (faster admission) while colocated
        # nodes must keep chunks small exactly because decode shares
        # their steps — each arm gets its honest configuration
        pre_budget = (disagg_prefill_budget
                      if disagg_prefill_budget is not None
                      else 4 * prefill_budget)

        def build_fleet(tag):
            servers = [make_server(budget=(pre_budget
                                           if role == "prefill"
                                           else None))
                       for role in roles]
            for srv in servers:
                # full leg warmup per budget signature: the disagg
                # arm's bigger prefill budget produces chunk/gather
                # shapes the shared pre-compile server never traced,
                # and a mid-storm 1s XLA compile is not a serving
                # number (the jit caches are process-global, so
                # repeated fleets pay nothing)
                srv.warmup()
            replicas = [ReplicaServer(srv, f"dsg-{tag}-{role}{i}",
                                      role=role, idle_wait=0.002)
                        for i, (srv, role)
                        in enumerate(zip(servers, roles))]
            router = RouterServer(load_refresh_s=0.05)
            router.start()
            for rep in replicas:
                rep.start()
                router.register_replica(rep.address)
            return servers, replicas, router

        # THROWAWAY warm fleet: one long + one short request drive the
        # wire + RESTORE legs once, landing the first-shape XLA
        # compiles (~100-250ms each) in the process-global caches —
        # then it is torn down, so the TIMED fleet below starts with
        # CLEAN counters and latency reservoirs (warmup samples and
        # warmup handoffs must never pollute the reported row)
        _ws, wreplicas, wrouter = build_fleet("warm")
        try:
            wrng = _random.Random(991)
            for j, n in enumerate((long_len, short_len)):
                request_json(
                    wrouter.address + "/generate",
                    {"prompt": [wrng.randrange(1, dcfg.vocab)
                                for _ in range(n)],
                     "timeout": 120.0},
                    idempotency_key=f"disagg-warm-{arm}-{j}",
                    timeout=120.0)
        finally:
            wrouter.shutdown()
            for rep in wreplicas:
                rep.shutdown(graceful=False)

        servers, replicas, router = build_fleet("run")
        try:
            def one(item):
                i, prompt = item
                return request_json(
                    router.address + "/generate",
                    {"prompt": prompt, "timeout": 120.0},
                    idempotency_key=f"disagg-storm-{arm}-{i}",
                    timeout=120.0)

            t0 = time.perf_counter()
            with ThreadPoolExecutor(max_workers=len(prompts)) as ex:
                bodies = list(ex.map(one, enumerate(prompts)))
            wall = time.perf_counter() - t0
            emitted = sum(len(b["emitted"]) for b in bodies)
            preserved = sum(1 for b, want in zip(bodies, expected)
                            if b.get("tokens") == want)
            for srv in servers:
                srv.check_invariants()   # the pool oracle rides the bench
            committed = sum(int(rep.server.obs.counter(
                "kubetpu_handoffs_total", result="committed").value)
                for rep in replicas)
            streamed = sum(int(rep.server.obs.counter(
                "kubetpu_handoff_pages_streamed_total").value)
                for rep in replicas)
            early = sum(rep._handoff_early_bytes for rep in replicas)
            total = sum(rep._handoff_bytes for rep in replicas)
            return {
                "metric": "disagg_storm",
                "arm": arm,
                "value": round(
                    _pooled_latency_ms(servers, "itl", 99), 3),
                "unit": "pooled ITL p99 ms",
                "ttft_p50_ms": round(
                    _pooled_latency_ms(servers, "ttft", 50), 3),
                "decode_tok_s": round(emitted / wall, 1) if wall else 0.0,
                "streams_preserved": preserved,
                "requests": len(prompts),
                "handoffs_committed": committed,
                "pages_streamed": streamed,
                "overlap_frac": round(early / total, 3) if total else 0.0,
                "n_long": n_long,
                "n_short": n_short,
                "max_new": max_new,
            }
        finally:
            router.shutdown()
            for rep in replicas:
                rep.shutdown(graceful=False)

    return tuple(run(a) for a in arms)


def packing_storm(cfg, n_tenants=4, n_adapters=2, prompt_len=10,
                  max_new=16, n_slots=2, pack=4, window_s=1.5,
                  think_s=0.05, topology="v5e-1",
                  arms=("whole", "packed")):
    """Round-18 headline: MULTI-TENANT REPLICA PACKING under fractional
    chip virtualization (vChips) vs whole-chip gang granularity, at
    EQUAL hardware. Each tenant runs its OWN small multi-LoRA replica
    (*n_adapters* private adapters over the shared base — tenants
    cannot share a replica: different adapter stacks, isolation); the
    replica needs only 1/*pack* of a chip's HBM. The arms differ only
    in how many tenant replicas the SCHEDULER can place on the same
    chips: the ``whole`` arm requests one whole chip per replica (the
    pre-Round-18 granularity — the other (pack-1)/pack of every chip is
    STRANDED and (n_tenants - n_chips) tenants get no replica at all),
    the ``packed`` arm requests ``1000//pack`` milli-chips so *pack*
    tenant replicas co-locate per chip and every tenant is served.
    Placement runs through the REAL ``Cluster`` (fake device manager,
    fractional accounting, ``check_invariants`` oracle); each SERVED
    tenant then drives its replica closed-loop (one interactive stream,
    *think_s* between requests — small tenants are exactly the traffic
    that leaves a whole chip idle) for a fixed *window_s* wall window.
    Reports aggregate fleet tok/s per chip (the
    ``packing_fleet_toks_s`` gate metric), replicas per chip, tenants
    served, plus a cross-arm greedy parity rider on the tenants both
    arms serve — packing must change THROUGHPUT, never tokens."""
    import dataclasses
    import random as _random
    import time
    from concurrent.futures import ThreadPoolExecutor

    from kubetpu.api.types import ContainerInfo, PodInfo
    from kubetpu.core import Cluster, SchedulingError
    from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
    from kubetpu.jobs import init_params
    from kubetpu.jobs.lora import LoraConfig, init_lora_params
    from kubetpu.jobs.multi_lora import MultiLoraDecodeServer, stack_adapters
    from kubetpu.plugintypes import ResourceTPU
    from kubetpu.plugintypes.mesh import TOPOLOGIES
    from kubetpu.scheduler.meshstate import MILLI_PER_CHIP, FracKey

    dcfg = dataclasses.replace(cfg, remat=False)
    params = init_params(jax.random.PRNGKey(0), dcfg)
    lcfg = LoraConfig(rank=4, alpha=8.0)

    def tenant_stack(t):
        adapters = []
        for a in range(n_adapters):
            lora = init_lora_params(
                jax.random.PRNGKey(t * 10 + a), dcfg, lcfg)
            keys = jax.random.split(
                jax.random.PRNGKey(100 + t * 10 + a), len(lcfg.targets))
            for i, tgt in enumerate(lcfg.targets):
                b = lora["blocks"][f"{tgt}_b"]
                lora["blocks"][f"{tgt}_b"] = (
                    jax.random.normal(keys[i], b.shape, b.dtype) * 0.05)
            adapters.append(lora)
        return stack_adapters(lcfg, adapters)

    stacks = [tenant_stack(t) for t in range(n_tenants)]
    rng = _random.Random(0)
    prompts = [[[rng.randrange(1, dcfg.vocab) for _ in range(prompt_len)]
                for _ in range(8)] for _ in range(n_tenants)]
    max_seq = prompt_len + max_new + 2
    n_chips = len(TOPOLOGIES[topology].host_coords(0))

    def make_server(tenant):
        return MultiLoraDecodeServer(
            dcfg, params, lcfg, stacks[tenant], n_slots=n_slots,
            max_seq=max_seq, max_new_tokens=max_new)

    # pre-compile the replica's leg shapes once (shared _LEG_CACHE) AND
    # seed the parity oracle from INDEPENDENT quiet reference runs —
    # one per tenant — so a single-arm invocation (the bench-gate smoke
    # runs only "packed") still compares against a real reference
    # instead of vacuously against itself
    expected = {}   # (tenant, 0) -> tokens from the quiet reference
    for t in range(n_tenants):
        ref = make_server(t)
        rid = ref.enqueue(prompts[t][0], adapter=0)
        ref.drain()
        expected[(t, 0)] = ref.pop_result(rid)

    def run(arm):
        cluster = Cluster()
        cluster.register_node(
            "bench-n0",
            device=new_fake_tpu_dev_manager(make_fake_tpus_info(topology)))
        placed = []
        # one replica pod per tenant, submitted until the hardware is
        # provably full — the SERVED-tenant count is the scheduler's
        # answer, not the bench's
        for t in range(n_tenants):
            if arm == "whole":
                pod = PodInfo(
                    name=f"tenant{t}",
                    running_containers={
                        "main": ContainerInfo(requests={ResourceTPU: 1})})
            else:
                pod = PodInfo(
                    name=f"tenant{t}",
                    requests={FracKey: MILLI_PER_CHIP // pack},
                    running_containers={"main": ContainerInfo()})
            try:
                cluster.schedule(pod)
                placed.append(t)
            except SchedulingError:
                continue   # this tenant is not served in this arm
        oracle = cluster.check_invariants()
        assert not oracle, oracle
        servers = {t: make_server(t) for t in placed}
        for srv in servers.values():
            srv.warmup()

        def client(t):
            """Tenant *t*'s interactive stream: request, read, think."""
            srv = servers[t]
            emitted = 0
            k = 0
            deadline = time.perf_counter() + window_s
            while time.perf_counter() < deadline:
                prompt = prompts[t][k % len(prompts[t])]
                rid = srv.enqueue(prompt, adapter=k % n_adapters)
                srv.drain()
                toks = srv.pop_result(rid)
                emitted += len(toks) - len(prompt)
                if k == 0:
                    want = expected.setdefault((t, 0), toks)
                    if want != toks:
                        return emitted, False
                k += 1
                time.sleep(think_s)
            return emitted, True

        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=max(1, len(placed))) as ex:
            results = list(ex.map(client, placed))
        wall = time.perf_counter() - t0
        emitted = sum(e for e, _ok in results)
        parity = all(ok for _e, ok in results)
        toks_s = (emitted / wall) if wall else 0.0
        return {
            "metric": "packing_storm",
            "arm": arm,
            "value": round(toks_s / n_chips, 1),
            "unit": "aggregate fleet tok/s per chip",
            "fleet_toks_s": round(toks_s, 1),
            "replicas": len(placed),
            "replicas_per_chip": round(len(placed) / n_chips, 2),
            "tenants_served": len(placed),
            "n_tenants": n_tenants,
            "n_chips": n_chips,
            "pack": pack,
            "parity": parity,
            "n_slots": n_slots,
            "max_new": max_new,
            "window_s": window_s,
            "think_s": think_s,
        }

    return tuple(run(a) for a in arms)


def multilora_storm(cfg, n_tenants=4, n_resident=16, prompt_len=10,
                    max_new=12, n_slots=4, pack=4, window_s=1.5,
                    think_s=0.05, page_size=8, topology="v5e-1",
                    arms=("per_tenant", "packed")):
    """Round-22 headline: ONE packed ``PagedMultiLoraDecodeServer``
    (every tenant's adapter resident in the stacked device tree, one
    compiled paged leg serving any tenant mix) vs the per-tenant-replica
    arm (each tenant its own merged-model paged replica on a Round-18
    fractional vChip — the best the fleet could do before this round),
    at EQUAL hardware. Placement runs through the REAL ``Cluster``:
    the per-tenant arm requests ``1000//pack`` milli-chips per replica,
    so only ``pack`` tenants per chip get served and every served
    tenant decodes alone in a batch of one; the packed arm requests the
    whole chip for ONE replica holding *n_resident* adapters and
    serves ALL *n_tenants* closed-loop streams from shared slots —
    cross-tenant continuous batching is exactly the capacity the
    merged-weights design forfeits. Reports aggregate fleet tok/s per
    chip (the ``multilora_fleet_toks_s`` gate metric) and resident
    adapters per replica (``adapters_per_replica``, the scheduler-
    visible density count — deterministic, NOT normalized), plus a
    greedy parity rider per driven tenant against an independent quiet
    merged reference — packing tenants must change THROUGHPUT, never
    tokens."""
    import dataclasses
    import random as _random
    import time
    from concurrent.futures import ThreadPoolExecutor

    from kubetpu.api.types import ContainerInfo, PodInfo
    from kubetpu.core import Cluster, SchedulingError
    from kubetpu.device import make_fake_tpus_info, new_fake_tpu_dev_manager
    from kubetpu.jobs import init_params
    from kubetpu.jobs.lora import LoraConfig, init_lora_params, merge_lora
    from kubetpu.jobs.multi_lora import PagedMultiLoraDecodeServer
    from kubetpu.jobs.paged import PagedDecodeServer
    from kubetpu.plugintypes import ResourceTPU
    from kubetpu.plugintypes.mesh import TOPOLOGIES
    from kubetpu.scheduler.meshstate import MILLI_PER_CHIP, FracKey

    assert n_resident >= n_tenants
    dcfg = dataclasses.replace(cfg, remat=False)
    params = init_params(jax.random.PRNGKey(0), dcfg)
    lcfg = LoraConfig(rank=4, alpha=8.0)

    def tenant_adapter(t):
        lora = init_lora_params(jax.random.PRNGKey(500 + t), dcfg, lcfg)
        keys = jax.random.split(jax.random.PRNGKey(900 + t),
                                len(lcfg.targets))
        for i, tgt in enumerate(lcfg.targets):
            b = lora["blocks"][f"{tgt}_b"]
            lora["blocks"][f"{tgt}_b"] = (
                jax.random.normal(keys[i], b.shape, b.dtype) * 0.05)
        return lora

    adapters = [tenant_adapter(t) for t in range(n_resident)]
    rng = _random.Random(0)
    prompts = [[[rng.randrange(1, dcfg.vocab) for _ in range(prompt_len)]
                for _ in range(8)] for _ in range(n_tenants)]
    max_seq = -(-(prompt_len + max_new + 2) // page_size) * page_size
    n_chips = len(TOPOLOGIES[topology].host_coords(0))

    def merged_server(t, n_slots_=1):
        return PagedDecodeServer(
            dcfg, merge_lora(params, adapters[t], lcfg),
            n_slots=n_slots_, max_seq=max_seq, max_new_tokens=max_new,
            page_size=page_size,
            n_pages=n_slots_ * (max_seq // page_size + 1))

    def packed_server():
        return PagedMultiLoraDecodeServer(
            dcfg, params, lcfg, adapters, n_slots=n_slots,
            max_seq=max_seq, max_new_tokens=max_new, page_size=page_size,
            n_pages=n_slots * (max_seq // page_size + 1))

    # the parity oracle, per compute path: an independent QUIET
    # reference per driven tenant, so a single-arm invocation (the
    # bench-gate smoke runs only "packed") still compares against a
    # real reference instead of vacuously against itself
    def seed_packed():
        out = {}
        ref = packed_server()
        for t in range(n_tenants):
            rid = ref.enqueue(prompts[t][0], adapter=t)
            ref.drain()
            out[t] = ref.pop_result(rid)
        return out

    def seed_merged():
        out = {}
        for t in range(n_tenants):
            ref = merged_server(t)
            rid = ref.enqueue(prompts[t][0])
            ref.drain()
            out[t] = ref.pop_result(rid)
        return out

    def place(arm):
        """One pod per replica through the real scheduler; returns the
        tenants that got a replica (per-tenant arm) or all tenants
        behind the one packed replica."""
        cluster = Cluster()
        cluster.register_node(
            "bench-n0",
            device=new_fake_tpu_dev_manager(make_fake_tpus_info(topology)))
        placed = []
        if arm == "packed":
            pod = PodInfo(
                name="packed0",
                running_containers={
                    "main": ContainerInfo(requests={ResourceTPU: 1})})
            cluster.schedule(pod)
            placed = list(range(n_tenants))
        else:
            for t in range(n_tenants):
                pod = PodInfo(
                    name=f"tenant{t}",
                    requests={FracKey: MILLI_PER_CHIP // pack},
                    running_containers={"main": ContainerInfo()})
                try:
                    cluster.schedule(pod)
                    placed.append(t)
                except SchedulingError:
                    continue   # this tenant is not served in this arm
        oracle = cluster.check_invariants()
        assert not oracle, oracle
        return placed

    def run_per_tenant():
        expected = seed_merged()
        placed = place("per_tenant")
        servers = {t: merged_server(t) for t in placed}
        for srv in servers.values():
            srv.warmup()

        def client(t):
            srv = servers[t]
            emitted, k, ok = 0, 0, True
            deadline = time.perf_counter() + window_s
            while time.perf_counter() < deadline:
                rid = srv.enqueue(prompts[t][k % len(prompts[t])])
                srv.drain()
                toks = srv.pop_result(rid)
                emitted += len(toks) - prompt_len
                if k == 0 and toks != expected[t]:
                    ok = False
                k += 1
                time.sleep(think_s)
            return emitted, ok

        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=max(1, len(placed))) as ex:
            results = list(ex.map(client, placed))
        wall = time.perf_counter() - t0
        emitted = sum(e for e, _ok in results)
        return (emitted, wall, all(ok for _e, ok in results),
                len(placed), len(placed), 1.0)

    def run_packed():
        expected = seed_packed()
        placed = place("packed")
        srv = packed_server()
        srv.warmup()
        # one driver loop, every tenant closed-loop: a tenant fires its
        # next request *think_s* after its last one finished, and the
        # shared slots batch whatever mix is in flight
        pending, inflight = {}, set()
        k = [0] * n_tenants
        next_fire = [0.0] * n_tenants
        emitted, parity = 0, True
        t0 = time.perf_counter()
        deadline = t0 + window_s
        while True:
            now = time.perf_counter()
            if now >= deadline and not pending:
                break
            if now < deadline:
                for t in placed:
                    if t not in inflight and now >= next_fire[t]:
                        rid = srv.enqueue(
                            prompts[t][k[t] % len(prompts[t])], adapter=t)
                        pending[rid] = t
                        inflight.add(t)
            if pending:
                srv.step()
            else:
                time.sleep(min(think_s, 0.002))
            for rid in [r for r in list(pending) if srv.finished(r)]:
                t = pending.pop(rid)
                inflight.discard(t)
                toks = srv.pop_result(rid)
                emitted += len(toks) - prompt_len
                if k[t] == 0 and toks != expected[t]:
                    parity = False
                k[t] += 1
                next_fire[t] = time.perf_counter() + think_s
        wall = time.perf_counter() - t0
        srv.check_invariants()
        return (emitted, wall, parity, len(placed), 1,
                float(len(srv.resident_adapters())))

    def run(arm):
        emitted, wall, parity, served, replicas, density = (
            run_packed() if arm == "packed" else run_per_tenant())
        toks_s = (emitted / wall) if wall else 0.0
        return {
            "metric": "multilora_storm",
            "arm": arm,
            "value": round(toks_s / n_chips, 1),
            "unit": "aggregate fleet tok/s per chip",
            "fleet_toks_s": round(toks_s, 1),
            "tenants_served": served,
            "n_tenants": n_tenants,
            "replicas": replicas,
            "adapters_per_replica": density,
            "n_resident": n_resident,
            "n_chips": n_chips,
            "pack": pack,
            "parity": parity,
            "n_slots": n_slots,
            "max_new": max_new,
            "window_s": window_s,
            "think_s": think_s,
        }

    return tuple(run(a) for a in arms)


def spec_serving_throughput(cfg, n_slots, prompt_len, rounds):
    """Continuous batching WITH speculation: tokens per round under churn
    (the round replaces the one-token step; acceptance sets the speedup
    for the memory-bound target). Quarter-size draft = the honest
    lower-bound pairing of the spec section's bounds."""
    import dataclasses

    from kubetpu.jobs import init_params
    from kubetpu.jobs.spec_serving import SpeculativeDecodeServer

    tcfg = dataclasses.replace(cfg, remat=False)
    dcfg = dataclasses.replace(
        tcfg,
        d_model=max(64, cfg.d_model // 4),
        n_layers=max(1, cfg.n_layers // 4),
        n_heads=max(1, cfg.n_heads // 4),
        d_ff=max(128, cfg.d_ff // 4),
    )
    server = SpeculativeDecodeServer(
        tcfg, dcfg,
        init_params(jax.random.PRNGKey(0), tcfg),
        init_params(jax.random.PRNGKey(7), dcfg),
        n_slots=n_slots, max_seq=min(cfg.max_seq, 1024),
        max_new_tokens=32, gamma=4,
    )
    server.warmup()
    rng = __import__("random").Random(0)
    emitted = 0
    for r in range(rounds):
        if r % 4 == 0:
            server.enqueue([rng.randrange(1, tcfg.vocab) for _ in range(prompt_len)])
        emitted += sum(len(v) for v in server.step().values())
    server.drain()
    stats = server.metrics_summary()
    return {
        "metric": "spec_serving_tokens_per_round",
        "value": round(server.mean_tokens_per_round(), 2),
        "unit": "tokens/round",
        "round_p50_ms": round(stats["step"]["p50_ms"], 3),
        "gamma": 4,
        "n_slots": n_slots,
        "tokens_emitted": emitted,
    }


def speculative_paged_storm(n_slots=4, long_len=48, short_len=12, n_shorts=3,
                            rounds=3, max_new=24, gamma_max=4, page_size=16,
                            small=False):
    """Round-10 headline: speculative decoding over the paged pool vs
    plain paged decode, under the mixed-load storm (each wave enqueues a
    long prompt with shorts right behind it), with a TRAINED draft
    (``_train_spec_pair`` — the well-agreeing pair; storm prompts come
    from the same corpus so decode-time agreement holds). Both arms run
    the identical trained target through the identical pool; the
    speculative arm adds draft+verify rounds with adaptive gamma.
    Reports decode tok/s, TTFT p50 (server-recorded Round-8 histogram),
    realized tokens/round and the device acceptance rate — the
    rounds-not-tokens win, measured on the production serving path."""
    import time as _time

    from kubetpu.jobs.paged import PagedDecodeServer
    from kubetpu.jobs.spec_serving import PagedSpeculativeDecodeServer

    tcfg, dcfg, t_params, d_params, data, agree = _train_spec_pair(small)
    rows = [[int(t) for t in data[i % len(data)][0][i % 4]]
            for i in range(rounds * (1 + n_shorts))]
    prompts = []
    for r in range(rounds):
        wave = rows[r * (1 + n_shorts):(r + 1) * (1 + n_shorts)]
        prompts.append([wave[0][:long_len]]
                       + [w[:short_len] for w in wave[1:]])
    # page-aligned max_seq (the paged warmup's bucket grid requires it)
    max_seq = -(-(long_len + max_new + gamma_max + 2) // page_size) * page_size
    n_pages = n_slots * ((max_seq + gamma_max + page_size - 1) // page_size)

    def run(server, spec):
        if spec:
            # Round-11: recompile tracking on the speculative arm — the
            # adaptive-gamma walk compiles one round leg per gamma, and
            # the profiler's per-leg counters make that storm legible
            server.enable_profiler(sample_every=8)
        server.warmup()
        rid_prompt = []
        t0 = _time.perf_counter()
        for wave in prompts:
            for p in wave:
                rid_prompt.append((server.enqueue(p), p))
            server.drain()
        dt = _time.perf_counter() - t0
        emitted = sum(len(server.result(rid)) - len(p)
                      for rid, p in rid_prompt)
        stats = server.metrics_summary()
        row = {
            "metric": "speculative_paged_storm",
            "variant": "speculative" if spec else "plain",
            "value": round(emitted / dt, 1),
            "unit": "decode tokens/s",
            "ttft_p50_ms": round(stats["ttft"]["p50_ms"], 3),
            "requests": len(rid_prompt),
            "tokens_emitted": emitted,
            "n_slots": n_slots,
            "gamma_max": gamma_max,
            "teacher_forced_agreement": round(agree, 3),
        }
        if spec:
            proposed = server._c_spec_proposed.value
            row["tokens_per_round"] = round(server.mean_tokens_per_round(), 2)
            row["acceptance_rate"] = round(
                server._c_spec_accepted.value / proposed, 3) if proposed else 0.0
            prof = server.profile_summary()
            row["recompiles"] = {k: v["recompiles"]
                                 for k, v in prof["recompiles"].items()}
            row["gamma_events"] = len(server.events.events(kind="gamma"))
            server.check_invariants()    # the pool oracle rides the bench
        return row

    plain = run(PagedDecodeServer(
        tcfg, t_params, n_slots=n_slots, max_seq=max_seq,
        max_new_tokens=max_new, page_size=page_size, n_pages=n_pages,
    ), spec=False)
    spec = run(PagedSpeculativeDecodeServer(
        tcfg, dcfg, t_params, d_params, n_slots=n_slots, max_seq=max_seq,
        max_new_tokens=max_new, page_size=page_size, n_pages=n_pages,
        gamma_max=gamma_max,
    ), spec=True)
    spec["speedup_vs_plain"] = round(spec["value"] / plain["value"], 2)
    return plain, spec


def paged_kernel_tune(cfg, n_slots, seq_len, variant, kv_int8,
                      pages_per_block, page_size=16, chunk_t=5):
    """One ``pagedtune`` point: raw fused paged-attention kernel
    throughput (Round-15) on a synthetic full pool — every slot holds
    *seq_len* tokens, the table walks ``pages_per_block`` pages per grid
    step (the VMEM tile knob). *variant* ``decode`` is the one-token
    step (T == 1), ``chunk`` the speculative-verify leg (T = chunk_t);
    *kv_int8* swaps the pool for (values int8, scales f32) pairs with
    in-kernel dequant. Parity is tier-1's job; this measures query
    tokens/s by the two-point marginal method."""
    import numpy as np

    from kubetpu.jobs.profiling import marginal_ms
    from kubetpu.jobs.quant import quantize_kv_chunk
    from kubetpu.ops.paged_attention import paged_attention_chunk

    h, h_kv, d = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    max_pages = (seq_len + page_size - 1) // page_size
    n_pool = n_slots * max_pages
    kk, kv_, kq = jax.random.split(jax.random.PRNGKey(0), 3)
    kp = jax.random.normal(kk, (n_pool, page_size, h_kv, d), jnp.float32)
    vp = jax.random.normal(kv_, (n_pool, page_size, h_kv, d), jnp.float32)
    if kv_int8:
        kp, vp = quantize_kv_chunk(kp), quantize_kv_chunk(vp)
    table = jnp.asarray(
        np.arange(n_pool, dtype=np.int32).reshape(n_slots, max_pages))
    pos = jnp.full((n_slots,), seq_len - 1, jnp.int32)
    t = 1 if variant == "decode" else chunk_t
    q0 = jax.random.normal(kq, (n_slots, t, h, d), jnp.float32)

    def make_run(n):
        @jax.jit
        def run():
            def body(_, q):
                out = paged_attention_chunk(
                    q, kp, vp, table, pos,
                    pages_per_block=pages_per_block)
                # live data dependency: the next query reads this output,
                # so XLA cannot CSE/dead-code the iterations
                return q + 1e-6 * out
            return jnp.sum(jax.lax.fori_loop(0, n, body, q0))
        return run

    step_ms = marginal_ms(make_run, 2, 10, reps=2)
    return {
        "metric": f"paged_kernel_{variant}_toks_s",
        "value": round(n_slots * t / (step_ms / 1e3), 1),
        "unit": "query tokens/s",
        "pool": "int8" if kv_int8 else "f32",
        "pages_per_block": pages_per_block,
        "n_slots": n_slots,
        "seq_len": seq_len,
        "page_size": page_size,
        "chunk_t": t,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny config (structure check; numbers meaningless)")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--out", default=None, help="also merge JSON lines into FILE")
    ap.add_argument("--only", default=None,
                    help="comma list of sections: train,flash,decode,spec,"
                         "flashtune,pagedtune,serving (big compiles over the "
                         "tunneled backend make a full run slow; sections "
                         "merge into --out)")
    args = ap.parse_args()

    if args.smoke:
        # Smoke must run where a sitecustomize pins JAX to a hardware
        # platform (tests/conftest.py documents the same workaround).
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001 — backend already initialized
            pass

    cfg = flagship_cfg(args.smoke)
    sections = {"train", "flash", "decode", "spec", "serving", "flashtune",
                "pagedtune"}
    only = (
        {s.strip() for s in args.only.split(",")} if args.only else set(sections)
    )
    unknown = only - sections
    if unknown:
        ap.error(f"unknown section(s) {sorted(unknown)}; choose from "
                 f"{sorted(sections)}")
    results = []

    def emit(r):
        results.append(r)
        print(json.dumps(r), flush=True)
        if args.out:
            # merge after EVERY measurement: a later section OOMing or
            # timing out must not lose the results already taken
            _merge_out(args.out, results)

    if args.smoke:
        batch, seq = 2, 256
        seqs = [256]
        dec = (2, 16, 8)
    else:
        batch, seq = 4, 2048
        seqs = [2048, 4096, 8192]
        dec = (8, 128, 128)

    if "flashtune" in only:
        # MFU push (VERDICT r5 #9): sweep the flash kernels' VMEM tiles on
        # the flagship train shape. TPU-only — the Pallas kernels don't
        # run on the CPU backend, and tile choice is a hardware question.
        if jax.default_backend() == "cpu":
            print(json.dumps({"metric": "flashtune", "skipped": "cpu backend"}))
        else:
            best = None
            # ALWAYS sweep the (128,128) default too: flashtune_best only
            # ranks rows from THIS sweep, so omitting the default could
            # crown a "best" tile slower than what the code ships with
            points = ((128, 128), (256, 128), (128, 256), (256, 256),
                      (64, 128), (128, 64), (512, 128))
            for bq, bk in points:
                try:
                    r = train_throughput(cfg, batch, seq, args.steps, "flash",
                                         remat_policy="dots",
                                         loss_chunk=64 if args.smoke else 256,
                                         block_q=bq, block_k=bk)
                except Exception as e:  # noqa: BLE001 — a tile may not fit VMEM
                    print(json.dumps({"metric": "flashtune_point",
                                      "block_q": bq, "block_k": bk,
                                      "error": str(e)[:120]}), flush=True)
                    continue
                emit(r)
                if best is None or r["value"] > best["value"]:
                    best = r
            if best is not None:
                print(json.dumps({"metric": "flashtune_best",
                                  "block_q": best["block_q"],
                                  "block_k": best["block_k"],
                                  "mfu": best["mfu"]}), flush=True)

    if "pagedtune" in only:
        # Round-15 raw-speed push: sweep the fused paged-attention
        # kernels' pages_per_block VMEM tile over the decode (T=1) and
        # speculative-verify chunk legs, f32 + int8 pools. TPU-only —
        # like flashtune, the Pallas kernels don't run compiled on the
        # CPU backend and tile choice is a hardware question.
        if jax.default_backend() == "cpu":
            print(json.dumps({"metric": "pagedtune", "skipped": "cpu backend"}))
        else:
            pt_slots = 4 if args.smoke else 16
            pt_seq = 256 if args.smoke else 2048
            for kv_int8 in (False, True):
                for variant in ("decode", "chunk"):
                    best = None
                    # ALWAYS sweep the shipped default tile (1) too:
                    # pagedtune_best only ranks rows from THIS sweep, so
                    # omitting the default could crown a "best" tile
                    # slower than what the code ships with
                    for ppb in (1, 2, 4, 8):
                        try:
                            r = paged_kernel_tune(
                                cfg, pt_slots, pt_seq, variant, kv_int8, ppb)
                        except Exception as e:  # noqa: BLE001 — a tile may not fit VMEM
                            print(json.dumps({
                                "metric": "pagedtune_point",
                                "variant": variant,
                                "pool": "int8" if kv_int8 else "f32",
                                "pages_per_block": ppb,
                                "error": str(e)[:120]}), flush=True)
                            continue
                        emit(r)
                        if best is None or r["value"] > best["value"]:
                            best = r
                    if best is not None:
                        print(json.dumps({
                            "metric": "pagedtune_best",
                            "variant": variant,
                            "pool": best["pool"],
                            "pages_per_block": best["pages_per_block"],
                            "toks_s": best["value"]}), flush=True)

    if "train" in only:
        attn = "flash" if jax.default_backend() != "cpu" else "dense"
        emit(train_throughput(cfg, batch, seq, args.steps, attn))
        # selective remat: save matmul outputs, recompute only elementwise —
        # trades activation memory for the full-remat recompute pass
        emit(train_throughput(cfg, batch, seq, args.steps, attn,
                              remat_policy="dots"))
        # chunked CE tail: stream the LM head over 256-token chunks instead
        # of materializing (B, S, 32k) f32 logits — the freed HBM is what
        # admits the doubled batch (same model, same seq)
        chunk = 64 if args.smoke else 256
        emit(train_throughput(cfg, batch, seq, args.steps, attn,
                              remat_policy="dots", loss_chunk=chunk))
        try:
            emit(train_throughput(cfg, batch * 2, seq, args.steps, attn,
                                  remat_policy="dots", loss_chunk=chunk))
        except Exception as e:  # noqa: BLE001 — batch 2x may OOM; keep artifact
            emit({"metric": "train_tokens_per_s", "value": None,
                  "unit": "tokens/s", "batch": batch * 2, "seq": seq,
                  "attention": attn, "remat": "dots", "loss_chunk": chunk,
                  "error": type(e).__name__})
    if "flash" in only:
        for r in flash_vs_dense(cfg, seqs):
            emit(r)
    if "decode" in only:
        emit(decode_throughput(cfg, *dec, n_kv_heads=0))
        emit(decode_throughput(cfg, *dec, n_kv_heads=4 if not args.smoke else 2))
        emit(decode_throughput(cfg, *dec, n_kv_heads=4 if not args.smoke else 2,
                               int8=True))
        emit(decode_throughput(cfg, *dec, n_kv_heads=4 if not args.smoke else 2,
                               kv_int8=True))
        emit(decode_throughput(cfg, *dec, n_kv_heads=4 if not args.smoke else 2,
                               int8=True, kv_int8=True))
    if "spec" in only:
        emit(speculative_throughput(cfg, *dec, gamma=4))
        emit(speculative_throughput(cfg, *dec, gamma=4, self_draft=True))
        emit(speculative_trained_pair(
            prompt_len=16 if args.smoke else 64,
            gen_steps=32 if args.smoke else 256, gamma=4,
            small=args.smoke))
    if "serving" in only:
        emit(serving_throughput(cfg, n_slots=4 if args.smoke else 8,
                                prompt_len=16 if args.smoke else 128,
                                rounds=20 if args.smoke else 60))
        # head-of-line blocking: a long prompt arriving mid-decode,
        # monolithic vs chunked-prefill (+ double-buffered host loop)
        # smoke sizes chosen so the inversion shows even on CPU, where
        # per-step dispatch overhead (not the chip) dominates small steps
        for row in mixed_load_serving(
                cfg, n_slots=4 if args.smoke else 8,
                long_len=384 if args.smoke else 2048,
                prefill_budget=128 if args.smoke else 256,
                smoke=args.smoke):
            emit(row)
        # admission storm measured by the server's OWN histograms, with
        # the Round-11 signal layer riding the chunked arm (sampled
        # profiler phase breakdown + recompiles, declared SLOs, event
        # counts in the row)
        for row in mixed_load_storm(
                cfg, n_slots=4, rounds=2 if args.smoke else 4):
            emit(row)
        # shared-prefix KV reuse: identical system prompt across a storm,
        # radix prefix cache on vs off (Round-9)
        for row in prefix_reuse_storm(
                cfg,
                n_slots=2 if args.smoke else 4,
                sys_len=96 if args.smoke else 1024,
                tail_len=8 if args.smoke else 32,
                n_requests=6 if args.smoke else 16,
                max_new=4 if args.smoke else 16,
                page_size=16,
                prefill_budget=32 if args.smoke else 256,
                cache_pages=16 if args.smoke else 128):
            emit(row)
        # Round-14 data plane: affinity vs random routing across a
        # replica fleet — cluster-wide hit rate and pooled TTFT/ITL
        for row in router_storm(
                cfg,
                n_replicas=2,
                n_families=3,
                sys_len=64 if args.smoke else 512,
                tail_len=8 if args.smoke else 32,
                requests_per_family=3 if args.smoke else 6,
                max_new=4 if args.smoke else 16,
                page_size=16,
                prefill_budget=32 if args.smoke else 256,
                cache_pages=32 if args.smoke else 128):
            emit(row)
        # Round-16: drain-with-live-migration vs wait-for-stream-end —
        # the elastic scale-down story (streams preserved, drain
        # latency, ITL blip during the handoff)
        for row in migration_storm(
                cfg,
                n_replicas=2,
                n_streams=3 if args.smoke else 6,
                prompt_len=16 if args.smoke else 64,
                max_new=32 if args.smoke else 128,
                page_size=16,
                n_slots=2 if args.smoke else 4):
            emit(row)
        # Round-17: disaggregated prefill/decode vs colocated over the
        # PREFILL-HEAVY mixed storm (long prompts poisoning short
        # decodes — the traffic disaggregation exists for): decode ITL
        # p99 stops paying for other users' prompts, and with the
        # pools matched to the work ratio the decode pool's tok/s
        # comes out ahead too (the pipelined KV handoff)
        for row in disagg_storm(
                cfg,
                n_long=3 if args.smoke else 4,
                long_len=192 if args.smoke else 384,
                n_short=5 if args.smoke else 6,
                short_len=8,
                max_new=24 if args.smoke else 64,
                page_size=16,
                prefill_budget=16 if args.smoke else 64,
                n_slots=8 if args.smoke else 10,
                n_prefill=2, n_decode=1):
            emit(row)
        # Round-18: fractional chip virtualization — multi-tenant
        # replica packing (vChips) vs whole-chip granularity at equal
        # hardware; the scheduler decides each arm's replica count
        for row in packing_storm(
                cfg,
                n_tenants=4,
                prompt_len=8 if args.smoke else 24,
                max_new=12 if args.smoke else 32,
                window_s=1.2 if args.smoke else 3.0,
                n_slots=2,
                pack=4):
            emit(row)
        # Round-19: tiered KV cache — a working set 4x the HBM tree
        # budget; LRU victims spill to host DRAM and fill back on
        # return (host arm) or arrive over /prefix_fetch from a warm
        # peer (host_peer arm) instead of cold-prefilling
        for row in tiering_storm(
                cfg,
                n_families=4,
                sys_len=96 if args.smoke else 512,
                tail_len=8 if args.smoke else 32,
                rounds=3 if args.smoke else 4,
                max_new=4 if args.smoke else 16,
                page_size=16,
                prefill_budget=32 if args.smoke else 256):
            emit(row)
        # Round-20: crash tolerance — SIGKILL a loaded replica
        # mid-storm, boot a same-name replacement (boot-nonce
        # takeover), measure kill-to-routable recovery with every
        # keyed stream preserved token-exact
        for row in crash_storm(
                cfg,
                n_replicas=2,
                n_streams=2 if args.smoke else 4,
                prompt_len=16 if args.smoke else 64,
                max_new=48 if args.smoke else 128,
                page_size=16,
                n_slots=2 if args.smoke else 4):
            emit(row)
        emit(spec_serving_throughput(cfg, n_slots=2 if args.smoke else 4,
                                     prompt_len=16 if args.smoke else 128,
                                     rounds=10 if args.smoke else 40))
        # Round-10: speculation over the paged pool with a trained draft
        # (prompt lengths bounded by the trained corpus' seq=64 rows)
        for row in speculative_paged_storm(
                n_slots=2 if args.smoke else 4,
                long_len=48 if args.smoke else 64,
                short_len=12 if args.smoke else 16,
                max_new=16 if args.smoke else 32,
                gamma_max=4, page_size=16,
                small=args.smoke):
            emit(row)
    return 0


if __name__ == "__main__":
    sys.exit(main())
