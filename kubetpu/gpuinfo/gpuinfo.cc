// gpuinfo — native GPU hardware enumerator (NVML wire schema).
//
// The GPU-side analog of tpuinfo and of the reference's nvmlinfo binary
// (nvidiagpuplugin/nvmlinfo/main.go): a short-lived native process that
// emits one JSON object in the NVML wire format
// (nvgputypes/types.go:8-43) on stdout, behind the same exec-JSON process
// boundary. No NVML linkage exists in this environment, so the probe reads
// sysfs PCI state; the P2P link levels NVML would report (1..6,
// nvidia_gpu_manager.go:158-176) are approximated from PCI topology:
//
//   same PCI parent bridge             -> link 4 (single switch)
//   same NUMA node (when known)        -> link 3 (hostbridge / same CPU)
//   different NUMA nodes               -> link 1 (cross CPU)
//   NUMA unknown, same PCI domain      -> link 3
//
// (links 6/5 — same board / NVLink — are NVML-only knowledge and never
// emitted by the sysfs probe; fixtures can exercise them via --fake.)
//
// Probe root defaults to /sys and is overridable via GPUINFO_SYSFS_ROOT so
// tests can fixture it. Fixture device dirs may carry two extra files the
// kernel doesn't provide: `parent` (opaque bridge token, stands in for the
// resolved parent path) and `vram_mib` (memory size).
//
// Modes:
//   gpuinfo json            probe sysfs, print JSON
//   gpuinfo --fake titan8   canned 8-GPU two-socket box (the TITAN X test
//                           fixture shape, nvidia_gpu_manager_test.go:16)
//   gpuinfo --fake k80x4    canned 4-GPU box with no topology (the K80
//                           cloud-box fixture, nvidia_gpu_manager_test.go:17)
//   gpuinfo                 human-readable dump

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <dirent.h>
#include <limits.h>
#include <string>
#include <vector>

#include "../native/json_escape.h"

namespace {

struct Gpu {
  std::string uuid;
  std::string model;
  std::string path;
  std::string bus_id;
  std::string parent;   // bridge token for link inference
  long long mem_mib = 0;
  int numa = -1;
  int bandwidth = 0;
  std::vector<std::pair<std::string, int>> topology;  // (BusID, Link)
};

std::string EnvOr(const char* key, const char* fallback) {
  const char* v = getenv(key);
  return v ? std::string(v) : std::string(fallback);
}

using kubetpu::JsonEscape;

std::string SysfsRoot() { return EnvOr("GPUINFO_SYSFS_ROOT", "/sys"); }

std::string ReadFileTrim(const std::string& path) {
  FILE* f = fopen(path.c_str(), "r");
  if (!f) return "";
  char buf[256] = {0};
  if (!fgets(buf, sizeof(buf), f)) buf[0] = '\0';
  fclose(f);
  size_t len = strlen(buf);
  while (len > 0 && (buf[len - 1] == '\n' || buf[len - 1] == '\r' || buf[len - 1] == ' '))
    buf[--len] = '\0';
  return buf;
}

// Known NVIDIA device ids -> marketing names; anything else gets the hex id.
const struct { const char* dev; const char* name; } kModels[] = {
    {"0x17c2", "GeForce GTX TITAN X"},
    {"0x102d", "Tesla K80"},
    {"0x1db4", "Tesla V100-PCIE-16GB"},
    {"0x20b0", "A100-SXM4-40GB"},
    {"0x2330", "H100 SXM5"},
};

std::string ModelFor(const std::string& device_id) {
  for (const auto& m : kModels)
    if (device_id == m.dev) return m.name;
  return device_id.empty() ? "NVIDIA GPU" : "NVIDIA GPU (" + device_id + ")";
}

// Bridge token of a PCI function: the fixture's `parent` file when present,
// else the parent directory of the resolved sysfs device path.
std::string ParentToken(const std::string& dev_dir) {
  std::string fixture = ReadFileTrim(dev_dir + "/parent");
  if (!fixture.empty()) return fixture;
  char resolved[PATH_MAX];
  if (realpath(dev_dir.c_str(), resolved) == nullptr) return "";
  std::string p(resolved);
  size_t slash = p.rfind('/');
  return slash == std::string::npos ? "" : p.substr(0, slash);
}

std::string RootComplex(const std::string& bus_id) {
  // "0000:05:00.0" -> domain+bus nibble "0000:05" is too fine; the root
  // complex is the PCI domain ("0000") — segment before the first ':'.
  size_t colon = bus_id.find(':');
  return colon == std::string::npos ? bus_id : bus_id.substr(0, colon);
}

std::vector<Gpu> ProbeSysfs() {
  std::vector<Gpu> gpus;
  std::string dev_root = SysfsRoot() + "/bus/pci/devices";
  DIR* dir = opendir(dev_root.c_str());
  if (!dir) return gpus;
  std::vector<std::string> entries;
  while (dirent* ent = readdir(dir)) {
    if (ent->d_name[0] == '.') continue;
    entries.push_back(ent->d_name);
  }
  closedir(dir);
  // sort bus ids so indices are stable
  for (size_t i = 0; i < entries.size(); i++)
    for (size_t j = i + 1; j < entries.size(); j++)
      if (entries[j] < entries[i]) std::swap(entries[i], entries[j]);

  int index = 0;
  for (const std::string& name : entries) {
    std::string d = dev_root + "/" + name;
    std::string vendor = ReadFileTrim(d + "/vendor");
    std::string cls = ReadFileTrim(d + "/class");
    // NVIDIA display (0x0300xx) / 3D (0x0302xx) controllers only
    if (vendor != "0x10de") continue;
    if (cls.rfind("0x0300", 0) != 0 && cls.rfind("0x0302", 0) != 0) continue;
    Gpu g;
    g.bus_id = name;
    g.uuid = "GPU-" + name;  // sysfs has no NVML UUID; bus id is unique
    g.model = ModelFor(ReadFileTrim(d + "/device"));
    char path[64];
    snprintf(path, sizeof(path), "/dev/nvidia%d", index);
    g.path = path;
    g.parent = ParentToken(d);
    std::string numa = ReadFileTrim(d + "/numa_node");
    g.numa = numa.empty() ? -1 : atoi(numa.c_str());
    std::string vram = ReadFileTrim(d + "/vram_mib");
    g.mem_mib = vram.empty() ? 0 : atoll(vram.c_str());
    index++;
    gpus.push_back(g);
  }
  // pairwise link levels from PCI topology (see header comment)
  for (size_t i = 0; i < gpus.size(); i++) {
    for (size_t j = 0; j < gpus.size(); j++) {
      if (i == j) continue;
      int link;
      if (!gpus[i].parent.empty() && gpus[i].parent == gpus[j].parent)
        link = 4;
      else if (gpus[i].numa >= 0 && gpus[j].numa >= 0)
        link = (gpus[i].numa == gpus[j].numa) ? 3 : 1;
      else
        link = RootComplex(gpus[i].bus_id) == RootComplex(gpus[j].bus_id) ? 3 : 1;
      gpus[i].topology.push_back({gpus[j].bus_id, link});
    }
  }
  return gpus;
}

std::vector<Gpu> FakeBox(const std::string& kind) {
  std::vector<Gpu> gpus;
  if (kind == "titan8") {
    // 8x TITAN X, two sockets; NVLink-ish pairs (link 5) within a socket,
    // hostbridge (3) across pairs on the same socket, and — like the
    // reference's TITAN fixture — NO cross-socket entries, so grouping
    // yields gpugrp0 pairs / one gpugrp1 quad per socket.
    for (int i = 0; i < 8; i++) {
      Gpu g;
      char buf[64];
      snprintf(buf, sizeof(buf), "0000:%02X:00.0", i + 4);
      g.bus_id = buf;
      snprintf(buf, sizeof(buf), "GPU-titan8-%d", i);
      g.uuid = buf;
      g.model = "GeForce GTX TITAN X";
      snprintf(buf, sizeof(buf), "/dev/nvidia%d", i);
      g.path = buf;
      g.mem_mib = 12238;
      g.bandwidth = 15760;
      gpus.push_back(g);
    }
    for (int i = 0; i < 8; i++)
      for (int j = 0; j < 8; j++) {
        if (i == j || i / 4 != j / 4) continue;  // same socket only
        gpus[i].topology.push_back({gpus[j].bus_id, (i / 2 == j / 2) ? 5 : 3});
      }
  } else if (kind == "k80x4") {
    for (int i = 0; i < 4; i++) {
      Gpu g;
      char buf[64];
      snprintf(buf, sizeof(buf), "0000:%02X:00.0", i + 4);
      g.bus_id = buf;
      snprintf(buf, sizeof(buf), "GPU-k80x4-%d", i);
      g.uuid = buf;
      g.model = "Tesla K80";
      snprintf(buf, sizeof(buf), "/dev/nvidia%d", i);
      g.path = buf;
      g.mem_mib = 11441;
      g.bandwidth = 11832;
      gpus.push_back(g);  // Topology deliberately empty (cloud box)
    }
  } else {
    fprintf(stderr, "gpuinfo: unknown fake box %s (titan8|k80x4)\n", kind.c_str());
    exit(2);
  }
  return gpus;
}

void PrintJson(const std::vector<Gpu>& gpus) {
  printf("{\"Version\":{\"Driver\":\"%s\",\"CUDA\":\"%s\"},",
         JsonEscape(EnvOr("GPUINFO_DRIVER_VERSION", "sysfs")).c_str(),
         JsonEscape(EnvOr("GPUINFO_CUDA_VERSION", "")).c_str());
  printf("\"Devices\":[");
  for (size_t i = 0; i < gpus.size(); i++) {
    const Gpu& g = gpus[i];
    if (i) printf(",");
    printf("{\"UUID\":\"%s\",\"Model\":\"%s\",\"Path\":\"%s\",",
           JsonEscape(g.uuid).c_str(), JsonEscape(g.model).c_str(),
           JsonEscape(g.path).c_str());
    printf("\"Memory\":{\"Global\":%lld},", g.mem_mib);
    printf("\"PCI\":{\"BusID\":\"%s\",\"Bandwidth\":%d},",
           JsonEscape(g.bus_id).c_str(), g.bandwidth);
    if (g.topology.empty()) {
      printf("\"Topology\":null}");
    } else {
      printf("\"Topology\":[");
      for (size_t t = 0; t < g.topology.size(); t++) {
        if (t) printf(",");
        printf("{\"BusID\":\"%s\",\"Link\":%d}",
               JsonEscape(g.topology[t].first).c_str(), g.topology[t].second);
      }
      printf("]}");
    }
  }
  printf("]}\n");
}

void PrintHuman(const std::vector<Gpu>& gpus) {
  printf("GPUs: %zu\n", gpus.size());
  for (const Gpu& g : gpus) {
    printf("  %s %s %s (%lld MiB) bus=%s\n", g.uuid.c_str(), g.model.c_str(),
           g.path.c_str(), g.mem_mib, g.bus_id.c_str());
    for (const auto& t : g.topology)
      printf("    -> %s link %d\n", t.first.c_str(), t.second);
  }
}

}  // namespace

int main(int argc, char** argv) {
  bool json = false;
  bool human = false;
  std::string fake;
  for (int i = 1; i < argc; i++) {
    std::string arg = argv[i];
    if (arg == "json") {
      json = true;
    } else if (arg == "--fake" && i + 1 < argc) {
      fake = argv[++i];
      json = true;
    } else if (arg == "--human") {
      human = true;
    } else {
      fprintf(stderr, "usage: gpuinfo [json] [--fake titan8|k80x4] [--human]\n");
      return 2;
    }
  }
  std::vector<Gpu> gpus = fake.empty() ? ProbeSysfs() : FakeBox(fake);
  if (json && !human)
    PrintJson(gpus);
  else
    PrintHuman(gpus);
  return 0;
}
