"""Token sampling for the decode paths: temperature, top-k, nucleus
(top-p) — TPU-shaped.

Everything here is built to live INSIDE a jitted decode scan: the sampler
configuration is static (baked at trace time, no data-dependent control
flow), the shapes are static (top-k via ``lax.top_k``, top-p via a full
sort + cumulative mask — never a dynamic gather), and the filtering is
expressed as masking logits to -inf so one ``jax.random.categorical``
draws from the renormalized distribution implicitly.

``make_sampler`` composes the three filters in the standard order
(temperature -> top-k -> top-p) and returns ``sample(logits, rng) ->
tokens`` for ``(..., V)`` logits. Greedy (temperature == 0) bypasses the
filters entirely — argmax needs none of them.

Reference: none (the reference has no inference stack, SURVEY.md §2);
semantics follow the de-facto public sampling stack (temperature scaling,
top-k truncation, nucleus sampling per Holtzman et al.).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Mask everything below the k-th largest logit to -inf.
    Static-shape: one lax.top_k for the threshold, then a compare."""
    if k <= 0:
        raise ValueError("top_k must be positive")
    k = min(k, logits.shape[-1])
    thresh = jax.lax.top_k(logits, k)[0][..., -1:]       # (..., 1)
    return jnp.where(logits < thresh, NEG_INF, logits)


def apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest set of tokens whose
    probabilities sum to >= p (the top token always survives). Full sort +
    cumulative mask — static shapes, no host control flow."""
    if not 0.0 < p <= 1.0:
        raise ValueError("top_p must be in (0, 1]")
    if p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]   # desc
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # token i (sorted) survives while the mass BEFORE it is < p — the
    # boundary token that crosses p is kept (standard nucleus semantics)
    keep_sorted = (cum - probs) < p
    # threshold = smallest surviving logit; everything below is cut
    cutoff = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < cutoff, NEG_INF, logits)


def make_sampler(
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
):
    """``sample(logits (..., V), rng) -> tokens (...)`` with the filters
    baked statically. temperature == 0 is greedy (argmax; rng unused,
    filters irrelevant — a truncated argmax is still the argmax)."""
    if temperature < 0:
        raise ValueError("temperature must be >= 0")

    def sample(logits, rng):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        x = logits.astype(jnp.float32) / temperature
        if top_k is not None:
            x = apply_top_k(x, top_k)
        if top_p is not None:
            x = apply_top_p(x, top_p)
        return jax.random.categorical(rng, x).astype(jnp.int32)

    return sample


def apply_top_k_rows(logits: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Per-row top-k: *k* is a traced (...) int32 array broadcast over the
    leading dims (0 = filter off for that row). Static shapes: one full
    descending sort, then a per-row threshold gather."""
    v = logits.shape[-1]
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    idx = jnp.clip(k - 1, 0, v - 1)
    thresh = jnp.take_along_axis(sorted_desc, idx[..., None], axis=-1)
    masked = jnp.where(logits < thresh, NEG_INF, logits)
    return jnp.where((k > 0)[..., None], masked, logits)


def apply_top_p_rows(logits: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Per-row nucleus filtering: *p* is a traced (...) float array
    (>= 1 = filter off for that row). Same boundary semantics as
    ``apply_top_p``."""
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < p[..., None]
    cutoff = jnp.min(
        jnp.where(keep_sorted, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    masked = jnp.where(logits < cutoff, NEG_INF, logits)
    return jnp.where((p < 1.0)[..., None], masked, logits)


def chosen_logprob(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """log P(token) under the RAW model distribution (log-softmax of the
    unfiltered, untempered logits) — the serving-API logprob convention,
    in ONE place so every server reports identically. logits (..., V),
    tokens (...) -> (...)."""
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(lp, tokens[..., None], axis=-1)[..., 0]


def make_slot_sampler():
    """Per-request sampling inside ONE compiled step:
    ``sample(logits (..., V), rng, temperature, top_k, top_p) -> (...)``
    where temperature/top_k/top_p are traced arrays broadcast over the
    leading dims — every slot of a serving batch draws with its own
    settings, no per-config recompile. Rows with temperature <= 0 are
    greedy (exact argmax, filters bypassed), matching ``make_sampler``'s
    static greedy path token-for-token.

    ``rng`` may be one key for the whole batch (the classic spelling) or
    PER-ROW keys shaped ``logits.shape[:-1] + (2,)`` — the serving
    request-determinism contract: each slot draws from its own request
    key stream, so a request's sampled tokens depend only on (seed, rid,
    position), never on batch composition or step alignment."""

    def sample(logits, rng, temperature, top_k, top_p):
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def stochastic(_):
            # two full-vocab sorts (one per filter) — acceptable at serving
            # batch sizes; the all-greedy fast path below skips them all
            x = logits.astype(jnp.float32) / jnp.maximum(
                temperature, 1e-6)[..., None]
            x = apply_top_k_rows(x, top_k)
            x = apply_top_p_rows(x, top_p)
            if rng.ndim > 1:   # per-row keys (..., 2): one draw per key
                flat_k = rng.reshape(-1, rng.shape[-1])
                flat_x = x.reshape(-1, x.shape[-1])
                drawn = jax.vmap(jax.random.categorical)(flat_k, flat_x)
                drawn = drawn.reshape(x.shape[:-1]).astype(jnp.int32)
            else:
                drawn = jax.random.categorical(rng, x).astype(jnp.int32)
            return jnp.where(temperature <= 0.0, greedy, drawn)

        # all-greedy batches (the server default) execute ONLY the argmax:
        # lax.cond skips the sort/softmax/categorical machinery at runtime
        return jax.lax.cond(
            jnp.all(temperature <= 0.0), lambda _: greedy, stochastic, None
        )

    return sample
