"""Continuous batching for KV-cached decoding: a fixed batch of SLOTS that
independent requests enter and leave without ever stopping the batch — the
serving pattern behind modern LLM inference engines, TPU-shaped:

- static shapes everywhere: the slot batch, per-slot caches
  (L, n_slots, S_max, H_kv, D) and positions are allocated once; a request
  entering/leaving never recompiles the step;
- one jitted decode step advances ALL active slots (per-slot positions via
  the same vmapped chunk forward speculative decoding uses); inactive
  slots compute a masked no-op — uniform work beats dynamic batch shapes
  on TPU;
- prefill writes a new request's prompt into its slot with one chunk
  forward, padded to the next power-of-two bucket so ONE compilation
  serves every prompt length in the bucket. Pad K/V entries are written
  past the true prompt length, but decode overwrites position p exactly
  when it first feeds the token at p — a real query at position p only
  ever attends positions <= p, all of which real tokens have re-written
  by then, so the pads are never read;
- the host-side loop only routes tokens and frees slots (EOS / length);
  no tensor work happens outside jit.

A drained slot is immediately reusable: its cache region is overwritten by
the next occupant's prefill, and every attention mask is position-bounded,
so stale entries are never read (same invariant as speculative decoding).

Reference: no inference stack exists in the reference (SURVEY.md §2) —
TPU-first extension.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubetpu.jobs.decode import forward_chunk, forward_chunk_at, init_kv_cache
from kubetpu.jobs.model import ModelConfig, Params


class DecodeServer:
    """Slot-based continuous batching over one model replica.

    ``submit(prompt)`` -> request id (or None when all slots are busy);
    ``step()`` advances every active request by one token and returns
    ``{request_id: token}``; ``finished(rid)``/``result(rid)`` collect
    completed sequences. ``max_new_tokens`` and optional ``eos_id`` bound
    each request.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params: Params,
        n_slots: int = 8,
        max_seq: int = 512,
        max_new_tokens: int = 64,
        eos_id: Optional[int] = None,
    ) -> None:
        self.cfg = cfg
        self.params = params
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id

        self.k_cache, self.v_cache = init_kv_cache(cfg, n_slots, max_seq)
        self.pos = jnp.zeros((n_slots,), jnp.int32)    # index of `last` token
        self.last = jnp.zeros((n_slots,), jnp.int32)   # last emitted token
        self.active = np.zeros((n_slots,), bool)       # host-side occupancy

        self._next_rid = 0
        self._slot_rid: List[Optional[int]] = [None] * n_slots
        self._prompts: Dict[int, List[int]] = {}
        self._emitted: Dict[int, List[int]] = {}
        self._done: Dict[int, bool] = {}

        cfg_ = cfg

        # donate_argnums=(1, 2): the caller overwrites self.k_cache/v_cache
        # with the results, so XLA updates the (large) cache buffers in
        # place instead of holding input+output copies live per step
        @partial(jax.jit, donate_argnums=(1, 2))
        def prefill_slot(params, k_cache, v_cache, prompt, slot, prompt_len):
            # single-sequence chunk forward at pos 0, written into `slot`;
            # `prompt` is bucket-padded (see module docstring) — only
            # prompt_len is real, and the last REAL position's logits pick
            # the first token
            k_s = jnp.take(k_cache, slot[None], axis=1)      # (L,1,S,Hkv,D)
            v_s = jnp.take(v_cache, slot[None], axis=1)
            logits, k_s, v_s = forward_chunk(
                cfg_, params, prompt[None], k_s, v_s, 0
            )
            k_cache = jax.lax.dynamic_update_slice(
                k_cache, k_s, (0, slot, 0, 0, 0)
            )
            v_cache = jax.lax.dynamic_update_slice(
                v_cache, v_s, (0, slot, 0, 0, 0)
            )
            first = jnp.argmax(
                jnp.take(logits[0], prompt_len - 1, axis=0)
            ).astype(jnp.int32)
            return k_cache, v_cache, first

        @partial(jax.jit, donate_argnums=(1, 2))
        def step_all(params, k_cache, v_cache, last, pos, active):
            logits, k_cache, v_cache = forward_chunk_at(
                cfg_, params, last[:, None], k_cache, v_cache, pos
            )
            nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            nxt = jnp.where(active, nxt, last)     # inactive slots hold
            pos = pos + active.astype(jnp.int32)
            return k_cache, v_cache, nxt, pos

        self._prefill_slot = prefill_slot
        self._step_all = step_all

    # -- request lifecycle ---------------------------------------------------

    def submit(self, prompt: List[int]) -> Optional[int]:
        """Admit a request into a free slot (None if the batch is full)."""
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) + self.max_new_tokens + 1 > self.max_seq:
            raise ValueError("prompt + max_new_tokens exceeds max_seq")
        free = [i for i in range(self.n_slots) if not self.active[i]]
        if not free:
            return None
        slot = free[0]
        rid = self._next_rid
        self._next_rid += 1

        # pad to the next power-of-two bucket (capped at max_seq) so one
        # compilation serves the whole bucket
        bucket = 1
        while bucket < len(prompt):
            bucket *= 2
        bucket = min(bucket, self.max_seq)
        padded = prompt + [0] * (bucket - len(prompt))
        self.k_cache, self.v_cache, first = self._prefill_slot(
            self.params, self.k_cache, self.v_cache,
            jnp.asarray(padded, jnp.int32), jnp.int32(slot),
            jnp.int32(len(prompt)),
        )
        self.pos = self.pos.at[slot].set(len(prompt))
        self.last = self.last.at[slot].set(first)
        self.active[slot] = True
        self._slot_rid[slot] = rid
        self._prompts[rid] = list(prompt)
        self._emitted[rid] = [int(first)]
        self._done[rid] = False
        self._retire_if_done(slot)
        return rid

    def step(self) -> Dict[int, int]:
        """One decode step for every active slot -> {request_id: new token}."""
        if not self.active.any():
            return {}
        self.k_cache, self.v_cache, nxt, self.pos = self._step_all(
            self.params, self.k_cache, self.v_cache, self.last, self.pos,
            jnp.asarray(self.active),
        )
        self.last = nxt
        tokens = np.asarray(nxt)
        out: Dict[int, int] = {}
        for slot in range(self.n_slots):
            if not self.active[slot]:
                continue
            rid = self._slot_rid[slot]
            tok = int(tokens[slot])
            self._emitted[rid].append(tok)
            out[rid] = tok
            self._retire_if_done(slot)
        return out

    def _retire_if_done(self, slot: int) -> None:
        rid = self._slot_rid[slot]
        emitted = self._emitted[rid]
        if len(emitted) >= self.max_new_tokens or (
            self.eos_id is not None and emitted[-1] == self.eos_id
        ):
            self._done[rid] = True
            self.active[slot] = False       # slot immediately reusable
            self._slot_rid[slot] = None

    # -- results -------------------------------------------------------------

    def finished(self, rid: int) -> bool:
        return self._done.get(rid, False)

    def result(self, rid: int) -> List[int]:
        """prompt + emitted tokens for a request (final once finished);
        retained until ``pop_result`` — a long-running server must pop."""
        return self._prompts[rid] + self._emitted[rid]

    def pop_result(self, rid: int) -> List[int]:
        """Collect AND evict a finished request's tokens — the bookkeeping
        for a request is dropped so an indefinitely-running server doesn't
        grow memory with every request ever served."""
        if not self._done.get(rid, False):
            raise KeyError(f"request {rid} is not finished")
        out = self._prompts.pop(rid) + self._emitted.pop(rid)
        del self._done[rid]
        return out

    def drain(self, max_steps: int = 10_000) -> None:
        """Run until every admitted request finishes."""
        for _ in range(max_steps):
            if not self.active.any():
                return
            self.step()
        raise RuntimeError("drain did not converge")
