"""Continuous batching for KV-cached decoding: a fixed batch of SLOTS that
independent requests enter and leave without ever stopping the batch — the
serving pattern behind modern LLM inference engines, TPU-shaped:

- static shapes everywhere: the slot batch, per-slot caches
  (L, n_slots, S_max, H_kv, D) and positions are allocated once; a request
  entering/leaving never recompiles the step;
- one jitted decode step advances ALL active slots (per-slot positions via
  the same vmapped chunk forward speculative decoding uses); inactive
  slots compute a masked no-op — uniform work beats dynamic batch shapes
  on TPU;
- prefill writes a new request's prompt into its slot with one chunk
  forward, padded to the next power-of-two bucket so ONE compilation
  serves every prompt length in the bucket. Pad K/V entries are written
  past the true prompt length, but decode overwrites position p exactly
  when it first feeds the token at p — a real query at position p only
  ever attends positions <= p, all of which real tokens have re-written
  by then, so the pads are never read;
- the host-side loop only routes tokens and frees slots (EOS / length);
  no tensor work happens outside jit;
- admission never blocks the caller or the dispatch pipeline (VERDICT r2
  weak #3): ``enqueue`` is pure host-side bookkeeping (returns
  immediately), and a queued request is admitted at the next step
  boundary with its first-token fetch DEFERRED — the admitting step
  dispatches the prefill and the decode back-to-back without a host sync
  between them, and materializes both results in one sync at token
  routing. On a single chip the device still executes prefill before
  that step's decode (the hardware is serial — the honest limit of
  "overlap" here); what the deferral removes is the host-side
  serialization, so an admission costs the step one prefill execution,
  not prefill + round-trip + decode. ``submit`` remains the synchronous
  spelling (admits and fetches immediately). ``warmup()`` pre-compiles
  every prompt bucket + the decode step so the first request of a bucket
  size never stalls the batch on a compile. Admission stall (the wall
  time a step pays to admit) is measured per admission and reported by
  ``metrics_summary``.

- chunked prefill under a TOKEN BUDGET (``prefill_budget``) kills
  head-of-line blocking: instead of one monolithic whole-prompt prefill
  at admission, each ``step()`` packs up to ``prefill_budget`` tokens of
  in-flight prompt CHUNKS (the Sarathi-Serve / vLLM discipline) through
  the same ``forward_chunk_io`` body decode uses, so a multi-thousand-
  token prompt never freezes the decode batch for more than one bounded
  chunk — the operator trades time-to-first-token against decode-stream
  p99 with one knob. Chunks are exact bucket-grid sizes (no padding
  except the single-chunk pos-0 case, which keeps the monolithic
  semantics), so a bounded set of compilations serves every prompt;
- ``overlap=True`` double-buffers the host loop: ``step()`` DISPATCHES
  step N+1 before MATERIALIZING step N's tokens, so the per-step
  blocking ``np.asarray`` host sync leaves the hot path — the device
  runs one step ahead of token routing. Emission (and therefore
  EOS/length retirement) lags one step; a retired slot's single
  in-flight token is discarded by the routing snapshot, and the one
  stray cache write it made lands at a position the next occupant
  overwrites before any read (the standard reuse invariant);
- sampling is REQUEST-DETERMINISTIC: the key for a request's token at
  position q is ``fold_in(fold_in(PRNGKey(seed), rid), q - 1)`` —
  sampled streams depend only on (seed, rid, position), never on batch
  composition, chunking, or step alignment, which is what makes the
  chunked server token-exact against the monolithic one under seeded
  sampling (pinned by test).

- the hot loop is UPLOAD-FREE in steady state (Round 10): the step legs'
  host-owned inputs — active mask, request keys, per-slot sampling
  settings, the multi-LoRA adapter ids, the paged server's page table —
  live in device-resident mirrors (``_dev``/``_invalidate_dev``)
  invalidated only by admission/retire/sampling/table changes, so a
  steady-state ``step()`` issues zero ``jnp.asarray`` uploads (pinned by
  regression test; greedy output is unchanged — only the upload moved);

- graceful degradation under overload: ``queue_ttl`` (server default) /
  ``enqueue(ttl=)`` (per request) bound the ADMISSION-QUEUE wait — a
  queued prompt past its deadline is expired (finished empty, reason
  readable via ``expire_reason`` and counted in ``metrics_summary`` as
  ``queue_expired``) instead of waiting forever behind a backlog.

A drained slot is immediately reusable: its cache region is overwritten by
the next occupant's prefill, and every attention mask is position-bounded,
so stale entries are never read (same invariant as speculative decoding).

``SlotServerBase`` holds the host-side request lifecycle (slots, request
ids, the admission queue, retire/EOS, metrics, results) shared with the
paged-cache server (``kubetpu.jobs.paged.PagedDecodeServer``) — a
lifecycle fix lands in both servers at once; subclasses provide only the
device legs (prefill, step, warmup).

Reference: no inference stack exists in the reference (SURVEY.md §2) —
TPU-first extension.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubetpu.core.metrics import LatencyRecorder
from kubetpu.obs.events import EventLog
from kubetpu.obs.profile import ServingProfiler
from kubetpu.obs.registry import Registry, install_process_gauges
from kubetpu.obs.slo import Objective, SloEngine
from kubetpu.jobs.decode import (
    _dense_cache_io,
    _int8_cache_io,
    forward_chunk_at_io,
    forward_chunk_io,
    init_kv_cache,
    init_kv_cache_int8,
)
from kubetpu.jobs.sampling import chosen_logprob
from kubetpu.jobs.model import ModelConfig, Params


class SlotServerBase:
    """Host-side continuous-batching lifecycle over ``n_slots`` slots.

    Subclass contract:
    - ``_admit_device(prompt, slot) -> Optional[(token, logprob)]``:
      reserve resources and prefill the WHOLE prompt; the first generated
      token and its raw-distribution logprob as device scalars, or None
      when resources are unavailable (the request stays queued — nothing
      may be mutated). The base spelling routes through the chunk leg;
    - ``_prefill_chunk_device(prompt, slot, pos, take, final) ->
      None | True | (token, logprob)``: prefill ``prompt[pos:pos+take]``
      into the slot's cache at position ``pos`` (``final`` marks the last
      chunk, which samples the first token). None = resources
      unavailable (nothing mutated; retried next step); True = chunk
      dispatched, more to come — the token-budget scheduler's leg;
    - ``_device_step() -> (tokens, logprobs)`` as DEVICE arrays: one
      decode step for all slots, updating device state; the base routes
      (and with ``overlap`` defers) the host materialization;
    - ``warmup()``: pre-compile; only valid while no request is active;
    - optional hooks ``_note_admitted(slot, prompt)``, ``_note_emitted
      (slot)``, ``_on_retire(slot)``, ``_bind_slot(rid, slot)``.
    """

    _min_bucket = 1

    def __init__(
        self,
        cfg: ModelConfig,
        params: Params,
        n_slots: int,
        max_seq: int,
        max_new_tokens: int,
        eos_id: Optional[int],
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        seed: int = 0,
        prefill_budget: int = 0,
        overlap: bool = False,
        queue_ttl: Optional[float] = None,
    ) -> None:
        self.cfg = cfg
        self.params = params
        # Per-request sampling: one compiled step serves every (temperature,
        # top_k, top_p) combination — the settings are traced per-slot
        # arrays, not baked constants (the samplers themselves live in the
        # shared compiled legs, _build_dense_legs/_build_paged_legs).
        # Server-level arguments are the defaults a request inherits
        # unless submit/enqueue overrides them.
        if temperature < 0:
            raise ValueError("temperature must be >= 0")
        if top_k is not None and top_k <= 0:
            raise ValueError("top_k must be positive (or None)")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")
        self._default_sampling = (
            float(temperature), int(top_k or 0), float(top_p or 1.0))
        self._slot_temp = np.full((n_slots,), temperature, np.float32)
        self._slot_topk = np.full((n_slots,), top_k or 0, np.int32)
        self._slot_topp = np.full((n_slots,), top_p or 1.0, np.float32)
        self._rid_sampling: Dict[int, Tuple[float, int, float]] = {}
        # request-deterministic sampling: per-slot REQUEST keys
        # (fold_in(base, rid)); the device legs fold the position in, so a
        # request's draws depend only on (seed, rid, position)
        self._base_key = jax.random.PRNGKey(seed)
        self._slot_reqkey = np.zeros((n_slots, 2), np.uint32)
        if prefill_budget < 0:
            raise ValueError("prefill_budget must be >= 0 (0 = monolithic)")
        self.prefill_budget = int(prefill_budget)
        self.overlap = bool(overlap)
        # token-budget scheduler state: slot -> in-flight prefill progress
        self._prefills: Dict[int, dict] = {}
        self._prefill_fifo: List[int] = []
        self._inflight = None          # overlap: the un-materialized step
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id

        self.pos = jnp.zeros((n_slots,), jnp.int32)    # index of `last` token
        self.last = jnp.zeros((n_slots,), jnp.int32)   # last emitted token
        self.active = np.zeros((n_slots,), bool)       # host-side occupancy

        self._next_rid = 0
        self._slot_rid: List[Optional[int]] = [None] * n_slots
        self._prompts: Dict[int, List[int]] = {}
        self._emitted: Dict[int, List[int]] = {}
        self._logprobs: Dict[int, List[float]] = {}
        self._done: Dict[int, bool] = {}
        # admission queue entries: (rid, prompt, deadline-or-None) — the
        # deadline is the graceful-degradation knob: under overload a
        # queued prompt past its TTL is EXPIRED (finished empty, reason
        # counted) instead of waiting forever
        if queue_ttl is not None and queue_ttl < 0:
            raise ValueError("queue_ttl must be >= 0 (None = no deadline)")
        self.queue_ttl = queue_ttl
        self._queue: List[Tuple[int, List[int], Optional[float]]] = []
        self._expired: Dict[int, str] = {}     # rid -> reason
        self._pending_first: Dict[int, object] = {}    # slot -> device scalar
        # -- live migration (Round-16): slots FROZEN mid-handoff (inactive
        # for the step legs but not reusable), streams that FINISHED here
        # by migrating away (rid -> new-owner info the wire layer reports
        # instead of tokens), and per-stream handoff identity — the
        # (origin replica, origin rid) pair and the handoff epoch the
        # target's fence compares (a stream born here has epoch 0 and no
        # origin until the wire layer names one)
        self._frozen: set = set()
        self._migrated: Dict[int, dict] = {}
        self._stream_epoch: Dict[int, int] = {}
        self._stream_origin: Dict[int, tuple] = {}
        # -- observability (Round-8): every histogram this server records
        # (admission stall, step, prefill chunks, and the per-request
        # TTFT / inter-token latency / queue wait) lives in ONE registry,
        # exposed as Prometheus text via ``metrics_text()`` (or over HTTP
        # through ``obs.exporter.MetricsServer``) and as the structured
        # ``metrics_summary()`` dict. Occupancy is collect-time gauges —
        # the hot loop pays nothing for them.
        self.obs = Registry()
        install_process_gauges(self.obs, "serving")
        # -- Round-11 signal layer: bounded structured event log (always
        # on — admission/retire/expiry are host bookkeeping, one dict
        # each), sampled profiler and SLO engine (both OFF by default;
        # ``enable_profiler`` / ``declare_slos`` opt in — the disabled
        # paths cost one ``is not None`` check per step, no syncs, no
        # uploads, pinned by regression test)
        self.events = EventLog(component="serving")
        self._profiler: Optional[ServingProfiler] = None
        self.slo: Optional[SloEngine] = None
        self._slo_interval = 1.0
        self._metrics = LatencyRecorder(
            registry=self.obs, metric="kubetpu_serving_latency_seconds")
        self.obs.gauge_fn("kubetpu_serving_active_slots",
                          lambda: int(self.active.sum()))
        self.obs.gauge_fn("kubetpu_serving_slots", lambda: self.n_slots)
        self.obs.gauge_fn("kubetpu_serving_queue_depth",
                          lambda: len(self._queue))
        self.obs.gauge_fn("kubetpu_serving_inflight_prefills",
                          lambda: len(self._prefills))
        self._arrive: Dict[int, float] = {}    # rid -> arrival perf stamp
        self._last_emit: Dict[int, float] = {}  # rid -> last emission stamp
        self._qw_recorded: set = set()         # rids with a queue_wait sample
        # -- hot-loop upload cache: device-resident mirrors of the host
        # slot state the step legs consume every step (active mask,
        # request keys, sampling settings; the paged server adds its page
        # table). The hot loop re-uploaded these unchanged arrays every
        # step; now a step issues zero ``jnp.asarray`` calls unless
        # admission / retirement / a sampling change dirtied a mirror
        # (pinned by regression test). Safe because no step leg donates
        # these arguments — the same device buffer serves every step.
        self._dev_cache: Dict[str, object] = {}
        self._dev_dirty: set = set()

    def _dev(self, name: str, fn):
        """Device-resident mirror of the host array ``fn()`` — uploaded
        once, then reused until ``_invalidate_dev(name)``. Mutation sites
        of the mirrored host state MUST invalidate, or the step reads a
        stale mirror (the invariant the upload-cache test pins)."""
        if name in self._dev_dirty or name not in self._dev_cache:
            # upload-on-miss IS this cache's job: steady-state steps hit
            # the cache and issue zero uploads (the Round-10 pinned
            # invariant KTP001 guards) # ktlint: disable=KTP001
            self._dev_cache[name] = jnp.asarray(fn())
            self._dev_dirty.discard(name)
        return self._dev_cache[name]

    def _invalidate_dev(self, *names: str) -> None:
        self._dev_dirty.update(names)

    def _request_key(self, rid: int) -> np.ndarray:
        """The request's sampling key: fold_in(PRNGKey(seed), rid)."""
        return np.asarray(jax.random.fold_in(self._base_key, rid))

    def _bind_slot(self, rid: int, slot: int) -> None:
        """Point the slot's traced per-slot arrays (sampling settings,
        request key) at *rid* — runs before ANY device leg touches the
        slot, on both the monolithic and the chunked admission path.
        Subclasses with more per-slot request state (multi-LoRA adapter
        ids) extend this."""
        temp, tk, tp = self._rid_sampling.get(rid, self._default_sampling)
        self._slot_temp[slot] = temp
        self._slot_topk[slot] = tk
        self._slot_topp[slot] = tp
        self._slot_reqkey[slot] = self._request_key(rid)
        self._invalidate_dev("reqkey", "temp", "topk", "topp")

    # -- multi-LoRA hooks (overridden by the multi_lora servers) --------------
    # On the BASE class so both cache layouts (DecodeServer's contiguous
    # cache AND PagedDecodeServer's pool) thread the same (stack, ids)
    # pair into their compiled legs — None/zeros is an empty pytree arg
    # with zero trace cost for the plain servers.

    def _admit_lora(self, slot: int):
        """(adapter stack, adapter id) for an admission — base: none."""
        return None, jnp.int32(0)

    def _step_lora(self):
        """(adapter stack, per-slot adapter ids) for a step — base: none."""
        return None, jnp.zeros((self.n_slots,), jnp.int32)

    def _drop_request_state(self, rid: int) -> None:
        """Subclass hook: drop any per-request bookkeeping keyed by *rid*
        (the multi-LoRA servers' adapter map). Called at EVERY path a
        request's bookkeeping dies through — ``pop_result``, ``cancel``
        (queued or active), and the queue-TTL expiry — so subclass state
        cannot leak on the paths that never reach ``pop_result``."""

    def _free_slots(self) -> List[int]:
        """Slots holding neither an active decode nor an in-flight
        prefill (nor a stream frozen mid-migration — inactive for the
        step legs, but its pages and bookkeeping are still live)."""
        return [i for i in range(self.n_slots)
                if not self.active[i] and i not in self._prefills
                and i not in self._frozen]

    # -- request lifecycle ---------------------------------------------------

    def _check_prompt(self, prompt: List[int]) -> None:
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) + self.max_new_tokens + 1 > self.max_seq:
            raise ValueError("prompt + max_new_tokens exceeds max_seq")

    def _bucket(self, n: int) -> int:
        # next power-of-two bucket (capped at max_seq) so one compilation
        # serves the whole bucket
        bucket = self._min_bucket
        while bucket < n:
            bucket *= 2
        return min(bucket, self.max_seq)

    def _try_admit(
        self, rid: int, prompt: List[int], slot: int, defer: bool = False
    ) -> bool:
        """Admission leg: device prefill + shared bookkeeping, timed as
        admission stall (what a step pays to take a request). With
        ``defer`` the first token stays ON DEVICE (no host sync) and is
        materialized by the next step's token routing — the step-boundary
        path, which must not serialize prefill-complete before the decode
        dispatch."""
        t0 = time.perf_counter()
        # slot sampling settings + request key BEFORE the prefill — it
        # samples the first token under them
        self._bind_slot(rid, slot)
        admitted = self._admit_device(prompt, slot)
        if admitted is None:
            return False
        self._record_queue_wait(rid, t0)
        first, first_lp = admitted
        self.pos = self.pos.at[slot].set(len(prompt))
        self.last = self.last.at[slot].set(first)
        self.active[slot] = True
        self._invalidate_dev("active")
        self._slot_rid[slot] = rid
        self._prompts[rid] = list(prompt)
        self._done[rid] = False
        self._note_admitted(slot, prompt)
        # admit BEFORE any first-token retire: a request finishing on its
        # very first token must still log admit -> retire in causal order
        self.events.emit("admit", rid=rid, slot=slot,
                         prompt_tokens=len(prompt), path="monolithic")
        if defer:
            self._emitted[rid] = []
            self._logprobs[rid] = []
            self._pending_first[slot] = (first, first_lp)
        else:
            self._emitted[rid] = [int(first)]
            self._logprobs[rid] = [float(first_lp)]
            self._obs_tokens(rid, 1)
            self._retire_if_done(slot)
        self._metrics.record("admission_stall", time.perf_counter() - t0)
        return True

    def _normalize_sampling(
        self, sampling: Optional[dict]
    ) -> Tuple[float, int, float]:
        if sampling is None:
            return self._default_sampling
        unknown = set(sampling) - {"temperature", "top_k", "top_p"}
        if unknown:
            raise ValueError(f"unknown sampling keys {sorted(unknown)}")
        d_temp, d_tk, d_tp = self._default_sampling
        # explicit falsy overrides are MEANINGFUL: top_k=0 / top_p=1.0 turn
        # the filter off for this request (None defers to the default)
        tk = sampling.get("top_k", d_tk)
        tp = sampling.get("top_p", d_tp)
        temp, tk, tp = (
            float(sampling.get("temperature", d_temp)),
            int(d_tk if tk is None else tk),
            float(d_tp if tp is None else tp),
        )
        if temp < 0:
            raise ValueError("temperature must be >= 0")
        if tk < 0:
            raise ValueError("top_k must be >= 0 (0 = off)")
        if not 0.0 < tp <= 1.0:
            raise ValueError("top_p must be in (0, 1]")
        return temp, tk, tp

    def submit(self, prompt: List[int],
               sampling: Optional[dict] = None) -> Optional[int]:
        """Admit into a free slot; None when slots (or, for the paged
        server, pool pages) are unavailable. Synchronous admission — the
        whole prompt prefills on the caller's clock even when
        ``prefill_budget`` is set; see ``enqueue`` for the non-blocking
        (and, with a budget, chunked) path. *sampling* overrides the
        server defaults for THIS request: a dict with any of temperature /
        top_k / top_p."""
        self._check_prompt(prompt)
        free = self._free_slots()
        if not free:
            return None
        rid = self._next_rid
        self._next_rid += 1
        self._rid_sampling[rid] = self._normalize_sampling(sampling)
        self._arrive[rid] = time.perf_counter()
        if not self._try_admit(rid, prompt, free[0]):
            self._next_rid -= 1
            del self._rid_sampling[rid]
            del self._arrive[rid]
            return None
        return rid

    def enqueue(self, prompt: List[int],
                sampling: Optional[dict] = None,
                ttl: Optional[float] = None) -> int:
        """Non-blocking admission: host-side bookkeeping ONLY — the caller
        never waits on a compile or a prefill. The request enters a slot at
        the next ``step`` boundary with one free (decode keeps emitting for
        active streams in the meantime). Always returns a request id.
        *sampling* as in ``submit``. *ttl* (seconds) bounds the QUEUE wait
        for this request (default: the server's ``queue_ttl``): past the
        deadline it is expired — finished with no tokens, reason counted
        (``expire_reason``/``metrics_summary``) — instead of waiting
        forever behind an overload."""
        self._check_prompt(prompt)
        rid = self._next_rid
        self._next_rid += 1
        self._rid_sampling[rid] = self._normalize_sampling(sampling)
        self._arrive[rid] = time.perf_counter()
        self._prompts[rid] = list(prompt)
        self._emitted[rid] = []
        self._logprobs[rid] = []
        self._done[rid] = False
        if ttl is None:
            ttl = self.queue_ttl
        deadline = None if ttl is None else time.monotonic() + ttl
        self._queue.append((rid, list(prompt), deadline))
        return rid

    def queued(self) -> int:
        """Requests enqueued but not yet admitted to a slot."""
        return len(self._queue)

    def expire_reason(self, rid: int) -> Optional[str]:
        """Why a request was expired ("queue_ttl"), or None for requests
        that were admitted (or are still waiting)."""
        return self._expired.get(rid)

    def _expire_queue(self) -> None:
        """Drop queued requests past their deadline — they finish EMPTY
        with a counted reason; a caller polling ``finished`` sees them
        complete and reads the reason instead of waiting forever."""
        if not self._queue:
            return
        now = time.monotonic()
        keep = []
        for rid, prompt, deadline in self._queue:
            if deadline is not None and now >= deadline:
                self._done[rid] = True
                self._expired[rid] = "queue_ttl"
                self._rid_sampling.pop(rid, None)
                self._arrive.pop(rid, None)  # no tokens ever: no TTFT
                self._drop_request_state(rid)  # never reaches pop_result
                self._metrics.record("queue_expired", now - deadline)
                self.events.emit("queue_expired", rid=rid)
            else:
                keep.append((rid, prompt, deadline))
        if len(keep) != len(self._queue):
            self._queue = keep

    def _record_queue_wait(self, rid: int, now: float) -> None:
        """One queue_wait sample per request, at its FIRST admission
        start — a deadlock-PARKED prefill re-entering the queue must not
        record a second, overlapping interval (the first already covers
        arrival -> first start)."""
        arrived = self._arrive.get(rid)
        if arrived is None or rid in self._qw_recorded:
            return
        self._qw_recorded.add(rid)
        self._metrics.record("queue_wait", now - arrived)

    def _obs_tokens(self, rid: int, n: int) -> None:
        """One emission event for *rid* (*n* tokens): the FIRST event
        records TTFT (arrival -> first token, host-observable wall time);
        later events record the inter-token latency, normalized by the
        event's token count so a speculative burst of k tokens reads as k
        tokens at gap/k, not one slow token."""
        now = time.perf_counter()
        last = self._last_emit.get(rid)
        if last is None:
            arrived = self._arrive.get(rid)
            if arrived is not None:
                self._metrics.record("ttft", now - arrived)
        elif n > 0:
            self._metrics.record("itl", (now - last) / n)
        self._last_emit[rid] = now

    def metrics_summary(self) -> dict:
        """{"admission_stall": {p50_ms, p90_ms, p99_ms, count},
        "step": {...}, "ttft": {...}, "itl": {...}, "queue_wait": {...},
        "queue_expired": {count, ...}} (the latter only once a TTL has
        expired a queued request). The same histograms render as
        Prometheus text via ``metrics_text``."""
        return self._metrics.summary()

    def metrics_text(self) -> str:
        """Prometheus exposition of this server's registry (latency
        summaries + occupancy gauges) — the text an
        ``obs.exporter.MetricsServer`` serves at ``/metrics``."""
        return self.obs.render()

    def load_info(self) -> dict:
        """The CHEAP load snapshot the data plane routes on (Round-14:
        ``kubetpu.router`` polls this as ``GET /load`` instead of
        parsing a full /metrics render per decision): host-side
        occupancy counters plus two bounded-reservoir percentile reads
        — no device sync, no exposition render. The percentiles are
        RECENT-window reads (``recent_percentile``), not lifetime: the
        autoscaler's hot signal feeds back into scaling decisions, and
        a lifetime p99 that never forgets one burst would latch "hot"
        forever (the SLO engine's windowed-percentile lesson).
        Subclasses extend with their pressure signals (the paged
        server adds pool pages and prefix-cache hit rate)."""
        return {
            "n_slots": self.n_slots,
            # frozen (mid-migration) slots COUNT as occupied: their
            # handoff has not resolved, so the capacity is genuinely
            # held — and the pool's drained() gate must never read a
            # replica idle while a transfer is still in flight (the
            # autoscaler would terminate the source before commit)
            "active_slots": int(self.active.sum()) + len(self._frozen),
            "migrating_slots": len(self._frozen),
            "queue_depth": len(self._queue),
            "inflight_prefills": len(self._prefills),
            "queue_wait_p99_ms": self._metrics.recent_percentile(
                "queue_wait", 99) * 1e3,
            "ttft_p50_ms": self._metrics.recent_percentile(
                "ttft", 50) * 1e3,
            # the DECODE-pool saturation signal (Round-17): a
            # disaggregated decode fleet scales on inter-token latency,
            # not admission-queue pressure (prompts never queue there)
            "itl_p99_ms": self._metrics.recent_percentile(
                "itl", 99) * 1e3,
        }

    def tier_stats(self) -> dict:
        """Tiered-KV-cache stats hook (Round-19): the base serving loop
        has no cache tiers, so this reports disabled — the paged server
        overrides with its per-tier hit/fill/spill counters and host
        occupancy. Replica ``/load`` and the CLI read through this one
        name regardless of server kind."""
        return {"enabled": False}

    # -- Round-11 signal layer ------------------------------------------------

    def enable_profiler(self, sample_every: int = 16) -> ServingProfiler:
        """Turn on the sampled continuous profiler (``obs.profile``):
        every *sample_every*-th ``step()`` records a per-phase wall
        breakdown (schedule / dispatch / device / materialize — the
        device phase costs that one step a ``block_until_ready``), and
        the compiled legs are wrapped for jit-recompile tracking
        (``kubetpu_jit_recompiles_total{leg=...}`` + compile seconds).
        Enable BEFORE ``warmup()`` to see the warmup compile storm
        attributed per leg. Un-sampled steps (and the default, disabled
        state) add zero device syncs and zero uploads."""
        prof = ServingProfiler(sample_every=sample_every, registry=self.obs)
        self._profiler = prof
        for attr, leg in (("_prefill_chunk", "prefill"),
                          ("_step_all", "step"),
                          ("_draft_prefill", "draft_prefill"),
                          ("_prefill_jit", "prefill"),
                          ("_round_jit", "round")):
            fn = getattr(self, attr, None)
            if fn is not None:
                setattr(self, attr, prof.watch(leg, fn))
        return prof

    def profile_summary(self) -> dict:
        """The profiler's structured snapshot (phase breakdown, coverage,
        per-leg recompiles) — {} while disabled."""
        return self._profiler.summary() if self._profiler else {}

    def declare_slos(self, objectives: List[Objective],
                     eval_interval: float = 1.0, **engine_kw) -> SloEngine:
        """Attach an SLO engine (``obs.slo``) over this server's own
        registry — ``obs.slo.serving_slos(...)`` builds the standard
        objective set. The engine re-evaluates at most once per
        *eval_interval* seconds, from inside ``step()`` (one monotonic
        read per step while declared); results render as
        ``kubetpu_slo_*`` gauges on ``metrics_text()`` and are readable
        via ``self.slo.results()``."""
        self.slo = SloEngine(objectives, registry=self.obs, **engine_kw)
        self._slo_interval = float(eval_interval)
        return self.slo

    def step(self) -> Dict[int, List[int]]:
        """Admit/advance prefills under the token budget (monolithic when
        ``prefill_budget == 0``; first-token fetch deferred either way),
        then one decode step for every active slot -> {rid: [tokens
        emitted this step]}. A request admitted from the queue THIS step
        emits two tokens (its prefill's first + this step's decode) — the
        list shape keeps both visible to streaming consumers. With
        ``overlap`` the decode materialization is DOUBLE-BUFFERED: this
        call dispatches step N and routes step N-1's tokens (decode
        emission lags one step; ``drain`` flushes the tail)."""
        prof = self._profiler
        rec = prof.begin_step() if prof is not None else None
        if self.slo is not None:
            self.slo.maybe_evaluate(self._slo_interval)
        self._schedule_prefills()
        if rec is not None:
            rec.mark("schedule")
        handle = None
        t0 = time.perf_counter()
        if self.active.any():
            handle = self._dispatch_step()
        if rec is not None:
            rec.mark("dispatch")
            if handle is not None:
                # the one sampled-step cost: wait for the dispatched leg
                # so device execution time is attributable (un-sampled
                # steps never sync here — the overlap pipeline is paused
                # for exactly this step, not defeated)
                jax.block_until_ready(handle[:2])  # ktlint: disable=KTP001
                rec.mark("device")
        if self.overlap:
            handle, self._inflight = self._inflight, handle
        out = self._materialize_pending()
        if handle is not None:
            self._route_step(handle, out)
        if rec is not None:
            rec.mark("materialize")
        if handle is not None or self._inflight is not None:
            self._metrics.record("step", time.perf_counter() - t0)
        if rec is not None:
            prof.end_step(rec)
        return out

    def _dispatch_step(self):
        """Dispatch one decode step; capture the (active, rid) snapshot
        the routing pass needs — under ``overlap`` the live tables may
        have moved on (retirement, re-admission) by the time the tokens
        are materialized, and a stale token must never reach a new
        occupant."""
        tokens, lps = self._device_step()
        return (tokens, lps, self.active.copy(), list(self._slot_rid))

    def _route_step(self, handle, out: Dict[int, List[int]]) -> None:
        """Materialize a dispatched step (the ONE host sync) and route its
        tokens by the dispatch-time snapshot. A token whose request has
        since retired or lost the slot is discarded — its stray cache
        write sits at a position the next occupant overwrites before any
        read (module docstring)."""
        tokens_d, lps_d, snap_active, snap_rids = handle
        tokens = np.asarray(tokens_d)
        lps = np.asarray(lps_d)
        for slot in range(self.n_slots):
            if not snap_active[slot]:
                continue
            rid = snap_rids[slot]
            if (rid is None or self._done.get(rid, True)
                    or self._slot_rid[slot] != rid):
                continue
            tok = int(tokens[slot])
            self._emitted[rid].append(tok)
            self._logprobs[rid].append(float(lps[slot]))
            self._note_emitted(slot)
            out.setdefault(rid, []).append(tok)
            self._obs_tokens(rid, 1)
            self._retire_if_done(slot)

    def _warmup_buckets(self, prefill_dummy) -> None:
        """Shared warmup skeleton: call *prefill_dummy(padded_prompt)* for
        every power-of-two prompt bucket from ``_min_bucket`` to
        ``max_seq`` — a bucketing change lands in every server at once."""
        assert (not self.active.any() and not self._queue
                and not self._prefills and self._inflight is None), (
            "warmup() must run before serving: it scribbles on slot 0's "
            "device state (and, for the paged server, on pool pages a "
            "mid-prefill slot may have mapped)"
        )
        bucket = self._min_bucket
        while True:
            dummy = [0] * min(bucket, self.max_seq)
            prefill_dummy(
                dummy + [0] * (self._chunk_bucket(0, len(dummy), True)
                               - len(dummy)))
            if bucket >= self.max_seq:
                break
            bucket *= 2

    def _drain_queue_into_slots(self) -> None:
        """Admit queued requests into free slots (resources permitting),
        first-token fetch deferred — the MONOLITHIC admission leg (whole
        prompt in one prefill), shared by every subclass's step. Expiry
        runs HERE too (not only in _schedule_prefills) so subclasses that
        call this leg directly (the speculative server) inherit the TTL."""
        self._expire_queue()
        while self._queue:
            free = self._free_slots()
            if not free:
                break
            rid, prompt, _deadline = self._queue[0]
            if not self._try_admit(rid, prompt, free[0], defer=True):
                break              # resources exhausted: retry next step
            self._queue.pop(0)

    # -- token-budget chunked prefill ----------------------------------------

    def _chunk_quantum(self) -> int:
        """Smallest chunk granularity (1 for contiguous caches; the page
        size for paged ones, so chunk starts stay page-aligned)."""
        return 1

    def _chunk_bucket(self, pos: int, take: int, final: bool) -> int:
        """Padded length of a prefill chunk: FINAL chunks bucket-pad,
        grid-exact when the pad would run past the cache end; non-final
        chunks are exact grid sizes. Subclasses reshape the rule (the
        paged server page-rounds), and warmup pads its dummies through
        this same hook — a warmed shape is exactly a served shape."""
        bucket = self._bucket(take) if final else take
        if pos + bucket > self.max_seq:
            bucket = take          # grid-exact tail: never overflows
        return bucket

    def _chunk_take(self, budget: int, pos: int, remaining: int) -> int:
        """Largest bucket-grid chunk (q * 2^k tokens) within
        min(max(budget, quantum), remaining) — grid-sized chunks keep the
        compilation set bounded, and at least one quantum always moves
        (the budget is a soft per-step bound). A TAIL that fits this
        step's allowance finishes NOW as one bucket-padded final chunk
        (pad K/V positions are dead by overwrite-before-read) instead of
        dribbling out as log2(tail) single-chunk steps — unless the pad
        would run past the cache end, where grid-exact fragmentation is
        the safe spelling."""
        q = self._chunk_quantum()
        cap = min(max(budget, q), remaining)
        take = q
        while take * 2 <= cap:
            take *= 2
        if (take < remaining and remaining <= max(budget, q)
                and pos + self._bucket(remaining) <= self.max_seq):
            return remaining       # final chunk, padded by the device leg
        return min(take, remaining)

    def _schedule_prefills(self) -> None:
        """The token-budget prefill scheduler: each step spends up to
        ``prefill_budget`` prompt tokens — first resuming in-flight
        chunked prefills (FIFO), then starting queued requests in free
        slots — so decode never waits more than one bounded chunk behind
        any prompt. ``prefill_budget == 0`` is the monolithic path."""
        self._expire_queue()   # graceful degradation: TTL'd waiters leave
        if self.prefill_budget <= 0:
            self._drain_queue_into_slots()
            return
        budget = self.prefill_budget
        progressed = False
        for slot in list(self._prefill_fifo):
            if budget <= 0:
                return
            used = self._advance_prefill(slot, budget)
            budget -= used
            progressed = progressed or used > 0
        while budget > 0 and self._queue:
            free = self._free_slots()
            if not free:
                break
            rid, prompt, deadline = self._queue.pop(0)
            self._begin_prefill(rid, prompt, free[0], deadline)
            used = self._advance_prefill(free[0], budget)
            budget -= used
            progressed = progressed or used > 0
        # Deadlock safeguard (paged pool pressure): several half-prefilled
        # slots can hold pages while none can take its next chunk and no
        # decoder is left to free any. Park every prefill but the oldest
        # back at the queue head (pages released, progress discarded) —
        # the oldest then owns the freed pool and completes.
        if (not progressed and len(self._prefills) > 1
                and not self.active.any()):
            for slot in list(self._prefill_fifo[1:])[::-1]:
                st = self._prefills[slot]
                # parked back with its ORIGINAL deadline: parking must not
                # grant a TTL'd request immortality
                self._queue.insert(
                    0, (st["rid"], st["prompt"], st["deadline"])
                )
                self._abort_prefill(slot)

    def _begin_prefill(self, rid: int, prompt: List[int], slot: int,
                       deadline: Optional[float] = None) -> None:
        """Occupy *slot* with a chunked prefill. Device resources are
        claimed chunk by chunk in ``_advance_prefill``. Progress starts at
        ``_prefill_start`` — 0 unless a subclass can reuse cached work
        (the paged server's prefix-cache hit maps shared pages and skips
        straight to the first uncached token). Once chunks start the TTL
        no longer applies (device work is under way); *deadline* is kept
        only so deadlock PARKING can re-queue the request without
        resetting its clock."""
        self._bind_slot(rid, slot)
        self._record_queue_wait(rid, time.perf_counter())
        self._slot_rid[slot] = rid        # cancel() finds mid-prefills
        self._done[rid] = False
        self._prefills[slot] = {
            "rid": rid, "prompt": list(prompt),
            "done": self._prefill_start(prompt, slot), "t": 0.0,
            "deadline": deadline,
        }
        self._prefill_fifo.append(slot)

    def _abort_prefill(self, slot: int) -> None:
        """Release a mid-prefill slot (deadlock parking): resources back
        via ``_on_retire``, slot free, NO result bookkeeping touched."""
        self._prefills.pop(slot, None)
        if slot in self._prefill_fifo:
            self._prefill_fifo.remove(slot)
        self._slot_rid[slot] = None
        self._on_retire(slot)

    def _advance_prefill(self, slot: int, budget: int) -> int:
        """Run one chunk of *slot*'s in-flight prefill (at most ~budget
        tokens; at least one quantum) -> tokens consumed. The FINAL chunk
        samples the request's first token and flips the slot to decoding
        with the first-token fetch deferred — from the decode batch's
        view a finishing prefill is indistinguishable from a monolithic
        admission."""
        st = self._prefills[slot]
        remaining = len(st["prompt"]) - st["done"]
        take = self._chunk_take(budget, st["done"], remaining)
        final = take >= remaining
        t0 = time.perf_counter()
        res = self._prefill_chunk_device(
            st["prompt"], slot, st["done"], take, final)
        if res is None:
            return 0               # resources unavailable: retry next step
        dt = time.perf_counter() - t0
        st["t"] += dt
        st["done"] += take
        self._metrics.record("prefill_chunk", dt)
        if final:
            rid = st["rid"]
            first, first_lp = res
            self.pos = self.pos.at[slot].set(len(st["prompt"]))
            self.last = self.last.at[slot].set(first)
            self.active[slot] = True
            self._invalidate_dev("active")
            self._note_admitted(slot, st["prompt"])
            self._pending_first[slot] = (first, first_lp)
            self._metrics.record("admission_stall", st["t"])
            self._prefills.pop(slot)
            self._prefill_fifo.remove(slot)
            self.events.emit("admit", rid=rid, slot=slot,
                             prompt_tokens=len(st["prompt"]),
                             path="chunked")
        return take

    def _prefill_chunk_device(self, prompt: List[int], slot: int, pos: int,
                              take: int, final: bool):
        """Subclass leg: prefill ``prompt[pos:pos+take]`` at position
        *pos* into *slot*'s cache. Returns None when resources are
        unavailable (nothing mutated), True for a dispatched non-final
        chunk, and the deferred (first token, logprob) device scalars for
        the final chunk."""
        raise NotImplementedError

    def _materialize_pending(self) -> Dict[int, List[int]]:
        """Fetch deferred first tokens (one sync AFTER the step's decode
        dispatch) and run their retire checks — a slot retired here (EOS
        on the first token / max_new_tokens == 1) drops out of the routing
        loop, discarding the step token it no longer needs."""
        out: Dict[int, List[int]] = {}
        for slot, (first, lp) in sorted(self._pending_first.items()):
            rid = self._slot_rid[slot]
            if rid is None:
                continue
            tok = int(np.asarray(first))
            self._emitted[rid] = [tok] + self._emitted[rid]
            self._logprobs[rid] = [float(np.asarray(lp))] + self._logprobs[rid]
            out.setdefault(rid, []).append(tok)
            self._obs_tokens(rid, 1)
            self._retire_if_done(slot)
        self._pending_first.clear()
        return out

    def _retire_if_done(self, slot: int) -> None:
        rid = self._slot_rid[slot]
        emitted = self._emitted[rid]
        if len(emitted) >= self.max_new_tokens or (
            self.eos_id is not None and emitted[-1] == self.eos_id
        ):
            self._retire(slot)

    def _retire(self, slot: int) -> None:
        rid = self._slot_rid[slot]
        self.events.emit("retire", rid=rid, slot=slot,
                         emitted=len(self._emitted.get(rid, ())))
        self._done[rid] = True
        self.active[slot] = False           # slot immediately reusable
        self._invalidate_dev("active")
        self._frozen.discard(slot)          # cancel() mid-migration
        self._slot_rid[slot] = None
        self._prefills.pop(slot, None)      # cancel() mid-prefill
        if slot in self._prefill_fifo:
            self._prefill_fifo.remove(slot)
        self._on_retire(slot)

    def cancel(self, rid: int) -> bool:
        """Stop a request wherever it is: queued requests are dropped, an
        active request's slot is freed (its cache region is dead until the
        next occupant's prefill overwrites it — the standard reuse
        invariant). Tokens emitted so far remain readable via ``result``;
        the request reports finished. Returns False for unknown/finished
        ids. A slot freed mid-step is handled like EOS retirement: the
        in-flight step's token for it is discarded by the routing loop.
        Result bookkeeping (prompt/emitted/logprobs) is retained until
        ``pop_result`` — same contract as ``result`` — so clients that
        cancel must still pop to reclaim memory; only the sampling params
        are evicted here (never consulted again once canceled)."""
        if self._done.get(rid, False) or rid not in self._prompts:
            return False
        for i, (qrid, _p, _d) in enumerate(self._queue):
            if qrid == rid:
                self._queue.pop(i)
                self._done[rid] = True
                self._rid_sampling.pop(rid, None)
                self._drop_request_state(rid)
                self.events.emit("cancel", rid=rid, queued=True)
                return True
        for slot in range(self.n_slots):
            if self._slot_rid[slot] == rid:
                if slot in self._frozen:
                    # mid-handoff: a cancel here races the in-flight
                    # wire transfer — the target could commit a live
                    # copy AFTER the local retire, breaking
                    # at-most-one-active. The handoff always resolves
                    # (commit-ack retires, refusal unfreezes); cancel
                    # again after it does.
                    return False
                # a deferred first token for this slot must not be routed
                # to the next occupant
                self._pending_first.pop(slot, None)
                self.events.emit("cancel", rid=rid, queued=False)
                self._retire(slot)
                self._rid_sampling.pop(rid, None)
                self._drop_request_state(rid)
                return True
        return False

    # hooks ------------------------------------------------------------------

    def _prefill_start(self, prompt: List[int], slot: int) -> int:
        """Position prefill should START at for a fresh admission into
        *slot* — 0 unless a subclass already holds the prefix's KV (the
        paged server's prefix cache maps shared pool pages read-only and
        returns the matched, page-aligned token count). Called once per
        admission attempt, after ``_bind_slot``, before any device leg.
        An implementation that maps resources here must release them in
        ``_on_retire`` (retire/abort both route through it)."""
        return 0

    def _note_admitted(self, slot: int, prompt: List[int]) -> None:
        pass

    def _note_emitted(self, slot: int) -> None:
        pass

    def _on_retire(self, slot: int) -> None:
        pass

    # -- live migration (Round-16) -------------------------------------------
    #
    # The HOST half of live KV migration: which streams may move, the
    # pause/resume dance around a wire handoff, and how a migrated-away
    # stream finishes locally. The page/cache half (snapshot_slot /
    # restore_slot) lives on the paged server — these legs are
    # cache-layout-free and shared with it. All of them are BARRIER legs
    # (never called from inside step(); they may sync and upload —
    # lint rule KTP001 classifies them so).

    def migratable_rids(self) -> List[int]:
        """Request ids whose stream may be snapshot NOW: actively
        decoding, not mid-(chunked-)prefill, first token materialized,
        not already frozen for another handoff. Migration happens only
        BETWEEN steps and only between rounds — a half-written prefill
        chunk has no token-exact resume point."""
        if self._inflight is not None:
            return []          # overlap pipeline holds an unrouted step
        out: List[int] = []
        for slot in range(self.n_slots):
            rid = self._slot_rid[slot]
            if (rid is None or not self.active[slot]
                    or slot in self._prefills
                    or slot in self._pending_first
                    or slot in self._frozen):
                continue
            out.append(rid)
        return out

    def prefill_progress(self, rid: int) -> "Optional[Tuple[int, int]]":
        """(prompt tokens prefilled so far, prompt length) for a request
        currently MID-chunked-prefill — None otherwise (queued, active,
        finished). Chunk starts are quantum-aligned (the paged server's
        page size), so every full page below the progress mark is final
        and will never be rewritten by a later chunk: the disaggregated
        handoff streamer (Round-17) reads this to know which page spans
        may ship while later chunks are still computing. A BARRIER leg —
        host bookkeeping reads only, never called from step()."""
        for st in self._prefills.values():
            if st["rid"] == rid:
                return int(st["done"]), len(st["prompt"])
        return None

    def freeze_slot(self, rid: int) -> None:
        """Pause *rid*'s slot for a handoff: inactive for the step legs
        (decode neither advances nor writes it — the masked no-op path),
        but NOT reusable and NOT idle. A frozen stream resumes exactly
        where it stopped (``unfreeze_slot``) or finishes by migrating
        (``finish_migrated``) — never both."""
        slot = self._slot_rid.index(rid)
        self._frozen.add(slot)
        self.active[slot] = False
        self._invalidate_dev("active")

    def unfreeze_slot(self, rid: int) -> None:
        """Resume a frozen stream after a DEFINITIVELY refused handoff —
        the stream continues here token-exactly (a paused slot's device
        state never moved). Tolerates a stream canceled mid-transfer."""
        try:
            slot = self._slot_rid.index(rid)
        except ValueError:
            return                 # canceled while the wire leg ran
        if slot in self._frozen:
            self._frozen.discard(slot)
            self.active[slot] = True
            self._invalidate_dev("active")

    def finish_migrated(self, rid: int, info: dict) -> None:
        """Source-side completion of a migrated stream: the slot frees
        exactly like a retire (pages released, prefix published), but
        the request FINISHES as migrated — result readers get the new
        owner (*info*: replica/rid/epoch, via ``migrated_to``) instead
        of tokens. Only call after the target's commit-ack (or on an
        AMBIGUOUS outcome, where resuming locally could double-run the
        stream — at-most-one-active beats finishing here)."""
        if rid in self._prompts:   # a canceled-AND-popped rid must not
            self._migrated[rid] = dict(info)   # leak an unpoppable entry
        try:
            slot = self._slot_rid.index(rid)
        except ValueError:
            return                 # canceled while the wire leg ran
        self.events.emit("migrate_out", rid=rid, slot=slot,
                         replica=info.get("replica"),
                         epoch=info.get("epoch"))
        self._retire(slot)

    def migrated_to(self, rid: int) -> Optional[dict]:
        """Where a migrated-away stream went ({replica, rid, epoch,
        ambiguous?}), or None for streams that finished here."""
        return self._migrated.get(rid)

    def cancel_expired(self, rid: int, reason: str) -> bool:
        """Cancel *rid* AND mark it expired with *reason* so the wire
        layer reports a retryable refusal (503, like a queue-TTL expiry)
        instead of returning partial tokens as success — the
        drain-timeout escalation's spelling."""
        if self._done.get(rid, False):
            return False
        self._expired[rid] = str(reason)
        ok = self.cancel(rid)
        if not ok:
            self._expired.pop(rid, None)
        return ok

    def unfinished_rids(self) -> List[int]:
        """Every request not yet finished — queued, mid-prefill, active
        or frozen — the set a drain timeout must resolve."""
        out: List[int] = [rid for rid, _p, _d in self._queue]
        out += [st["rid"] for st in self._prefills.values()]
        out += [r for r in self._slot_rid if r is not None]
        seen: set = set()
        uniq = []
        for r in out:
            if r not in seen and not self._done.get(r, False):
                seen.add(r)
                uniq.append(r)
        return uniq

    def snapshot_slot(self, rid: int, from_page: int = 0,
                      allow_frozen: bool = False) -> dict:
        """Base servers carry no shippable cache view: live migration
        is implemented by the PAGED servers (the page table is the
        portable representation). Raises NotImplementedError, which the
        wire layer's migrate AND disagg-handoff legs treat as a
        per-stream skip — a fleet of dense replicas degrades to
        wait-drain / local decode instead of crashing the transfer
        thread (the signature must match the paged one exactly, or the
        keyword call would raise TypeError past those handlers)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support live migration — "
            f"snapshot/restore ship the paged servers' page view")

    def restore_slot(self, snap: dict, reason: str = "migrate"):
        raise NotImplementedError(
            f"{type(self).__name__} does not support live migration — "
            f"snapshot/restore ship the paged servers' page view")

    def _snapshot_request(self, rid: int, slot: int) -> dict:
        """The cache-layout-free half of a slot snapshot: request
        bookkeeping (prompt, emitted, logprobs, sampling), the RAW
        request key (restore must reuse it verbatim — the target's own
        ``fold_in(seed, rid)`` would change every sampled draw), the
        device position/last-token pair, and the stream's handoff
        identity. The device reads here are the snapshot's designed
        sync — a barrier leg, never inside step()."""
        return {
            "version": 1,
            "prompt": [int(t) for t in self._prompts[rid]],
            "emitted": [int(t) for t in self._emitted[rid]],
            "logprobs": [float(x) for x in self._logprobs[rid]],
            "sampling": list(self._rid_sampling.get(
                rid, self._default_sampling)),
            "reqkey": [int(x) for x in self._slot_reqkey[slot]],
            "pos": int(np.asarray(self.pos)[slot]),
            "last": int(np.asarray(self.last)[slot]),
            "origin": (list(self._stream_origin[rid])
                       if rid in self._stream_origin else None),
            "epoch": int(self._stream_epoch.get(rid, 0)),
            "max_new_tokens": self.max_new_tokens,
            "eos_id": self.eos_id,
        }

    def _restore_request(self, snap: dict, slot: int) -> int:
        """Rebuild the request-state half of a restored slot -> the new
        LOCAL rid. The caller (``restore_slot``) owns page/cache
        restoration and activation ordering; this leg only installs
        bookkeeping + per-slot sampling state."""
        prompt = [int(t) for t in snap["prompt"]]
        emitted = [int(t) for t in snap["emitted"]]
        rid = self._next_rid
        self._next_rid += 1
        s = snap.get("sampling") or list(self._default_sampling)
        self._rid_sampling[rid] = (float(s[0]), int(s[1]), float(s[2]))
        now = time.perf_counter()
        self._arrive[rid] = now        # TTFT/ITL restart at the handoff:
        self._last_emit[rid] = now     # the blip is the honest number
        self._qw_recorded.add(rid)     # queue wait was paid at the source
        self._bind_slot(rid, slot)
        # the SOURCE's request key, verbatim: sampled continuation must
        # draw exactly what an unmigrated run would have drawn
        self._slot_reqkey[slot] = np.asarray(snap["reqkey"], np.uint32)
        self._invalidate_dev("reqkey")
        self._prompts[rid] = prompt
        self._emitted[rid] = emitted
        self._logprobs[rid] = [float(x) for x in snap.get("logprobs", [])]
        self._done[rid] = False
        self._slot_rid[slot] = rid
        self._stream_epoch[rid] = int(snap.get("epoch", 0))
        if snap.get("origin") is not None:
            self._stream_origin[rid] = tuple(snap["origin"])
        return rid

    # -- results -------------------------------------------------------------

    def finished(self, rid: int) -> bool:
        return self._done.get(rid, False)

    def result(self, rid: int) -> List[int]:
        """prompt + emitted tokens for a request (final once finished);
        retained until ``pop_result`` — a long-running server must pop."""
        return self._prompts[rid] + self._emitted[rid]

    def result_logprobs(self, rid: int) -> List[float]:
        """Model log-probability (log-softmax of the RAW logits, before
        any sampling filter) of each EMITTED token, parallel to the
        emitted part of ``result`` — the serving-API convention."""
        return list(self._logprobs[rid])

    def pop_result(self, rid: int) -> List[int]:
        """Collect AND evict a finished request's tokens — the bookkeeping
        for a request is dropped so an indefinitely-running server doesn't
        grow memory with every request ever served."""
        if not self._done.get(rid, False):
            raise KeyError(f"request {rid} is not finished")
        out = self._prompts.pop(rid) + self._emitted.pop(rid)
        del self._done[rid]
        self._rid_sampling.pop(rid, None)
        self._logprobs.pop(rid, None)
        self._expired.pop(rid, None)  # expiry reason is bookkeeping too
        self._arrive.pop(rid, None)   # observability stamps are too
        self._last_emit.pop(rid, None)
        self._qw_recorded.discard(rid)
        self._migrated.pop(rid, None)
        self._stream_epoch.pop(rid, None)
        self._stream_origin.pop(rid, None)
        self._drop_request_state(rid)
        return out

    def _runnable(self) -> bool:
        """A ``step()`` would advance something: active decodes, queued
        admissions, in-flight prefill chunks or an unflushed overlap
        step. A server whose ONLY remaining work is frozen (mid-
        migration) slots is NOT runnable — stepping it is a no-op, and
        a driver loop should sleep instead of spinning until the
        handoff resolves — but it is not idle either (``_idle``)."""
        return bool(self.active.any() or self._queue
                    or self._prefills or self._inflight is not None)

    def _idle(self) -> bool:
        """Nothing to do: no active decode, no queue, no in-flight
        prefill chunks, no un-materialized overlap step, no stream
        frozen mid-migration (its handoff has not resolved yet)."""
        return not self._runnable() and not self._frozen

    def drain(self, max_steps: int = 10_000) -> None:
        """Run until every admitted AND queued request finishes (flushing
        in-flight prefill chunks and the overlap pipeline)."""
        for _ in range(max_steps):
            if self._idle():
                return
            self.step()
        raise RuntimeError("drain did not converge")


# Default slot count for DecodeServer and its subclasses; subclasses that
# size per-slot state BEFORE super().__init__ (MultiLoraDecodeServer's
# adapter-id array) must read this, not repeat the literal.
DEFAULT_N_SLOTS = 8


# Compiled device legs shared across same-configuration servers: the legs
# are pure functions of their arguments (cfg/cache layout baked at build
# time), so two servers over the same key reuse ONE jit cache — spinning
# up another replica (or the parity-heavy test suite's Nth server) never
# recompiles. Keys are value-hashable (ModelConfig is a frozen
# dataclass); the cache lives for the process, like jit caches do.
_LEG_CACHE: Dict[tuple, tuple] = {}


def _cached_legs(key: tuple, builder):
    if key not in _LEG_CACHE:
        _LEG_CACHE[key] = builder()
    return _LEG_CACHE[key]


def _build_dense_legs(cfg_, cache_io, lora_scale):
    """(prefill_chunk, step_all) jits for the contiguous-cache server —
    see DecodeServer for the calling contract."""
    from kubetpu.jobs.sampling import make_slot_sampler

    sampler = make_slot_sampler()

    # donate_argnums=(1,): the caller overwrites self.cache with the
    # result, so XLA updates the (large) cache buffers in place
    # instead of holding input+output copies live per step.
    # The trailing (lora, aid/aids) pair is the multi-LoRA hook
    # (kubetpu.jobs.multi_lora): None/zeros for the plain server — an
    # empty pytree arg, zero trace cost.
    @partial(jax.jit, donate_argnums=(1,))
    def prefill_chunk(params, cache, chunk, slot, pos, last_idx,
                      reqkey, temp, tk, tp, lora, aid):
        # single-sequence chunk forward at *pos*, written into `slot`
        # — the monolithic prefill is the pos == 0 whole-prompt case
        # (chunk then bucket-padded; only last_idx + 1 is real and the
        # last REAL position's logits pick the first token). *pos* is
        # traced, so ONE compilation per chunk length serves every
        # offset a resumed prefill lands on.
        cache_s = jax.tree.map(
            lambda x: jnp.take(x, slot[None], axis=1), cache
        )  # every leaf: (L, 1, S, Hkv, D-or-1)
        logits, cache_s = forward_chunk_io(
            cfg_, params, chunk[None], cache_s, pos, cache_io,
            lora=lora, adapter_ids=None if lora is None else aid[None],
            lora_scale=lora_scale,
        )
        cache = jax.tree.map(
            lambda c, s: jax.lax.dynamic_update_slice(
                c, s, (0, slot, 0, 0, 0)
            ),
            cache, cache_s,
        )
        row = jnp.take(logits[0], last_idx, axis=0)
        # request-deterministic draw: the token at position q samples
        # under fold_in(request_key, q - 1), whatever the chunking
        first = sampler(row, jax.random.fold_in(reqkey, pos + last_idx),
                        temp, tk, tp)
        return cache, first, chosen_logprob(row, first)

    @partial(jax.jit, donate_argnums=(1,))
    def step_all(params, cache, last, pos, active, reqkeys,
                 temp, tk, tp, lora, aids):
        # INACTIVE slots must not scribble K/V at their stale pos: a
        # mid-prefill neighbor's already-written chunks live there
        # (the monolithic whole-prompt overwrite no longer protects
        # them). Redirect their write to S_max - 1 — never attended
        # before the decode step that rewrites it (the overwrite-
        # before-read invariant), so the row is provably dead.
        smax = jax.tree.leaves(cache)[0].shape[2]
        pos_w = jnp.where(active, pos, smax - 1)
        logits, cache = forward_chunk_at_io(
            cfg_, params, last[:, None], cache, pos_w, cache_io,
            lora=lora, adapter_ids=aids, lora_scale=lora_scale,
        )
        keys = jax.vmap(jax.random.fold_in)(reqkeys, pos)
        nxt = sampler(logits[:, 0], keys, temp, tk, tp)
        nxt = jnp.where(active, nxt, last)     # inactive slots hold
        lp = chosen_logprob(logits[:, 0], nxt)
        pos = pos + active.astype(jnp.int32)
        return cache, nxt, pos, lp

    return prefill_chunk, step_all


class DecodeServer(SlotServerBase):
    """Slot-based continuous batching over one model replica, with a
    contiguous per-slot KV cache in either layout: dense (``cfg.dtype``)
    or int8 with per-token per-head scales (``kv_int8=True`` — ~2x
    effective slot capacity, greedy token-exact on trained models). The
    device legs are cache-layout-blind (a pytree + ``cache_io``
    strategy); ``PagedDecodeServer`` is the pool-backed sibling.

    ``submit(prompt)`` -> request id (or None when all slots are busy);
    ``enqueue(prompt)`` -> request id, admitted at a step boundary;
    ``step()`` advances every active request and returns
    ``{request_id: [tokens emitted this step]}``;
    ``finished(rid)``/``result(rid)`` collect completed sequences.
    ``max_new_tokens`` and optional ``eos_id`` bound each request.

    ``prefill_budget=N`` turns on CHUNKED prefill for the queued
    (``enqueue``) path: each step spends at most ~N prompt tokens on
    prefill chunks interleaved with the decode batch, so a long prompt
    never blocks decoding for more than one chunk — token-exact vs the
    monolithic path (greedy AND seeded sampling; the sampling keys are
    request-deterministic). ``overlap=True`` double-buffers the host
    loop: step N+1 is dispatched before step N's tokens are materialized
    (emission lags one step; ``drain()`` flushes).
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params: Params,
        n_slots: int = DEFAULT_N_SLOTS,
        max_seq: int = 512,
        max_new_tokens: int = 64,
        eos_id: Optional[int] = None,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        seed: int = 0,
        mesh=None,
        kv_int8: bool = False,
        prefill_budget: int = 0,
        overlap: bool = False,
        queue_ttl: Optional[float] = None,
    ) -> None:
        super().__init__(cfg, params, n_slots, max_seq, max_new_tokens,
                         eos_id, temperature=temperature, top_k=top_k,
                         top_p=top_p, seed=seed,
                         prefill_budget=prefill_budget, overlap=overlap,
                         queue_ttl=queue_ttl)
        # The cache is a PYTREE + a cache_io strategy (decode.py's slot):
        # dense (k, v) or int8 ((kq, ks), (vq, vs)) — the server legs are
        # layout-blind. ``kv_int8=True`` stores the cache in int8 with
        # per-token per-head scales (~2x effective slot capacity at the
        # same HBM; greedy token-exact on trained models, test_quant.py).
        self.kv_int8 = kv_int8
        if kv_int8:
            self.cache = init_kv_cache_int8(cfg, n_slots, max_seq)
            cache_io = _int8_cache_io(cfg.window)
        else:
            self.cache = init_kv_cache(cfg, n_slots, max_seq)
            cache_io = _dense_cache_io(cfg.window)
        if mesh is not None:
            # Multi-chip serving: params tensor-parallel over tp (same
            # specs training uses — a trained checkpoint serves without a
            # resharding step), KV cache kv-heads on tp and slots on dp
            # (slots only when dp divides n_slots; otherwise replicated —
            # correctness never depends on the slot split). Committed input
            # shardings propagate through the donated jit legs, so every
            # step keeps the layout without per-call constraints. The int8
            # scale leaves share the spec (their head axis is axis 3 too).
            from jax.sharding import NamedSharding, PartitionSpec as P

            from kubetpu.jobs.decode import kv_cache_specs
            from kubetpu.jobs.train import _filter_spec, _shardings, param_specs

            self.params = jax.device_put(
                params, _shardings(mesh, param_specs(cfg)))
            cache_spec = kv_cache_specs()
            dp = mesh.shape.get("dp", 1)
            if n_slots % max(dp, 1):
                cache_spec = P(None, None, *cache_spec[2:])
            csh = NamedSharding(mesh, _filter_spec(mesh, cache_spec))
            self.cache = jax.tree.map(
                lambda x: jax.device_put(x, csh), self.cache
            )

        lora_scale = getattr(self, "_lora_scale", 1.0)
        self._prefill_chunk, self._step_all = _cached_legs(
            ("dense", cfg, kv_int8, float(lora_scale)),
            lambda: _build_dense_legs(cfg, cache_io, lora_scale),
        )

    @property
    def k_cache(self):
        """Dense-layout K cache array — kept for introspection/tests. The
        int8 layout has no single K array; read ``self.cache`` (the
        ((kq, ks), (vq, vs)) pytree) there instead of getting cache[0]'s
        tuple masquerading as an array."""
        if self.kv_int8:
            raise AttributeError(
                "kv_int8 server: no dense k_cache array — use self.cache"
            )
        return self.cache[0]

    @property
    def v_cache(self):
        if self.kv_int8:
            raise AttributeError(
                "kv_int8 server: no dense v_cache array — use self.cache"
            )
        return self.cache[1]

    # -- device legs ---------------------------------------------------------

    def _admit_device(self, prompt: List[int], slot: int):
        """Dispatch the whole-prompt prefill (one pos-0 chunk); returns
        the first token as a DEVICE scalar (no host sync — the defer path
        depends on it)."""
        return self._prefill_chunk_device(prompt, slot, 0, len(prompt), True)

    def _prefill_chunk_device(self, prompt: List[int], slot: int, pos: int,
                              take: int, final: bool):
        """One prefill chunk through the slot's cache rows. Non-final
        chunks are exact bucket-grid sizes (no padding); FINAL chunks
        bucket-pad (the monolithic pos-0 path, and the finish-the-tail
        rule of ``_chunk_take``) — pad K/V positions are dead by
        overwrite-before-read, and the pad never runs past the cache end
        (``_chunk_take`` only returns a paddable final; the clamp is a
        defensive spelling of the same bound)."""
        bucket = self._chunk_bucket(pos, take, final)
        chunk = prompt[pos:pos + take] + [0] * (bucket - take)
        lora, aid = self._admit_lora(slot)
        self.cache, first, first_lp = self._prefill_chunk(
            self.params, self.cache,
            jnp.asarray(chunk, jnp.int32), jnp.int32(slot),
            jnp.int32(pos), jnp.int32(take - 1),
            jnp.asarray(self._slot_reqkey[slot]),
            jnp.float32(self._slot_temp[slot]),
            jnp.int32(self._slot_topk[slot]),
            jnp.float32(self._slot_topp[slot]),
            lora, aid,
        )
        return (first, first_lp) if final else True

    def _device_step(self):
        # slot state flows through the device-resident upload cache
        # (SlotServerBase._dev): unchanged arrays are never re-uploaded,
        # so a steady-state step issues no host->device transfers beyond
        # the compiled call itself
        lora, aids = self._step_lora()
        self.cache, nxt, self.pos, lp = self._step_all(
            self.params, self.cache, self.last, self.pos,
            self._dev("active", lambda: self.active),
            self._dev("reqkey", lambda: self._slot_reqkey),
            self._dev("temp", lambda: self._slot_temp),
            self._dev("topk", lambda: self._slot_topk),
            self._dev("topp", lambda: self._slot_topp),
            lora, aids,
        )
        self.last = nxt
        return nxt, lp

    def warmup(self) -> None:
        """Pre-compile every prompt bucket's prefill and the decode step so
        no live request ever pays a compile (VERDICT r2: the first request
        of each bucket size blocked every active stream). Only valid while
        NO request is active: the dummy prefill rewrites slot 0's cache
        rows, which a live occupant still reads every step."""
        d_temp, d_tk, d_tp = self._default_sampling

        def prefill_dummy(padded):
            lora, aid = self._admit_lora(0)
            self.cache, _f, _lp = self._prefill_chunk(
                self.params, self.cache,
                jnp.asarray(padded, jnp.int32), jnp.int32(0), jnp.int32(0),
                jnp.int32(0), jnp.asarray(self._slot_reqkey[0]),
                jnp.float32(d_temp), jnp.int32(d_tk),
                jnp.float32(d_tp), lora, aid,
            )

        self._warmup_buckets(prefill_dummy)
        lora, aids = self._step_lora()
        self.cache, _nxt, _pos, _lps = self._step_all(
            self.params, self.cache, self.last, self.pos,
            jnp.asarray(np.zeros((self.n_slots,), bool)),
            jnp.asarray(self._slot_reqkey),
            jnp.asarray(self._slot_temp), jnp.asarray(self._slot_topk),
            jnp.asarray(self._slot_topp), lora, aids,
        )
        # drain the dispatch queue: without this the FIRST live admission
        # pays the wall time of every queued warmup execution and records
        # it as admission stall (seen as a ~1.3 s p99 outlier on the
        # tunneled backend, BENCH_MODEL.json serving row)
        jax.block_until_ready(self.cache)
