"""Ring attention: sequence-parallel attention over a device ring —
causal (decoder) by default, bidirectional (encoder) with ``causal=False``.

Long-context first-class support: the sequence axis is sharded across the
``sp`` mesh axis; each device holds one contiguous block of queries and
rotates the key/value blocks around the ring with ``lax.ppermute`` (one ICI
hop per step), accumulating a numerically-stable flash-style softmax
(running max + normalizer). Peak activation memory per chip stays
O(S/sp_size) while computing exact full causal attention — no approximation.

This is the TPU-native shape of the idea (jax collectives over ICI inside
``shard_map``), not a port: rotation is a static ``fori_loop`` of
``sp_size`` steps so XLA overlaps each hop's ppermute with the current
block's matmuls.

Causal structure across blocks (device i holds global query block i):
- source block j <  i : fully visible (no mask)
- source block j == i : local causal mask
- source block j >  i : fully masked (contributes nothing; with static
  control flow we still run the matmul — uniform steps beat a data-dependent
  branch on TPU)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def shard_map_compat(f, mesh, in_specs, out_specs, axis_names,
                     check_vma=False):
    """``jax.shard_map`` across jax versions: the promoted API where it
    exists, else ``jax.experimental.shard_map`` (``check_vma`` ->
    ``check_rep``). The legacy fallback runs FULL-manual rather than
    mapping ``axis_names`` onto the partial-auto ``auto=`` complement:
    old XLA fatally aborts (``IsManualSubgroup`` check) on ``ppermute``
    inside a partial-auto region, and our regions only ever reference
    their manual axes in the specs — unnamed axes are replicated either
    way, so the result is identical and merely loses the GSPMD
    auto-sharding of the replicated dims."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, axis_names=axis_names,
                             check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as legacy
    if mesh is None:
        raise ValueError(
            "mesh=None (use the context mesh) needs jax.shard_map; "
            "this jax version's shard_map requires an explicit mesh")
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)


def _ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool = True,
) -> jnp.ndarray:
    """Per-device body (runs under shard_map). q/k/v: (B, S_local, H, D).
    ``causal=False`` is the bidirectional (encoder) ring: every block is
    fully visible, so the mask machinery drops away entirely."""
    sp_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale = d ** -0.5

    q32 = q.astype(jnp.float32)
    local_pos = jnp.arange(s_local)

    def step(t, carry):
        o, m, l, k_blk, v_blk = carry
        # after t rotations (shift +1 each step) we hold block (my_idx - t)
        src_idx = (my_idx - t) % sp_size

        scores = jnp.einsum("bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)) * scale
        if causal:
            # blockwise causal mask in global positions
            q_pos = my_idx * s_local + local_pos
            k_pos = src_idx * s_local + local_pos
            mask = q_pos[:, None] >= k_pos[None, :]  # (S_local, S_local)
            scores = jnp.where(mask[None, None], scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))  # (B, H, Q)
        if causal:
            # exp under explicit mask: avoids exp(NEG_INF - NEG_INF) = 1
            # garbage on blocks where nothing is visible yet
            p = jnp.where(
                mask[None, None], jnp.exp(scores - m_new[..., None]), 0.0
            )
        else:
            p = jnp.exp(scores - m_new[..., None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1)
        o_new = o * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )

        # rotate k/v one hop around the ring (ICI neighbor exchange)
        perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return o_new, m_new, l_new, k_next, v_next

    o = jnp.zeros((b, h, s_local, d), jnp.float32)
    m = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_local), jnp.float32)
    o, m, l, _, _ = jax.lax.fori_loop(0, sp_size, step, (o, m, l, k, v))

    out = o / l[..., None]  # every query row sees at least itself (causal)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # (B, S_local, H, D)


def _ring_banded_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    window: int,
) -> jnp.ndarray:
    """Banded (sliding-window) sequence-parallel attention — the ring x
    window composition. With ``window <= S_local`` every query's band
    (the previous ``window`` positions including itself, the repo-wide
    convention) lies inside its OWN block plus the last ``window - 1``
    keys of the LEFT neighbor's block, so the full ``sp_size``-step ring
    rotation degenerates to ONE ``ppermute`` of that boundary tail: long-
    document training gets sequence parallelism AND O(window) attention
    in the same step. Device 0's incoming (wrapped) tail carries the
    sequence END's keys — masked out by global position, not by a branch
    (uniform SPMD steps).

    Exact banded softmax in f32 (stable max-subtraction); gradients flow
    through ``ppermute``'s transpose (the reverse hop) — no custom VJP
    needed at one step. Memory: O(S_local * (S_local + window)) scores —
    the band is materialized per block pair, which is fine at the
    window sizes that make windowed attention worth it."""
    sp_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    if window > s_local:
        raise ValueError(
            f"banded ring needs window <= S/sp ({window} > {s_local}): "
            f"lower sp, shorten the window, or use the full ring "
            f"(window=0)"
        )
    tail = window - 1  # how far a query reaches into the left block
    if tail > 0:
        perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]
        left_k = jax.lax.ppermute(k[:, s_local - tail :], axis_name, perm)
        left_v = jax.lax.ppermute(v[:, s_local - tail :], axis_name, perm)
        kk = jnp.concatenate([left_k, k], axis=1)
        vv = jnp.concatenate([left_v, v], axis=1)
    else:
        kk, vv = k, v
    scale = d ** -0.5
    q_pos = my_idx * s_local + jnp.arange(s_local)
    k_pos = my_idx * s_local - tail + jnp.arange(s_local + tail)
    diff = q_pos[:, None] - k_pos[None, :]
    # k_pos >= 0 kills device 0's wrapped tail (negative global positions)
    mask = (diff >= 0) & (diff < window) & (k_pos[None, :] >= 0)
    scores = (
        jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   kk.astype(jnp.float32)) * scale
    )
    scores = jnp.where(mask[None, None], scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.where(mask[None, None], jnp.exp(scores - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)  # >= 1 term: self is visible
    out = jnp.einsum("bhqk,bkhd->bqhd", p / l, vv.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Flash-in-ring: each ring step runs the Pallas flash kernels on the visiting
# K/V block instead of a dense S_local x S_local softmax — per-step score
# materialization drops from O(S_local^2) HBM to VMEM tiles, which is what
# lets local blocks grow to 8k+ under sequence parallelism. Block results
# merge through their log-sum-exp (exact, no approximation); the backward is
# its own ring: dK/dV accumulators travel WITH the rotating K/V block and
# arrive home fully summed, dQ accumulates locally — all through the fused
# FlashAttention-2 kernels with the GLOBAL lse (their P-recompute formulas
# are exact under a global lse, see ops.flash_attention._flash_backward).
# ---------------------------------------------------------------------------


def _merge_weights(w, b, h, s_local):
    """(B*H, S, 1) lse-space weight -> (B, S, H, 1) activation layout."""
    return w.reshape(b, h, s_local, 1).transpose(0, 2, 1, 3)


def _ring_flash_fwd_impl(q, k, v, axis_name, block_q, block_k, interpret,
                         causal=True):
    from kubetpu.ops.flash_attention import _flash_forward

    sp_size = jax.lax.psum(1, axis_name)  # static under shard_map
    my_idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape

    # step 0 is ALWAYS the diagonal block: causal kernel (bidirectional
    # rings run it unmasked), always visible
    o0, lse0 = _flash_forward(q, k, v, block_q, block_k, interpret,
                              causal=causal)

    def rotate(x):
        return jax.lax.ppermute(
            x, axis_name, [(i, (i + 1) % sp_size) for i in range(sp_size)]
        )

    def step(t, carry):
        o_acc, lse, k_blk, v_blk = carry
        k_blk = rotate(k_blk)
        v_blk = rotate(v_blk)
        # after t rotations we hold block (my_idx - t); causal rings see it
        # iff j < i, i.e. t <= my_idx (wrapped blocks are future
        # positions); bidirectional rings see every block
        visible = (t <= my_idx) if causal else jnp.bool_(True)
        o_t, lse_t = _flash_forward(
            q, k_blk, v_blk, block_q, block_k, interpret, causal=False
        )
        lse_t = jnp.where(visible, lse_t, NEG_INF)
        lse_new = jnp.logaddexp(lse, lse_t)
        w_old = _merge_weights(jnp.exp(lse - lse_new), b, h, s_local)
        w_new = _merge_weights(jnp.exp(lse_t - lse_new), b, h, s_local)
        o_acc = o_acc * w_old + o_t.astype(jnp.float32) * w_new
        return o_acc, lse_new, k_blk, v_blk

    o_acc, lse, _, _ = jax.lax.fori_loop(
        1, sp_size, step, (o0.astype(jnp.float32), lse0, k, v)
    )
    return o_acc.astype(q.dtype), lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis_name, block_q, block_k, interpret, causal=True):
    out, _lse = _ring_flash_fwd_impl(q, k, v, axis_name, block_q, block_k,
                                     interpret, causal)
    return out


def _ring_flash_vjp_fwd(q, k, v, axis_name, block_q, block_k, interpret,
                        causal=True):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, block_q, block_k,
                                    interpret, causal)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, block_q, block_k, interpret, causal, res, g):
    from kubetpu.ops.flash_attention import _flash_backward

    q, k, v, out, lse = res
    my_idx = jax.lax.axis_index(axis_name)
    sp_size = jax.lax.psum(1, axis_name)

    def rotate(x):
        return jax.lax.ppermute(
            x, axis_name, [(i, (i + 1) % sp_size) for i in range(sp_size)]
        )

    # diagonal step: causal kernels (unmasked for bidirectional rings),
    # contributions to MY home block
    dq0, dk0, dv0 = _flash_backward(
        q, k, v, out, lse, g, block_q, block_k, interpret, causal=causal
    )

    def step(t, carry):
        dq, k_blk, v_blk, dk_blk, dv_blk = carry
        # the (k, v, dk, dv) quad travels together around the ring
        k_blk = rotate(k_blk)
        v_blk = rotate(v_blk)
        dk_blk = rotate(dk_blk)
        dv_blk = rotate(dv_blk)
        visible = ((t <= my_idx) if causal else jnp.bool_(True)).astype(jnp.float32)
        dq_t, dk_t, dv_t = _flash_backward(
            q, k_blk, v_blk, out, lse, g, block_q, block_k, interpret,
            causal=False,
        )
        dq = dq + dq_t.astype(jnp.float32) * visible
        dk_blk = dk_blk + dk_t.astype(jnp.float32) * visible
        dv_blk = dv_blk + dv_t.astype(jnp.float32) * visible
        return dq, k_blk, v_blk, dk_blk, dv_blk

    dq, _k_home, _v_home, dk, dv = jax.lax.fori_loop(
        1, sp_size, step,
        (dq0.astype(jnp.float32), k, v,
         dk0.astype(jnp.float32), dv0.astype(jnp.float32)),
    )
    # after sp_size - 1 in-loop rotations the quad is ONE hop short of home:
    # complete the cycle so each device's dk/dv correspond to its own block
    dk = rotate(dk)
    dv = rotate(dv)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def make_ring_local(
    impl: str,
    axis_name: str = "sp",
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
    causal: bool = True,
    window: int = 0,
):
    """The per-device ring body (q, k, v) -> out, for callers that are
    ALREADY inside a manual region over *axis_name* (e.g. the pipeline's
    {pp, sp} region) — the single place the impl dispatch lives.
    ``causal=False`` gives the bidirectional (encoder) ring. ``window``
    > 0 selects the BANDED ring (one boundary ppermute instead of the
    full rotation — both impls share it; the band is too narrow for the
    flash kernels to pay for themselves)."""
    if impl not in ("dense", "flash"):
        raise ValueError(f"unknown ring impl {impl!r} (expected 'dense' or 'flash')")
    if window > 0:
        if not causal:
            raise ValueError("window > 0 requires causal attention")
        return partial(_ring_banded_local, axis_name=axis_name, window=window)
    if impl == "flash":
        return lambda q, k, v: _ring_flash(
            q, k, v, axis_name, block_q, block_k, interpret, causal
        )
    return partial(_ring_attention_local, axis_name=axis_name, causal=causal)


def make_ring_attention(
    mesh: "Mesh | None",
    axis_name: str = "sp",
    impl: str = "dense",
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
    causal: bool = True,
    window: int = 0,
):
    """An attention core (q, k, v) -> out with the sequence axis sharded over
    *axis_name*, drop-in for ``model.forward``'s ``attn_fn``.

    Partial-manual shard_map: only the ``sp`` axis is manual (the ring);
    batch/head shardings over dp/tp stay automatic GSPMD inside the region,
    so the same core composes under the plain GSPMD train step *and* inside
    the pipeline's pp-manual region — pass ``mesh=None`` when nesting inside
    another shard_map so the context (abstract) mesh is used.

    ``impl="flash"`` runs the Pallas flash kernels inside every ring step
    (VMEM-tiled scores instead of a dense per-step softmax; fused ring
    backward). ``interpret=True`` for CPU tests of the flash impl.
    ``causal=False`` is the bidirectional ring for long-context ENCODER
    stacks (and the seq2seq encoder): same rotation, no mask — drop-in for
    ``encoder_forward``'s ``attn_fn``. ``window > 0`` is the banded ring
    (sliding-window x sequence-parallel; one boundary ppermute).
    """
    specs = P(None, axis_name, None, None)
    local = make_ring_local(impl, axis_name, block_q, block_k, interpret,
                            causal, window=window)
    return shard_map_compat(
        lambda q, k, v: local(q, k, v),
        mesh=mesh,
        in_specs=(specs, specs, specs),
        out_specs=specs,
        axis_names={axis_name},
        check_vma=False,
    )
