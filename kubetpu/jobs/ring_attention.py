"""Ring attention: sequence-parallel causal attention over a device ring.

Long-context first-class support: the sequence axis is sharded across the
``sp`` mesh axis; each device holds one contiguous block of queries and
rotates the key/value blocks around the ring with ``lax.ppermute`` (one ICI
hop per step), accumulating a numerically-stable flash-style softmax
(running max + normalizer). Peak activation memory per chip stays
O(S/sp_size) while computing exact full causal attention — no approximation.

This is the TPU-native shape of the idea (jax collectives over ICI inside
``shard_map``), not a port: rotation is a static ``fori_loop`` of
``sp_size`` steps so XLA overlaps each hop's ppermute with the current
block's matmuls.

Causal structure across blocks (device i holds global query block i):
- source block j <  i : fully visible (no mask)
- source block j == i : local causal mask
- source block j >  i : fully masked (contributes nothing; with static
  control flow we still run the matmul — uniform steps beat a data-dependent
  branch on TPU)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
) -> jnp.ndarray:
    """Per-device body (runs under shard_map). q/k/v: (B, S_local, H, D)."""
    sp_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale = d ** -0.5

    q32 = q.astype(jnp.float32)
    local_pos = jnp.arange(s_local)

    def step(t, carry):
        o, m, l, k_blk, v_blk = carry
        # after t rotations (shift +1 each step) we hold block (my_idx - t)
        src_idx = (my_idx - t) % sp_size

        scores = jnp.einsum("bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)) * scale
        # blockwise causal mask in global positions
        q_pos = my_idx * s_local + local_pos
        k_pos = src_idx * s_local + local_pos
        mask = q_pos[:, None] >= k_pos[None, :]  # (S_local, S_local)
        scores = jnp.where(mask[None, None], scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))  # (B, H, Q)
        # exp under explicit mask: avoids exp(NEG_INF - NEG_INF) = 1 garbage
        # on blocks where nothing is visible yet
        p = jnp.where(
            mask[None, None], jnp.exp(scores - m_new[..., None]), 0.0
        )
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1)
        o_new = o * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )

        # rotate k/v one hop around the ring (ICI neighbor exchange)
        perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return o_new, m_new, l_new, k_next, v_next

    o = jnp.zeros((b, h, s_local, d), jnp.float32)
    m = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_local), jnp.float32)
    o, m, l, _, _ = jax.lax.fori_loop(0, sp_size, step, (o, m, l, k, v))

    out = o / l[..., None]  # every query row sees at least itself (causal)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # (B, S_local, H, D)


def make_ring_attention(mesh: "Mesh | None", axis_name: str = "sp"):
    """An attention core (q, k, v) -> out with the sequence axis sharded over
    *axis_name*, drop-in for ``model.forward``'s ``attn_fn``.

    Partial-manual shard_map: only the ``sp`` axis is manual (the ring);
    batch/head shardings over dp/tp stay automatic GSPMD inside the region,
    so the same core composes under the plain GSPMD train step *and* inside
    the pipeline's pp-manual region — pass ``mesh=None`` when nesting inside
    another shard_map so the context (abstract) mesh is used.
    """
    specs = P(None, axis_name, None, None)
    local = partial(_ring_attention_local, axis_name=axis_name)
    return jax.shard_map(
        lambda q, k, v: local(q, k, v),
        mesh=mesh,
        in_specs=(specs, specs, specs),
        out_specs=specs,
        axis_names={axis_name},
        check_vma=False,
    )
