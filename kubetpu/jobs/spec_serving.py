"""Speculative decoding inside the continuous-batching server.

``SpeculativeDecodeServer`` is ``serving.DecodeServer``'s request
lifecycle (slots, queue, deferred admission, retire/EOS) with the decode
step replaced by a speculative ROUND: a draft model proposes ``gamma``
tokens per slot, the target verifies them in one (gamma+1)-chunk cached
forward (``decode.forward_chunk_at`` — the same block implementation as
plain decoding), and each slot emits its longest agreeing prefix plus the
target's correction/bonus token. Per-slot positions diverge naturally
(slots accept different counts per round); rejected cache entries need no
rollback — positions rewind and the position-bounded attention mask never
reads them (``jobs.speculative``'s argument, per slot).

``PagedSpeculativeDecodeServer`` is the PRODUCTION-PATH sibling (Round
10): the same draft+verify rounds over ``paged.PagedDecodeServer``'s page
pool — the target's (gamma+1)-token verify chunk reads and writes THROUGH
the slot page table (``paged.paged_forward_chunk``), so speculation
composes with everything the pool already carries: chunked prefill,
kv_int8 pools, the fused Pallas paged-attention kernel
(``use_kernel=True`` — the verify chunk is the Round-15 chunk kernel,
in-kernel int8 dequant included), and shared-prefix radix-cache hits (a matched prefix skips
the DRAFT's prefill too — draft staleness there can only lower
acceptance, never change output, because verification is greedy-exact).
Copy-on-write boundary rules are untouched: every speculative write lands
at ``>= pos``, strictly past any shared prefix. Rounds add ADAPTIVE
GAMMA: a per-slot EMA of the acceptance rate walks each slot's gamma
within [1, gamma_max] (one jitted round per gamma value, all warmable);
the device round runs at the max over active slots and per-slot
acceptance is capped at the slot's own gamma, so a batch of
low-agreement slots stops paying for verify bandwidth it never converts.

Greedy only: speculative acceptance is exactly-greedy-equivalent, so both
servers' output is token-identical to their plain siblings' greedy stream
— the parity tests pin this (for the paged server: f32 + kv_int8, cold +
prefix-hit, chunked + monolithic admission). Sampling overrides are
rejected at admission.

The win is rounds, not tokens: decode is memory-bound, and the target's
weights stream once per ROUND instead of once per token; a slot with mean
acceptance a emits a+1 tokens per round. ``mean_tokens_per_round()``
reports the measured rate; the serving registry exports
``kubetpu_spec_rounds_total`` / ``kubetpu_spec_accepted_tokens_total`` /
``kubetpu_spec_proposed_tokens_total`` and (paged) a per-slot
``kubetpu_spec_gamma`` gauge.

Reference: none (the reference has no inference stack, SURVEY.md §2).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubetpu.jobs.decode import (
    _dense_cache_io,
    forward_chunk,
    forward_chunk_at,
    init_kv_cache,
)
from kubetpu.jobs.model import ModelConfig, Params
from kubetpu.jobs.paged import PagedDecodeServer, paged_forward_chunk
from kubetpu.jobs.sampling import chosen_logprob
from kubetpu.jobs.serving import SlotServerBase, _build_dense_legs, _cached_legs
from kubetpu.jobs.speculative import draft_and_verify, draft_propose

import time

# adaptive-gamma controller (PagedSpeculativeDecodeServer): the per-slot
# acceptance EMA walks gamma down when fewer than half the proposals
# convert and back up when nearly all do — one step per round, so a slot
# reaches gamma 1 from gamma_max within a handful of hopeless rounds
_EMA_ALPHA = 0.5
_GAMMA_UP = 0.85
_GAMMA_DOWN = 0.5


class _SpecRoundsMixin:
    """Tokens-per-round bookkeeping shared by the dense and paged
    speculative servers; the backing counters are zeroed by
    ``_init_spec_obs`` and fed by ``_route_round``."""

    def mean_tokens_per_round(self) -> float:
        """Measured accepted tokens per live (slot, round) — the speedup
        factor over one-token decoding for a memory-bound target."""
        return self._round_tokens / self._rounds if self._rounds else 0.0


def _init_spec_obs(server) -> None:
    """Speculation counters on the server's serving registry — shared by
    the dense and paged speculative servers so dashboards read one set of
    series: rounds executed, draft tokens proposed/accepted (acceptance
    rate = accepted/proposed), and the measured tokens-per-round."""
    server._rounds = 0
    server._round_tokens = 0
    server._c_spec_rounds = server.obs.counter(
        "kubetpu_spec_rounds_total", "device draft+verify rounds executed")
    server._c_spec_accepted = server.obs.counter(
        "kubetpu_spec_accepted_tokens_total",
        "draft tokens accepted by the target verifier")
    server._c_spec_proposed = server.obs.counter(
        "kubetpu_spec_proposed_tokens_total",
        "draft tokens proposed for verification")
    server.obs.gauge_fn("kubetpu_spec_mean_tokens_per_round",
                        server.mean_tokens_per_round)


def _route_round(server, toks, n_emit, lps, out):
    """Host-side routing of one device round's results, SHARED by the
    dense and paged speculative servers (a change to the clip/emit rules
    lands in both): agreement counters at DEVICE level before host
    clipping (the honest acceptance numerator/denominator for the obs
    series), room + EOS clipping, emit/logprob bookkeeping, retire.
    Server hooks supply the variance: ``_slot_proposed(slot)`` (constant
    gamma vs the slot's adaptive gamma) and ``_note_round_result`` (the
    paged server's adaptive-gamma controller)."""
    server._c_spec_rounds.inc()
    for slot in range(server.n_slots):
        if not server.active[slot]:
            continue
        rid = server._slot_rid[slot]
        n_dev = int(n_emit[slot])
        proposed = server._slot_proposed(slot)
        server._c_spec_proposed.inc(proposed)
        server._c_spec_accepted.inc(max(n_dev - 1, 0))
        server._note_round_result(slot, max(n_dev - 1, 0), proposed)
        accepted = [int(t) for t in toks[slot][:n_dev]]
        room = server.max_new_tokens - len(server._emitted[rid])
        accepted = accepted[:room]
        if server.eos_id is not None and server.eos_id in accepted:
            accepted = accepted[: accepted.index(server.eos_id) + 1]
        if not accepted:
            server._retire_if_done(slot)
            continue
        server._rounds += 1
        server._round_tokens += len(accepted)
        server._emitted[rid].extend(accepted)
        server._logprobs[rid].extend(
            float(x) for x in lps[slot][: len(accepted)])
        for _ in accepted:
            server._note_emitted(slot)   # paged: per-token host length
        out.setdefault(rid, []).extend(accepted)
        server._obs_tokens(rid, len(accepted))
        server._retire_if_done(slot)
    return out


class SpeculativeDecodeServer(_SpecRoundsMixin, SlotServerBase):
    """Continuous batching with draft+verify rounds (greedy-exact).

    ``target_cfg``/``draft_cfg`` must share a vocabulary; the draft is
    typically a few-layer shrink of the target. Public surface matches
    ``DecodeServer`` (submit/enqueue/step/drain/result), except sampling
    overrides are rejected (greedy only) and ``step`` may emit up to
    ``gamma + 1`` tokens per request.
    """

    def __init__(
        self,
        target_cfg: ModelConfig,
        draft_cfg: ModelConfig,
        target_params: Params,
        draft_params: Params,
        n_slots: int = 8,
        max_seq: int = 512,
        max_new_tokens: int = 64,
        eos_id: Optional[int] = None,
        gamma: int = 4,
        seed: int = 0,
        queue_ttl: Optional[float] = None,
    ) -> None:
        if target_cfg.vocab != draft_cfg.vocab:
            raise ValueError("target and draft must share a vocabulary")
        super().__init__(target_cfg, target_params, n_slots, max_seq,
                         max_new_tokens, eos_id, seed=seed,
                         queue_ttl=queue_ttl)
        self.draft_cfg = draft_cfg
        self.draft_params = draft_params
        self.gamma = gamma
        # margin: a round's verify chunk may write up to gamma tokens past
        # a sequence's final accepted position before the host retires it
        cache_len = max_seq + gamma + 1
        self.k_cache, self.v_cache = init_kv_cache(target_cfg, n_slots, cache_len)
        self.dk_cache, self.dv_cache = init_kv_cache(draft_cfg, n_slots, cache_len)
        _init_spec_obs(self)

        tcfg, dcfg = target_cfg, draft_cfg

        @partial(jax.jit, donate_argnums=(2, 3, 4, 5))
        def prefill_slot(t_params, d_params, tk, tv, dk, dv, prompt, slot,
                         prompt_len):
            # both models prefill the same bucket-padded prompt into their
            # slot rows; the target's last REAL position picks token 0
            k_s = jnp.take(tk, slot[None], axis=1)
            v_s = jnp.take(tv, slot[None], axis=1)
            t_logits, k_s, v_s = forward_chunk(tcfg, t_params, prompt[None],
                                               k_s, v_s, 0)
            tk = jax.lax.dynamic_update_slice(tk, k_s, (0, slot, 0, 0, 0))
            tv = jax.lax.dynamic_update_slice(tv, v_s, (0, slot, 0, 0, 0))

            kd = jnp.take(dk, slot[None], axis=1)
            vd = jnp.take(dv, slot[None], axis=1)
            _dl, kd, vd = forward_chunk(dcfg, d_params, prompt[None], kd, vd, 0)
            dk = jax.lax.dynamic_update_slice(dk, kd, (0, slot, 0, 0, 0))
            dv = jax.lax.dynamic_update_slice(dv, vd, (0, slot, 0, 0, 0))

            row = jnp.take(t_logits[0], prompt_len - 1, axis=0)
            first = jnp.argmax(row).astype(jnp.int32)
            return tk, tv, dk, dv, first, chosen_logprob(row, first)

        @partial(jax.jit, donate_argnums=(2, 3, 4, 5))
        def round_all(t_params, d_params, tk, tv, dk, dv, last, pos, active):
            # the round's device math is speculative.draft_and_verify —
            # ONE implementation for the batch generate loop and this
            # server; here we only add inactive-slot masking and logprobs
            tk, tv, dk, dv, target_tok, accepted, t_logits = draft_and_verify(
                tcfg, dcfg, gamma, t_params, d_params,
                tk, tv, dk, dv, last, pos,
            )
            n_emit = jnp.where(active, accepted + 1, 0)      # (B,)

            new_last = jnp.take_along_axis(
                target_tok, jnp.maximum(n_emit - 1, 0)[:, None], axis=1
            )[:, 0]
            new_last = jnp.where(active, new_last, last)
            new_pos = pos + n_emit
            lps = chosen_logprob(t_logits, target_tok)       # (B, gamma+1)
            return tk, tv, dk, dv, new_last, new_pos, target_tok, n_emit, lps

        self._prefill_jit = prefill_slot
        self._round_jit = round_all

    # -- device legs ---------------------------------------------------------

    def _normalize_sampling(self, sampling):
        if sampling is not None:
            raise ValueError(
                "SpeculativeDecodeServer is greedy-exact; per-request "
                "sampling is not supported"
            )
        return self._default_sampling

    def _admit_device(self, prompt: List[int], slot: int):
        bucket = self._bucket(len(prompt))
        padded = prompt + [0] * (bucket - len(prompt))
        (self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
         first, first_lp) = self._prefill_jit(
            self.params, self.draft_params,
            self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
            jnp.asarray(padded, jnp.int32), jnp.int32(slot),
            jnp.int32(len(prompt)),
        )
        return first, first_lp

    def _device_round(self):
        (self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
         self.last, self.pos, toks, n_emit, lps) = self._round_jit(
            self.params, self.draft_params,
            self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
            self.last, self.pos,
            self._dev("active", lambda: self.active),
        )
        # the round's ONE designed materialize: acceptance decides what
        # the host emits, so the round loop must read these — the exact
        # analogue of _route_step's sync # ktlint: disable=KTP001
        return np.asarray(toks), np.asarray(n_emit), np.asarray(lps)

    def _device_step(self):  # pragma: no cover — step() is overridden
        raise NotImplementedError("speculative serving steps in rounds")

    def step(self) -> Dict[int, List[int]]:
        """One speculative round for every active slot -> {rid: [tokens]};
        each request receives 1..gamma+1 tokens (clipped at EOS and
        max_new_tokens host-side; the device overshoot is never read)."""
        prof = self._profiler
        rec = prof.begin_step() if prof is not None else None
        if self.slo is not None:
            self.slo.maybe_evaluate(self._slo_interval)
        self._drain_queue_into_slots()
        if rec is not None:
            rec.mark("schedule")
        if not self.active.any():
            out = self._materialize_pending()
            if rec is not None:
                rec.mark("materialize")
                prof.end_step(rec)
            return out
        t0 = time.perf_counter()
        toks, n_emit, lps = self._device_round()
        if rec is not None:
            # _device_round materializes internally: dispatch + device +
            # fetch read as one "round" phase on this server
            rec.mark("round")
        out = self._materialize_pending()
        self._metrics.record("step", time.perf_counter() - t0)
        out = _route_round(self, toks, n_emit, lps, out)
        if rec is not None:
            rec.mark("materialize")
            prof.end_step(rec)
        return out

    def _slot_proposed(self, slot: int) -> int:
        return self.gamma            # fixed gamma: every slot proposes it

    def _note_round_result(self, slot: int, accepted: int,
                           proposed: int) -> None:
        pass                         # no adaptive controller here

    def warmup(self) -> None:
        """Pre-compile every prompt bucket's dual prefill and the round."""

        def prefill_dummy(padded):
            (self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
             _f, _lp) = self._prefill_jit(
                self.params, self.draft_params,
                self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
                jnp.asarray(padded, jnp.int32), jnp.int32(0), jnp.int32(1),
            )

        self._warmup_buckets(prefill_dummy)
        (self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
         _l, _p, _t, _n, _lps) = self._round_jit(
            self.params, self.draft_params,
            self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
            self.last, self.pos,
            jnp.asarray(np.zeros((self.n_slots,), bool)),
        )
        jax.block_until_ready((self.k_cache, self.v_cache))


def _build_paged_spec_round(tcfg, dcfg, gamma, dead_pos, attend_chunk=None,
                            lora_scale=1.0):
    """The jitted paged speculative ROUND for one static *gamma*: draft
    ``gamma`` greedy tokens through the (dense, per-slot) draft cache at
    per-slot positions (``speculative.draft_propose`` — the same
    implementation the dense server and the batch loop run), verify them
    in ONE (gamma+1)-token target chunk THROUGH the page pool
    (``paged.paged_forward_chunk``), and emit each slot's longest
    agreeing prefix plus the bonus/correction token, capped at the slot's
    own adaptive gamma (``slot_gamma``; the round runs at the batch max).

    *dead_pos*: the draft-cache row an INACTIVE slot's draft writes are
    redirected to — a mid-(chunked-)prefill slot is inactive but its
    draft rows already hold real prompt KV, so a stale-position write
    would corrupt them (the same hazard the dense step's ``pos_w``
    redirect covers); row ``dead_pos`` is past every position a real
    query can ever attend. The target side needs no redirect: inactive
    slots' pool writes are dropped via ``write_enable``.

    *attend_chunk* (``use_kernel``): the fused Pallas chunk kernel
    (``ops.paged_attention_chunk``) replaces the verify chunk's gather
    core — one compiled round per (gamma, kernel) signature, all warmed
    by ``warmup()`` through the profiler's per-gamma watch.

    The trailing (lora, aids) pair is the multi-LoRA hook: the TARGET's
    verify chunk applies each slot's adapter (``paged_forward_chunk``'s
    per-example deltas), so acceptance compares drafts against the
    TENANT's greedy stream. The draft stays adapterless — a base-model
    draft can only lower acceptance, never change output, because
    verification is greedy-exact (the prefix-hit argument, per tenant)."""

    # built lazily per gamma on first use, then cached (and warmup()
    # pre-compiles every gamma); the profiler's round[gamma=G] watch
    # counts any recompile this misses # ktlint: disable=KTP006
    @partial(jax.jit, donate_argnums=(2, 3, 4))
    def round_all(t_params, d_params, k_pages, v_pages, dcache,
                  table, last, pos, active, slot_gamma, lora, aids):
        dk, dv = dcache
        pos_d = jnp.where(active, pos, dead_pos)
        dk, dv, drafts = draft_propose(
            dcfg, gamma, d_params, dk, dv, last, pos_d)
        chunk = jnp.concatenate([last[:, None], drafts], axis=1)
        t_logits, k_pages, v_pages = paged_forward_chunk(
            tcfg, t_params, chunk, k_pages, v_pages, table, pos,
            write_enable=active, attend_chunk=attend_chunk,
            lora=lora, adapter_ids=aids, lora_scale=lora_scale,
        )
        target_tok = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
        agree = (drafts == target_tok[:, :gamma]).astype(jnp.int32)
        accepted = jnp.sum(jnp.cumprod(agree, axis=1), axis=1)   # (B,)
        # a slot whose adaptive gamma sits below the round's max emits at
        # most its OWN gamma of draft tokens — a prefix of an accepted
        # run is still exactly the target's greedy stream
        accepted = jnp.minimum(accepted, slot_gamma)
        n_emit = jnp.where(active, accepted + 1, 0)
        new_last = jnp.take_along_axis(
            target_tok, jnp.maximum(n_emit - 1, 0)[:, None], axis=1
        )[:, 0]
        new_last = jnp.where(active, new_last, last)
        new_pos = pos + n_emit
        lps = chosen_logprob(t_logits, target_tok)               # (B, g+1)
        return (k_pages, v_pages, (dk, dv), new_last, new_pos,
                target_tok, n_emit, lps)

    return round_all


class PagedSpeculativeDecodeServer(_SpecRoundsMixin, PagedDecodeServer):
    """Speculative draft+verify rounds over the PAGED KV pool — the
    production serving path (``PagedDecodeServer``: pool pages, chunked
    prefill, kv_int8, shared-prefix radix cache) with the one-token
    decode step replaced by a speculative round, greedy token-exact
    against its plain sibling.

    Composition rules (module docstring):

    - the verify chunk writes through the slot page table; every write
      lands at ``>= pos``, strictly past any read-only shared prefix, so
      the prefix cache's structural COW argument is untouched — hits,
      publication and reclamation all behave exactly as in the plain
      server, and ``check_invariants()`` is inherited unchanged;
    - a prefix-cache hit skips the DRAFT's prefill over the matched
      tokens too: the draft's dense cache simply starts at ``pos =
      matched_tokens`` with whatever its rows held before (zeros, or a
      previous occupant's KV). That can only lower acceptance — never
      change output — because verification is greedy-exact; the pinned
      hit-vs-cold parity test relies on exactly this;
    - page reservation extends by ``gamma_max`` positions per slot
      (``_seq_margin``): a round may write up to gamma tokens past the
      final accepted position, and those entries are never rolled back —
      positions rewind and the position-bounded mask never reads them;
    - ADAPTIVE GAMMA: per-slot EMA of the acceptance rate walks gamma in
      [1, gamma_max] (reset at admission); the device round runs at the
      max over active slots (one compiled round per gamma value — all
      warmed by ``warmup``) with per-slot acceptance capped at the
      slot's own gamma;
    - windowed (``cfg.window > 0``) configs are refused: the ring table
      aliases logical pages, and an overshoot write past the accepted
      position could evict a band entry a REWOUND position still needs;
    - ``use_kernel=True`` (Round-15) runs the verify chunk through the
      fused Pallas chunk kernel (``ops.paged_attention_chunk``): the
      (gamma+1)-token target read walks the page table in VMEM with
      in-kernel int8 dequant instead of materializing the gathered
      (and, for kv_int8, dequantized) cache — one compiled kernel round
      per gamma, all warmed by ``warmup()`` through the Round-11
      profiler watch, greedy token-exact vs the gather core (the
      interpret-mode storm and ``make spec-check`` kernel arms pin it);
    - greedy only (sampling overrides rejected) and no ``overlap`` (a
      round emits a variable burst; the one-step pipeline doesn't
      apply).
    """

    def __init__(
        self,
        target_cfg: ModelConfig,
        draft_cfg: ModelConfig,
        target_params: Params,
        draft_params: Params,
        n_slots: int = 8,
        max_seq: int = 512,
        max_new_tokens: int = 64,
        page_size: int = 16,
        n_pages: Optional[int] = None,
        eos_id: Optional[int] = None,
        seed: int = 0,
        mesh=None,
        kv_int8: bool = False,
        prefill_budget: int = 0,
        queue_ttl: Optional[float] = None,
        prefix_cache_pages: int = 0,
        gamma_max: int = 4,
        adaptive_gamma: bool = True,
        use_kernel: bool = False,
        interpret: bool = False,
        pages_per_block: int = 1,
    ) -> None:
        if target_cfg.vocab != draft_cfg.vocab:
            raise ValueError("target and draft must share a vocabulary")
        if target_cfg.window > 0 or draft_cfg.window > 0:
            raise NotImplementedError(
                "paged speculative serving does not compose with windowed "
                "configs: the ring table aliases logical pages, and a "
                "verify overshoot write could evict a band entry a rewound "
                "position still reads"
            )
        if gamma_max < 1:
            raise ValueError("gamma_max must be >= 1")
        # consumed by _seq_margin() during super().__init__ (table width
        # and worst-case reservations include the verify overshoot)
        self.gamma_max = int(gamma_max)
        self.adaptive_gamma = bool(adaptive_gamma)
        super().__init__(
            target_cfg, target_params, n_slots=n_slots, max_seq=max_seq,
            max_new_tokens=max_new_tokens, page_size=page_size,
            n_pages=n_pages, eos_id=eos_id, seed=seed, mesh=mesh,
            kv_int8=kv_int8, prefill_budget=prefill_budget,
            queue_ttl=queue_ttl, prefix_cache_pages=prefix_cache_pages,
            use_kernel=use_kernel, interpret=interpret,
            pages_per_block=pages_per_block,
        )
        self.draft_cfg = draft_cfg
        self.draft_params = draft_params
        # draft: a small DENSE per-slot cache spanning the TARGET's table
        # width, +1 for the dead row — any prefill bucket the base
        # clamp admits (pos + bucket <= table width) fits the draft
        # cache by construction (a non-page-aligned max_seq can round a
        # final chunk's bucket past max_seq + gamma_max), and the round's
        # deepest draft write (pos + gamma) stays strictly below the
        # dead row
        self._draft_len = self.max_pages_per_slot * page_size + 1
        self.dcache = init_kv_cache(draft_cfg, n_slots, self._draft_len)
        self._gamma = np.full((n_slots,), self.gamma_max, np.int32)
        self._accept_ema = np.ones((n_slots,), np.float64)
        _init_spec_obs(self)
        for s in range(n_slots):
            self.obs.gauge_fn("kubetpu_spec_gamma",
                              lambda s=s: float(self._gamma[s]),
                              slot=str(s))
        # draft prefill rides the SAME compiled dense legs a DecodeServer
        # over draft_cfg would use (shared process-wide leg cache)
        self._draft_prefill, _ = _cached_legs(
            ("dense", draft_cfg, False, 1.0),
            lambda: _build_dense_legs(
                draft_cfg, _dense_cache_io(draft_cfg.window), 1.0),
        )

    # -- adaptive gamma -------------------------------------------------------

    def _seq_margin(self) -> int:
        return self.gamma_max

    def _round_leg(self, gamma: int):
        lora_scale = getattr(self, "_lora_scale", 1.0)
        return _cached_legs(
            ("paged_spec", self.cfg, self.draft_cfg, self.page_size,
             self.kv_int8, gamma, self._draft_len - 1, self.use_kernel,
             self.interpret, self.pages_per_block, float(lora_scale)),
            lambda: _build_paged_spec_round(
                self.cfg, self.draft_cfg, gamma, self._draft_len - 1,
                attend_chunk=self._attend_chunk, lora_scale=lora_scale),
        )

    def _note_admitted(self, slot: int, prompt: List[int]) -> None:
        super()._note_admitted(slot, prompt)
        # every request starts optimistic at gamma_max; the EMA walks it
        # down within a few rounds if this stream disagrees with the draft
        if int(self._gamma[slot]) != self.gamma_max:
            self._gamma[slot] = self.gamma_max
            self._invalidate_dev("gamma")
        self._accept_ema[slot] = 1.0

    def _update_gamma(self, slot: int, accepted: int, proposed: int) -> None:
        if not self.adaptive_gamma:
            return
        frac = accepted / max(proposed, 1)
        ema = (1.0 - _EMA_ALPHA) * self._accept_ema[slot] + _EMA_ALPHA * frac
        self._accept_ema[slot] = ema
        g = int(self._gamma[slot])
        if ema >= _GAMMA_UP and g < self.gamma_max:
            self._gamma[slot] = g + 1
            self._invalidate_dev("gamma")
            self.events.emit("gamma", slot=slot, old=g, new=g + 1,
                             ema=round(ema, 3))
        elif ema < _GAMMA_DOWN and g > 1:
            self._gamma[slot] = g - 1
            self._invalidate_dev("gamma")
            self.events.emit("gamma", slot=slot, old=g, new=g - 1,
                             ema=round(ema, 3))

    def slot_gammas(self) -> List[int]:
        """Current per-slot adaptive gamma (the ``kubetpu_spec_gamma``
        gauge's values)."""
        return [int(g) for g in self._gamma]

    # -- request lifecycle ----------------------------------------------------

    def _normalize_sampling(self, sampling):
        if sampling is not None:
            raise ValueError(
                "PagedSpeculativeDecodeServer is greedy-exact; per-request "
                "sampling is not supported"
            )
        return self._default_sampling

    def _prefill_chunk_device(self, prompt: List[int], slot: int, pos: int,
                              take: int, final: bool):
        """Target chunk through the pool (inherited), then the SAME chunk
        into the draft's dense cache — both caches stay position-aligned
        whatever the admission path (monolithic, chunked, prefix-hit:
        a hit starts BOTH at ``pos = matched_tokens``)."""
        res = super()._prefill_chunk_device(prompt, slot, pos, take, final)
        if res is None:
            return None               # pool exhausted: nothing mutated
        bucket = self._chunk_bucket(pos, take, final)
        chunk = prompt[pos:pos + take] + [0] * (bucket - take)
        self.dcache, _first, _lp = self._draft_prefill(
            self.draft_params, self.dcache,
            jnp.asarray(chunk, jnp.int32), jnp.int32(slot),
            jnp.int32(pos), jnp.int32(take - 1),
            jnp.asarray(self._slot_reqkey[slot]),
            jnp.float32(0.0), jnp.int32(0), jnp.float32(1.0),
            None, jnp.int32(0),
        )
        return res

    def _device_step(self):  # pragma: no cover — step() is overridden
        raise NotImplementedError("paged speculative serving steps in rounds")

    def step(self) -> Dict[int, List[int]]:
        """One speculative round for every active slot -> {rid: [tokens]};
        each request receives 1..gamma+1 tokens (clipped at EOS and
        max_new_tokens host-side; the device overshoot is never read).
        Admission runs the base scheduler first — monolithic or
        token-budget chunked, both composing with prefix-cache hits."""
        prof = self._profiler
        rec = prof.begin_step() if prof is not None else None
        if self.slo is not None:
            self.slo.maybe_evaluate(self._slo_interval)
        self._schedule_prefills()
        if rec is not None:
            rec.mark("schedule")
        if not self.active.any():
            out = self._materialize_pending()
            if rec is not None:
                rec.mark("materialize")
                prof.end_step(rec)
            return out
        t0 = time.perf_counter()
        self._note_kernel_step()   # the verify chunk is a kernel leg too
        g = max(int(self._gamma[s]) for s in range(self.n_slots)
                if self.active[s])
        round_all = self._round_leg(g)
        if prof is not None:
            # compile tracking per gamma: an adaptive walk onto an
            # unwarmed gamma reads as a recompile on ITS leg, not a
            # mystery stall (watch is idempotent per leg name)
            round_all = prof.watch(f"round[gamma={g}]", round_all)
        lora, aids = self._step_lora()
        (self.k_pages, self.v_pages, self.dcache, self.last, self.pos,
         toks_d, n_emit_d, lps_d) = round_all(
            self.params, self.draft_params, self.k_pages, self.v_pages,
            self.dcache,
            self._dev("table", lambda: self._table), self.last, self.pos,
            self._dev("active", lambda: self.active),
            self._dev("gamma", lambda: self._gamma),
            lora, aids,
        )
        if rec is not None:
            rec.mark("dispatch")
            # sampled-step profiler sync only (same shape as the base
            # step's device mark) # ktlint: disable=KTP001
            jax.block_until_ready((toks_d, n_emit_d, lps_d))
            rec.mark("device")
        # the round's ONE designed materialize — rounds emit variable
        # bursts, so there is no overlap double-buffer to hide behind
        toks = np.asarray(toks_d)      # ktlint: disable=KTP001
        n_emit = np.asarray(n_emit_d)  # ktlint: disable=KTP001
        lps = np.asarray(lps_d)        # ktlint: disable=KTP001
        out = self._materialize_pending()
        self._metrics.record("step", time.perf_counter() - t0)
        out = _route_round(self, toks, n_emit, lps, out)
        if rec is not None:
            rec.mark("materialize")
            prof.end_step(rec)
        return out

    def _slot_proposed(self, slot: int) -> int:
        return int(self._gamma[slot])  # adaptive: the slot's own gamma

    def _note_round_result(self, slot: int, accepted: int,
                           proposed: int) -> None:
        self._update_gamma(slot, accepted, proposed)

    # -- live KV migration (Round-16) -----------------------------------------

    def _migration_kind(self) -> str:
        return "paged_spec"

    def snapshot_slot(self, rid: int, from_page: int = 0,
                      allow_frozen: bool = False) -> dict:
        """The paged snapshot plus the speculative controller's state:
        the slot's adaptive gamma and acceptance EMA survive the handoff
        (a migrated low-agreement stream must not restart optimistic at
        gamma_max and re-pay the walk down). The draft's dense cache
        rows do NOT ship: stale draft KV on the target can only lower
        acceptance, never change output — verification is greedy-exact
        (the prefix-hit argument, applied to migration)."""
        snap = super().snapshot_slot(rid, from_page=from_page,
                                     allow_frozen=allow_frozen)
        slot = self._slot_rid.index(rid)
        snap["draft_fp"] = repr(self.draft_cfg)
        snap["spec"] = {
            "gamma": int(self._gamma[slot]),
            "accept_ema": float(self._accept_ema[slot]),
        }
        return snap

    def restore_slot(self, snap: dict, reason: str = "migrate"):
        if snap.get("draft_fp") != repr(self.draft_cfg):
            raise ValueError(
                "snapshot draft config does not match this server's — "
                "migration requires config-identical replicas")
        rid = super().restore_slot(snap, reason=reason)
        if rid is None:
            return None
        spec = snap.get("spec") or {}
        slot = self._slot_rid.index(rid)
        # _note_admitted (via super) reset the controller optimistic;
        # the snapshot's walked-down state wins
        g = min(max(int(spec.get("gamma", self.gamma_max)), 1),
                self.gamma_max)
        if int(self._gamma[slot]) != g:
            self._gamma[slot] = g
            self._invalidate_dev("gamma")
        self._accept_ema[slot] = float(spec.get("accept_ema", 1.0))
        return rid

    def warmup(self) -> None:
        """Base warmup (target prompt buckets + chunked signatures + the
        one-token step; flushes the prefix tree), then the draft's
        buckets and EVERY round gamma the adaptive controller can pick —
        a round compile mid-serving is exactly the stall warmup exists to
        prevent."""
        super().warmup()
        d_temp, d_tk, d_tp = self._default_sampling

        def draft_dummy(padded):
            self.dcache, _f, _lp = self._draft_prefill(
                self.draft_params, self.dcache,
                jnp.asarray(padded, jnp.int32), jnp.int32(0), jnp.int32(0),
                jnp.int32(0), jnp.asarray(self._slot_reqkey[0]),
                jnp.float32(d_temp), jnp.int32(d_tk), jnp.float32(d_tp),
                None, jnp.int32(0),
            )

        self._warmup_buckets(draft_dummy)
        gammas = (range(1, self.gamma_max + 1) if self.adaptive_gamma
                  else (self.gamma_max,))
        idle = jnp.asarray(np.zeros((self.n_slots,), bool))
        lora, aids = self._step_lora()
        for g in gammas:
            round_all = self._round_leg(g)
            if self._profiler is not None:
                # warm up THROUGH the same watch wrapper step() uses:
                # the warmup compile is attributed to its gamma leg, and
                # the first live round at this gamma (same signature) is
                # NOT falsely booked as a serving-time recompile
                round_all = self._profiler.watch(
                    f"round[gamma={g}]", round_all)
            (self.k_pages, self.v_pages, self.dcache,
             _l, _p, _t, _n, _lps) = round_all(
                self.params, self.draft_params, self.k_pages, self.v_pages,
                self.dcache,
                self._dev("table", lambda: self._table), self.last, self.pos,
                idle, self._dev("gamma", lambda: self._gamma),
                lora, aids,
            )
        jax.block_until_ready((self.k_pages, self.v_pages))
