"""Speculative decoding inside the continuous-batching server.

``SpeculativeDecodeServer`` is ``serving.DecodeServer``'s request
lifecycle (slots, queue, deferred admission, retire/EOS) with the decode
step replaced by a speculative ROUND: a draft model proposes ``gamma``
tokens per slot, the target verifies them in one (gamma+1)-chunk cached
forward (``decode.forward_chunk_at`` — the same block implementation as
plain decoding), and each slot emits its longest agreeing prefix plus the
target's correction/bonus token. Per-slot positions diverge naturally
(slots accept different counts per round); rejected cache entries need no
rollback — positions rewind and the position-bounded attention mask never
reads them (``jobs.speculative``'s argument, per slot).

Greedy only: speculative acceptance is exactly-greedy-equivalent, so the
server's output is token-identical to ``DecodeServer``'s greedy stream —
the parity test pins this. Sampling overrides are rejected at admission.

The win is rounds, not tokens: decode is memory-bound, and the target's
weights stream once per ROUND instead of once per token; a slot with mean
acceptance a emits a+1 tokens per round. ``mean_tokens_per_round()``
reports the measured rate.

Reference: none (the reference has no inference stack, SURVEY.md §2).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubetpu.jobs.decode import forward_chunk, forward_chunk_at, init_kv_cache
from kubetpu.jobs.model import ModelConfig, Params
from kubetpu.jobs.sampling import chosen_logprob
from kubetpu.jobs.serving import SlotServerBase
from kubetpu.jobs.speculative import draft_and_verify

import time


class SpeculativeDecodeServer(SlotServerBase):
    """Continuous batching with draft+verify rounds (greedy-exact).

    ``target_cfg``/``draft_cfg`` must share a vocabulary; the draft is
    typically a few-layer shrink of the target. Public surface matches
    ``DecodeServer`` (submit/enqueue/step/drain/result), except sampling
    overrides are rejected (greedy only) and ``step`` may emit up to
    ``gamma + 1`` tokens per request.
    """

    def __init__(
        self,
        target_cfg: ModelConfig,
        draft_cfg: ModelConfig,
        target_params: Params,
        draft_params: Params,
        n_slots: int = 8,
        max_seq: int = 512,
        max_new_tokens: int = 64,
        eos_id: Optional[int] = None,
        gamma: int = 4,
        seed: int = 0,
        queue_ttl: Optional[float] = None,
    ) -> None:
        if target_cfg.vocab != draft_cfg.vocab:
            raise ValueError("target and draft must share a vocabulary")
        super().__init__(target_cfg, target_params, n_slots, max_seq,
                         max_new_tokens, eos_id, seed=seed,
                         queue_ttl=queue_ttl)
        self.draft_cfg = draft_cfg
        self.draft_params = draft_params
        self.gamma = gamma
        # margin: a round's verify chunk may write up to gamma tokens past
        # a sequence's final accepted position before the host retires it
        cache_len = max_seq + gamma + 1
        self.k_cache, self.v_cache = init_kv_cache(target_cfg, n_slots, cache_len)
        self.dk_cache, self.dv_cache = init_kv_cache(draft_cfg, n_slots, cache_len)
        self._rounds = 0
        self._round_tokens = 0

        tcfg, dcfg = target_cfg, draft_cfg

        @partial(jax.jit, donate_argnums=(2, 3, 4, 5))
        def prefill_slot(t_params, d_params, tk, tv, dk, dv, prompt, slot,
                         prompt_len):
            # both models prefill the same bucket-padded prompt into their
            # slot rows; the target's last REAL position picks token 0
            k_s = jnp.take(tk, slot[None], axis=1)
            v_s = jnp.take(tv, slot[None], axis=1)
            t_logits, k_s, v_s = forward_chunk(tcfg, t_params, prompt[None],
                                               k_s, v_s, 0)
            tk = jax.lax.dynamic_update_slice(tk, k_s, (0, slot, 0, 0, 0))
            tv = jax.lax.dynamic_update_slice(tv, v_s, (0, slot, 0, 0, 0))

            kd = jnp.take(dk, slot[None], axis=1)
            vd = jnp.take(dv, slot[None], axis=1)
            _dl, kd, vd = forward_chunk(dcfg, d_params, prompt[None], kd, vd, 0)
            dk = jax.lax.dynamic_update_slice(dk, kd, (0, slot, 0, 0, 0))
            dv = jax.lax.dynamic_update_slice(dv, vd, (0, slot, 0, 0, 0))

            row = jnp.take(t_logits[0], prompt_len - 1, axis=0)
            first = jnp.argmax(row).astype(jnp.int32)
            return tk, tv, dk, dv, first, chosen_logprob(row, first)

        @partial(jax.jit, donate_argnums=(2, 3, 4, 5))
        def round_all(t_params, d_params, tk, tv, dk, dv, last, pos, active):
            # the round's device math is speculative.draft_and_verify —
            # ONE implementation for the batch generate loop and this
            # server; here we only add inactive-slot masking and logprobs
            tk, tv, dk, dv, target_tok, accepted, t_logits = draft_and_verify(
                tcfg, dcfg, gamma, t_params, d_params,
                tk, tv, dk, dv, last, pos,
            )
            n_emit = jnp.where(active, accepted + 1, 0)      # (B,)

            new_last = jnp.take_along_axis(
                target_tok, jnp.maximum(n_emit - 1, 0)[:, None], axis=1
            )[:, 0]
            new_last = jnp.where(active, new_last, last)
            new_pos = pos + n_emit
            lps = chosen_logprob(t_logits, target_tok)       # (B, gamma+1)
            return tk, tv, dk, dv, new_last, new_pos, target_tok, n_emit, lps

        self._prefill_jit = prefill_slot
        self._round_jit = round_all

    # -- device legs ---------------------------------------------------------

    def _normalize_sampling(self, sampling):
        if sampling is not None:
            raise ValueError(
                "SpeculativeDecodeServer is greedy-exact; per-request "
                "sampling is not supported"
            )
        return self._default_sampling

    def _admit_device(self, prompt: List[int], slot: int):
        bucket = self._bucket(len(prompt))
        padded = prompt + [0] * (bucket - len(prompt))
        (self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
         first, first_lp) = self._prefill_jit(
            self.params, self.draft_params,
            self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
            jnp.asarray(padded, jnp.int32), jnp.int32(slot),
            jnp.int32(len(prompt)),
        )
        return first, first_lp

    def _device_round(self):
        (self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
         self.last, self.pos, toks, n_emit, lps) = self._round_jit(
            self.params, self.draft_params,
            self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
            self.last, self.pos, jnp.asarray(self.active),
        )
        return np.asarray(toks), np.asarray(n_emit), np.asarray(lps)

    def _device_step(self):  # pragma: no cover — step() is overridden
        raise NotImplementedError("speculative serving steps in rounds")

    def step(self) -> Dict[int, List[int]]:
        """One speculative round for every active slot -> {rid: [tokens]};
        each request receives 1..gamma+1 tokens (clipped at EOS and
        max_new_tokens host-side; the device overshoot is never read)."""
        self._drain_queue_into_slots()
        if not self.active.any():
            return self._materialize_pending()
        t0 = time.perf_counter()
        toks, n_emit, lps = self._device_round()
        out = self._materialize_pending()
        self._metrics.record("step", time.perf_counter() - t0)
        for slot in range(self.n_slots):
            if not self.active[slot]:
                continue
            rid = self._slot_rid[slot]
            accepted = [int(t) for t in toks[slot][: int(n_emit[slot])]]
            room = self.max_new_tokens - len(self._emitted[rid])
            accepted = accepted[:room]
            if self.eos_id is not None and self.eos_id in accepted:
                accepted = accepted[: accepted.index(self.eos_id) + 1]
            if not accepted:
                self._retire_if_done(slot)
                continue
            self._rounds += 1
            self._round_tokens += len(accepted)
            self._emitted[rid].extend(accepted)
            self._logprobs[rid].extend(
                float(x) for x in lps[slot][: len(accepted)])
            self._note_emitted(slot)
            out.setdefault(rid, []).extend(accepted)
            self._obs_tokens(rid, len(accepted))
            self._retire_if_done(slot)
        return out

    def mean_tokens_per_round(self) -> float:
        """Measured accepted tokens per live (slot, round) — the speedup
        factor over one-token decoding for a memory-bound target."""
        return self._round_tokens / self._rounds if self._rounds else 0.0

    def warmup(self) -> None:
        """Pre-compile every prompt bucket's dual prefill and the round."""

        def prefill_dummy(padded):
            (self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
             _f, _lp) = self._prefill_jit(
                self.params, self.draft_params,
                self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
                jnp.asarray(padded, jnp.int32), jnp.int32(0), jnp.int32(1),
            )

        self._warmup_buckets(prefill_dummy)
        (self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
         _l, _p, _t, _n, _lps) = self._round_jit(
            self.params, self.draft_params,
            self.k_cache, self.v_cache, self.dk_cache, self.dv_cache,
            self.last, self.pos,
            jnp.asarray(np.zeros((self.n_slots,), bool)),
        )
        jax.block_until_ready((self.k_cache, self.v_cache))
