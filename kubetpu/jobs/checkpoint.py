"""Checkpoint / resume for training jobs (orbax-backed, sharding-aware).

The *scheduler* side of kubetpu is deliberately stateless and rebuilds from
probes (the reference's contract, SURVEY.md §5.4); the *job* side is where
durable state lives. Checkpoints restore directly into the target mesh's
shardings — each host writes/reads only its shards (OCDBT), which is what
makes resume-on-a-new-slice (after the gang scheduler re-places a job)
practical.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Optional

import jax
import numpy as np

from kubetpu.jobs.train import TrainState


def save_checkpoint(path: str, state: TrainState) -> None:
    """Write a TrainState to *path* (created if needed)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state)
        ckptr.wait_until_finished()


class AsyncCheckpointer:
    """Non-blocking checkpointing for the train loop: ``save`` returns as
    soon as device arrays are snapshotted (orbax serializes to disk on a
    background thread), so training resumes while I/O drains — the step
    only ever pays device->host transfer, not the filesystem.

    One in-flight save at a time: a second ``save`` first waits for the
    previous one (bounding dirty state at one checkpoint), matching the
    single-writer layout ``latest_step_dir`` resumes from. Use as a
    context manager or call ``close()`` — pending writes flush on exit.
    """

    def __init__(self) -> None:
        import orbax.checkpoint as ocp

        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())

    def save(self, path: str, state: TrainState) -> None:
        self._ckptr.wait_until_finished()  # at most one in flight
        # Snapshot BEFORE returning: the train step donates its state, so
        # the caller's very next step deletes these buffers while orbax's
        # background thread still reads them. All device->host copies are
        # dispatched async first (they overlap), then collected — save()
        # costs one host transfer, never the filesystem write. Leaves that
        # are not fully addressable (multi-host shards) cannot be
        # host-snapshotted here — for those the save degrades to
        # synchronous below (warned), so donation stays safe either way.
        def start(x):
            if isinstance(x, jax.Array) and x.is_fully_addressable:
                x.copy_to_host_async()
            return x

        def collect(x):
            if isinstance(x, jax.Array) and x.is_fully_addressable:
                return np.asarray(x)
            return x

        has_remote = any(
            isinstance(x, jax.Array) and not x.is_fully_addressable
            for x in jax.tree.leaves(state)
        )
        if has_remote:
            # Non-addressable (multi-host) leaves cannot be host-snapshotted
            # here: orbax's background thread reads the live device buffers,
            # so a donating train step could free them mid-write. Degrade to
            # a synchronous save (the blocking wait alone protects every
            # leaf, so skip the snapshot copies entirely) rather than race.
            warnings.warn(
                "AsyncCheckpointer.save: state has non-fully-addressable "
                "leaves; falling back to synchronous save to avoid a "
                "use-after-donation race (don't donate checkpointed state "
                "on multi-host, or accept the blocking save).",
                stacklevel=2,
            )
            self._ckptr.save(os.path.abspath(path), args=_standard_save_args(state))
            self._ckptr.wait_until_finished()
            return
        state = jax.tree.map(collect, jax.tree.map(start, state))
        self._ckptr.save(os.path.abspath(path), args=_standard_save_args(state))

    def wait(self) -> None:
        self._ckptr.wait_until_finished()

    def close(self) -> None:
        self._ckptr.close()

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _standard_save_args(state):
    import orbax.checkpoint as ocp

    return ocp.args.StandardSave(state)


def restore_checkpoint(path: str, target: TrainState) -> TrainState:
    """Restore into the structure/shardings of *target* (a freshly-built
    state on the destination mesh — possibly a different slice than the one
    that saved)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if hasattr(x, "sharding")
            else x,
            target,
        )
        restored = ckptr.restore(path, abstract)
    # Pin every leaf to a committed mesh sharding. Freshly-initialized
    # scalars (optimizer counts, step) are uncommitted single-device arrays
    # that jit may re-place freely, but restored arrays come back committed —
    # a committed single-device scalar then clashes with mesh-sharded params
    # inside one jitted step. Replicate such leaves over the target's mesh.
    from jax.sharding import NamedSharding, PartitionSpec

    meshes = [
        leaf.sharding.mesh
        for leaf in jax.tree.leaves(target)
        if hasattr(leaf, "sharding") and isinstance(leaf.sharding, NamedSharding)
    ]
    mesh = meshes[0] if meshes else None

    def pin(restored_leaf, target_leaf):
        sharding = getattr(target_leaf, "sharding", None)
        if isinstance(sharding, NamedSharding):
            return jax.device_put(restored_leaf, sharding)
        if mesh is not None:
            return jax.device_put(restored_leaf, NamedSharding(mesh, PartitionSpec()))
        return restored_leaf

    return jax.tree.map(pin, restored, target)


def latest_step_dir(root: str) -> Optional[str]:
    """Resume helper: the highest-numbered step directory under *root*
    (layout: root/<step>/...)."""
    if not os.path.isdir(root):
        return None
    steps = [d for d in os.listdir(root) if d.isdigit()]
    if not steps:
        return None
    return os.path.join(root, max(steps, key=int))
