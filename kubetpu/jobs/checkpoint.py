"""Checkpoint / resume for training jobs (orbax-backed, sharding-aware).

The *scheduler* side of kubetpu is deliberately stateless and rebuilds from
probes (the reference's contract, SURVEY.md §5.4); the *job* side is where
durable state lives. Checkpoints restore directly into the target mesh's
shardings — each host writes/reads only its shards (OCDBT), which is what
makes resume-on-a-new-slice (after the gang scheduler re-places a job)
practical.

Crash safety (Round-7): every save writes to a TEMP sibling directory and
atomically renames into place only after the writer flushed — a job killed
mid-save (the exact window elastic recovery creates: the gang scheduler
re-places a job whenever a node dies) leaves a ``.tmp-*`` orphan, never a
half-written directory at the real path. ``latest_step_dir`` ignores
orphans (non-digit names), and ``restore_checkpoint`` raises the typed
``CorruptCheckpointError`` for a missing/truncated/mangled checkpoint so
resume logic can fall back to an older step instead of crashing on an
anonymous orbax stack trace.
"""

from __future__ import annotations

import os
import shutil
import warnings
from typing import Any, Optional

import jax
import numpy as np

from kubetpu.jobs.train import TrainState


class CheckpointError(RuntimeError):
    """Base for checkpoint load/save failures."""


class CorruptCheckpointError(CheckpointError):
    """The checkpoint at this path is missing, truncated, or mangled —
    resume from an older step (``latest_step_dir`` of the parent) or
    restart from scratch."""


def _tmp_path(path: str) -> str:
    # sibling, same filesystem (os.replace must not cross devices); pid
    # disambiguates concurrent writers from different processes
    return f"{path}.tmp-{os.getpid()}"


def _single_host() -> bool:
    """Atomic temp-write + rename is a SINGLE-HOST protocol: on a
    multi-host job every process writes shards of the same directory, and
    per-pid temp dirs would scatter them (then race the rename). There the
    save degrades to writing the final path directly — orbax's own
    multi-host commit protocol applies instead."""
    return jax.process_count() == 1


def _commit(tmp: str, path: str) -> None:
    """Atomically move a finished write into place. An overwritten
    previous checkpoint is first set ASIDE (rename, not rmtree) so no
    crash window loses both generations: a kill between the two renames
    leaves the old checkpoint at ``<path>.old``, which
    ``restore_checkpoint`` falls back to."""
    old = path + ".old"
    had_old = False
    if os.path.isdir(path):
        if os.path.isdir(old):
            shutil.rmtree(old)
        os.replace(path, old)
        had_old = True
    os.replace(tmp, path)
    if had_old:
        shutil.rmtree(old, ignore_errors=True)


def save_checkpoint(path: str, state: TrainState) -> None:
    """Write a TrainState to *path* (created if needed): temp-write +
    atomic rename, so a crash mid-save never leaves a torn checkpoint at
    the real path. Spanned (``checkpoint.save``): save stalls are visible
    on the same trace timeline as the scheduling/serving work around
    them."""
    import orbax.checkpoint as ocp

    from kubetpu.obs import trace as obs_trace
    from kubetpu.obs.events import event_log

    path = os.path.abspath(path)
    with obs_trace.span("checkpoint.save", path=path):
        if not _single_host():
            with ocp.StandardCheckpointer() as ckptr:
                ckptr.save(path, state)
                ckptr.wait_until_finished()
            event_log().emit("checkpoint_save", path=path)
            return
        tmp = _tmp_path(path)
        if os.path.isdir(tmp):  # stale orphan from a crashed writer: replace
            shutil.rmtree(tmp)
        try:
            with ocp.StandardCheckpointer() as ckptr:
                ckptr.save(tmp, state)
                ckptr.wait_until_finished()
            _commit(tmp, path)
            event_log().emit("checkpoint_save", path=path)
        finally:
            if os.path.isdir(tmp):  # failed before commit: no orphan leak
                shutil.rmtree(tmp, ignore_errors=True)


class AsyncCheckpointer:
    """Non-blocking checkpointing for the train loop: ``save`` returns as
    soon as device arrays are snapshotted (orbax serializes to disk on a
    background thread), so training resumes while I/O drains — the step
    only ever pays device->host transfer, not the filesystem.

    One in-flight save at a time: a second ``save`` first waits for the
    previous one (bounding dirty state at one checkpoint), matching the
    single-writer layout ``latest_step_dir`` resumes from. Use as a
    context manager or call ``close()`` — pending writes flush on exit.

    Crash safety: the background write lands in a ``.tmp-*`` sibling and
    is renamed into place only once finished (at the next ``save``/
    ``wait``/``close``) — a crash mid-write leaves an ignored orphan,
    never a torn checkpoint at the real path.
    """

    def __init__(self) -> None:
        import orbax.checkpoint as ocp

        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        self._pending: Optional[tuple] = None  # (tmp, final) awaiting commit

    def _finalize(self) -> None:
        """Commit the finished background write (caller has waited)."""
        if self._pending is not None:
            tmp, final = self._pending
            self._pending = None
            _commit(tmp, final)
            from kubetpu.obs.events import event_log

            event_log().emit("checkpoint_save", path=final, deferred=True)

    def _abort_pending(self) -> None:
        """The awaited write FAILED: never commit its torn tmp over the
        last good checkpoint — drop the marker and the debris."""
        if self._pending is not None:
            tmp, _final = self._pending
            self._pending = None
            shutil.rmtree(tmp, ignore_errors=True)

    def _await_writer(self) -> None:
        try:
            self._ckptr.wait_until_finished()
        except BaseException:
            self._abort_pending()
            raise

    def save(self, path: str, state: TrainState) -> None:
        self._await_writer()  # at most one in flight
        self._finalize()
        # Snapshot BEFORE returning: the train step donates its state, so
        # the caller's very next step deletes these buffers while orbax's
        # background thread still reads them. All device->host copies are
        # dispatched async first (they overlap), then collected — save()
        # costs one host transfer, never the filesystem write. Leaves that
        # are not fully addressable (multi-host shards) cannot be
        # host-snapshotted here — for those the save degrades to
        # synchronous below (warned), so donation stays safe either way.
        def start(x):
            if isinstance(x, jax.Array) and x.is_fully_addressable:
                x.copy_to_host_async()
            return x

        def collect(x):
            if isinstance(x, jax.Array) and x.is_fully_addressable:
                return np.asarray(x)
            return x

        path = os.path.abspath(path)
        atomic = _single_host()
        tmp = _tmp_path(path) if atomic else path
        if atomic and os.path.isdir(tmp):  # stale orphan, crashed writer
            shutil.rmtree(tmp)
        has_remote = any(
            isinstance(x, jax.Array) and not x.is_fully_addressable
            for x in jax.tree.leaves(state)
        )
        if has_remote:
            # Non-addressable (multi-host) leaves cannot be host-snapshotted
            # here: orbax's background thread reads the live device buffers,
            # so a donating train step could free them mid-write. Degrade to
            # a synchronous save (the blocking wait alone protects every
            # leaf, so skip the snapshot copies entirely) rather than race.
            warnings.warn(
                "AsyncCheckpointer.save: state has non-fully-addressable "
                "leaves; falling back to synchronous save to avoid a "
                "use-after-donation race (don't donate checkpointed state "
                "on multi-host, or accept the blocking save).",
                stacklevel=2,
            )
            self._ckptr.save(tmp, args=_standard_save_args(state))
            self._ckptr.wait_until_finished()
            if atomic:
                _commit(tmp, path)
            return
        state = jax.tree.map(collect, jax.tree.map(start, state))
        self._ckptr.save(tmp, args=_standard_save_args(state))
        if atomic:
            self._pending = (tmp, path)

    def wait(self) -> None:
        self._await_writer()
        self._finalize()

    def close(self) -> None:
        try:
            self._ckptr.close()  # flushes the in-flight write
        except BaseException:
            self._abort_pending()
            raise
        self._finalize()

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _standard_save_args(state):
    import orbax.checkpoint as ocp

    return ocp.args.StandardSave(state)


def restore_checkpoint(path: str, target: TrainState) -> TrainState:
    """Restore into the structure/shardings of *target* (a freshly-built
    state on the destination mesh — possibly a different slice than the one
    that saved). Raises ``CorruptCheckpointError`` when the checkpoint is
    missing, truncated, or otherwise unreadable — the typed signal resume
    logic needs to fall back to an older step."""
    import orbax.checkpoint as ocp

    from kubetpu.obs import trace as obs_trace
    from kubetpu.obs.events import event_log

    path = os.path.abspath(path)
    with obs_trace.span("checkpoint.restore", path=path):
        out = _restore_inner(path, target, ocp)
        event_log().emit("checkpoint_restore", path=path)
    return out


def _restore_inner(path: str, target: TrainState, ocp) -> TrainState:
    if not os.path.isdir(path):
        if os.path.isdir(path + ".old"):
            # a writer died between _commit's two renames: the previous
            # generation survives set-aside — restore it rather than fail
            warnings.warn(
                f"checkpoint at {path} is missing but a set-aside "
                f"previous generation exists; restoring {path}.old",
                stacklevel=2,
            )
            path = path + ".old"
        else:
            raise CorruptCheckpointError(
                f"no checkpoint directory at {path} (crashed mid-save "
                f"leaves only a .tmp-* orphan; resume from an older step)"
            )
    try:
        with ocp.StandardCheckpointer() as ckptr:
            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
                if hasattr(x, "sharding")
                else x,
                target,
            )
            restored = ckptr.restore(path, abstract)
    except Exception as e:  # noqa: BLE001 — orbax raises library-specific
        # types for truncation/mangling; surface ONE typed error
        raise CorruptCheckpointError(
            f"checkpoint at {path} is unreadable (truncated, mangled, or "
            f"not matching the target structure): {e}"
        ) from e
    # Pin every leaf to a committed mesh sharding. Freshly-initialized
    # scalars (optimizer counts, step) are uncommitted single-device arrays
    # that jit may re-place freely, but restored arrays come back committed —
    # a committed single-device scalar then clashes with mesh-sharded params
    # inside one jitted step. Replicate such leaves over the target's mesh.
    from jax.sharding import NamedSharding, PartitionSpec

    meshes = [
        leaf.sharding.mesh
        for leaf in jax.tree.leaves(target)
        if hasattr(leaf, "sharding") and isinstance(leaf.sharding, NamedSharding)
    ]
    mesh = meshes[0] if meshes else None

    def pin(restored_leaf, target_leaf):
        sharding = getattr(target_leaf, "sharding", None)
        if isinstance(sharding, NamedSharding):
            return jax.device_put(restored_leaf, sharding)
        if mesh is not None:
            return jax.device_put(restored_leaf, NamedSharding(mesh, PartitionSpec()))
        return restored_leaf

    return jax.tree.map(pin, restored, target)


def latest_step_dir(root: str) -> Optional[str]:
    """Resume helper: the highest-numbered step directory under *root*
    (layout: root/<step>/...)."""
    if not os.path.isdir(root):
        return None
    steps = [d for d in os.listdir(root) if d.isdigit()]
    if not steps:
        return None
    return os.path.join(root, max(steps, key=int))
