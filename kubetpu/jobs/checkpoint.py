"""Checkpoint / resume for training jobs (orbax-backed, sharding-aware).

The *scheduler* side of kubetpu is deliberately stateless and rebuilds from
probes (the reference's contract, SURVEY.md §5.4); the *job* side is where
durable state lives. Checkpoints restore directly into the target mesh's
shardings — each host writes/reads only its shards (OCDBT), which is what
makes resume-on-a-new-slice (after the gang scheduler re-places a job)
practical.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

from kubetpu.jobs.train import TrainState


def save_checkpoint(path: str, state: TrainState) -> None:
    """Write a TrainState to *path* (created if needed)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state)
        ckptr.wait_until_finished()


def restore_checkpoint(path: str, target: TrainState) -> TrainState:
    """Restore into the structure/shardings of *target* (a freshly-built
    state on the destination mesh — possibly a different slice than the one
    that saved)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if hasattr(x, "sharding")
            else x,
            target,
        )
        restored = ckptr.restore(path, abstract)
    # Pin every leaf to a committed mesh sharding. Freshly-initialized
    # scalars (optimizer counts, step) are uncommitted single-device arrays
    # that jit may re-place freely, but restored arrays come back committed —
    # a committed single-device scalar then clashes with mesh-sharded params
    # inside one jitted step. Replicate such leaves over the target's mesh.
    from jax.sharding import NamedSharding, PartitionSpec

    meshes = [
        leaf.sharding.mesh
        for leaf in jax.tree.leaves(target)
        if hasattr(leaf, "sharding") and isinstance(leaf.sharding, NamedSharding)
    ]
    mesh = meshes[0] if meshes else None

    def pin(restored_leaf, target_leaf):
        sharding = getattr(target_leaf, "sharding", None)
        if isinstance(sharding, NamedSharding):
            return jax.device_put(restored_leaf, sharding)
        if mesh is not None:
            return jax.device_put(restored_leaf, NamedSharding(mesh, PartitionSpec()))
        return restored_leaf

    return jax.tree.map(pin, restored, target)


def latest_step_dir(root: str) -> Optional[str]:
    """Resume helper: the highest-numbered step directory under *root*
    (layout: root/<step>/...)."""
    if not os.path.isdir(root):
        return None
    steps = [d for d in os.listdir(root) if d.isdigit()]
    if not steps:
        return None
    return os.path.join(root, max(steps, key=int))
