"""Draft-model construction for speculative decoding: distillation and
truncated self-drafts.

Speculation only pays when the draft AGREES with the target (VERDICT r4:
a random quarter-size draft measures as a slowdown — tokens/round 1.0).
Two ways to a high-agreement draft, both TPU-shaped (pure jit steps over
the same mesh/sharding machinery as training):

- ``make_distill_step``: train a small draft against the FROZEN target's
  logits (soft cross-entropy at a temperature, optionally mixed with the
  data CE). Greedy agreement is exactly what speculation accepts, and
  matching the teacher's distribution maximizes it where it matters (the
  teacher's argmax).
- ``truncated_draft``: a zero-training draft — the first ``n_layers`` of
  the target plus its own final norm/head. Useful as a starting point
  for distillation (layers already speak the model's representation
  language) and as the self-draft upper-bound harness.

Reference: none (the reference has no inference stack, SURVEY.md §2);
the distillation objective is the standard Hinton softening, reshaped to
one fused jit step.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from kubetpu.jobs import model as model_lib
from kubetpu.jobs.model import ModelConfig, Params
from kubetpu.jobs.train import TrainState, make_optimizer


def truncated_draft(cfg: ModelConfig, params: Params,
                    n_layers: int) -> Tuple[ModelConfig, Params]:
    """Draft = the target's first *n_layers* blocks + its embed/ln_f/head
    (shared arrays, no copy). The stacked-layer layout makes this a slice
    on axis 0 of every block leaf."""
    if not 0 < n_layers <= cfg.n_layers:
        raise ValueError(f"n_layers must be in (0, {cfg.n_layers}]")
    dcfg = dataclasses.replace(cfg, n_layers=n_layers)
    dparams = dict(params)
    dparams["blocks"] = {
        k: v[:n_layers] for k, v in params["blocks"].items()
    }
    return dcfg, dparams


def distill_loss(
    draft_cfg: ModelConfig,
    draft_params: Params,
    target_logits: jnp.ndarray,
    tokens: jnp.ndarray,
    targets: jnp.ndarray,
    temperature: float = 1.0,
    hard_weight: float = 0.5,
) -> jnp.ndarray:
    """Soft CE against the teacher's logits + ``hard_weight`` x data CE.
    The T^2 factor keeps the soft-gradient scale independent of the
    softening temperature (Hinton et al.)."""
    d_logits = model_lib.forward(draft_params, tokens, draft_cfg)
    d_logits = d_logits.astype(jnp.float32)
    t_soft = jax.nn.softmax(target_logits.astype(jnp.float32) / temperature,
                            axis=-1)
    d_logsoft = jax.nn.log_softmax(d_logits / temperature, axis=-1)
    soft = -jnp.mean(jnp.sum(t_soft * d_logsoft, axis=-1)) * temperature**2
    # hard CE from the SAME logits (one draft forward per step, not two)
    logp = jax.nn.log_softmax(d_logits, axis=-1)
    hard = -jnp.mean(
        jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    )
    return soft + hard_weight * hard


def make_distill_step(
    target_cfg: ModelConfig,
    draft_cfg: ModelConfig,
    optimizer: Optional[Any] = None,
    temperature: float = 1.0,
    hard_weight: float = 0.5,
):
    """Jitted ``step(draft_state, target_params, tokens, targets) ->
    (draft_state, loss)``: one distillation update of the draft against
    the frozen target. The target forward runs inside the same jit (no
    teacher-logit materialization on host; XLA fuses and frees). Build
    ``draft_state`` with ``init_draft_state``."""
    if target_cfg.vocab != draft_cfg.vocab:
        raise ValueError("target and draft must share a vocabulary")
    optimizer = optimizer or make_optimizer()

    @jax.jit
    def step(state: TrainState, target_params: Params, tokens, targets):
        t_logits = jax.lax.stop_gradient(
            model_lib.forward(target_params, tokens, target_cfg)
        )

        def loss_fn(p):
            return distill_loss(draft_cfg, p, t_logits, tokens, targets,
                                temperature, hard_weight)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            state.params)
        new_params = optax.apply_updates(state.params, updates)
        return TrainState(new_params, new_opt, state.step + 1), loss

    return step, optimizer


def init_draft_state(
    rng: jax.Array, draft_cfg: ModelConfig, optimizer,
    init_params: Optional[Params] = None,
) -> TrainState:
    """Fresh (or warm-started, e.g. ``truncated_draft``) distillation
    state. Warm starts COPY the arrays — the target's own weights must
    not be donated away by the draft's updates."""
    params = (
        jax.tree.map(jnp.array, init_params)
        if init_params is not None
        else model_lib.init_params(rng, draft_cfg)
    )
    return TrainState(params=params, opt_state=jax.jit(optimizer.init)(params),
                      step=jnp.zeros((), jnp.int32))


def agreement_rate(
    target_cfg: ModelConfig,
    draft_cfg: ModelConfig,
    target_params: Params,
    draft_params: Params,
    tokens: jnp.ndarray,
) -> float:
    """Teacher-forced greedy agreement: fraction of positions where the
    draft's argmax equals the target's argmax given the same prefix. The
    per-position acceptance probability speculation sees; mean
    tokens/round is ~ (1 - a^(gamma+1)) / (1 - a) for agreement a."""
    t = jnp.argmax(model_lib.forward(target_params, tokens, target_cfg), -1)
    d = jnp.argmax(model_lib.forward(draft_params, tokens, draft_cfg), -1)
    return float(jnp.mean((t == d).astype(jnp.float32)))
