"""Shared-prefix KV reuse: a host-side RADIX TREE over token-ID prefixes
whose nodes own page-granular spans of the paged server's page pool.

A fleet serving millions of users re-prefills the same system prompt /
few-shot preamble on every admission — the dominant share of prefill
FLOPs under mixed load, and the admission-stall tail BENCH_MODEL already
shows. The page pool + host-owned page tables (``kubetpu.jobs.paged``)
are exactly the substrate for cross-request sharing: KV at position ``p``
depends only on ``tokens[0..p]`` and the params (causal attention, RoPE
by absolute position), so two requests with the same token prefix compute
bit-identical KV for it — one of them can simply *map* the other's pages.

Design (the same "share hardware along the natural hierarchy" move the
reference makes for topology groups, applied to pool pages):

- the tree's unit of sharing is the PAGE: a node owns ``k`` physical pool
  pages covering ``k * page_size`` token positions. Children are keyed by
  their edge's FIRST PAGE of tokens (a ``page_size``-tuple), so sibling
  edges never collide and splits only ever happen at page boundaries —
  sub-page divergence is simply not shareable and never enters the tree;
- ``match(tokens)`` walks greedily and returns the longest FULL-PAGE
  cached prefix plus the deepest node, which the caller pins
  (``refcount += 1``) for the lifetime of the slot that maps the pages.
  Eviction only ever removes LEAF nodes with ``refcount == 0`` (LRU by a
  logical clock), so a pinned node protects itself and every ancestor
  (ancestors have children by construction) — mapped pages can never be
  reclaimed under a live reader;
- ``insert(tokens, pages)`` publishes a retiring slot's prompt KV by
  DONATING the slot's physical pages to the tree (ownership transfer, no
  device copy): the walk consumes existing coverage, splits a mid-node
  divergence at the page boundary, and attaches the uncovered suffix as a
  new branch. Pages the tree already covers are NOT consumed — the caller
  returns them to the pool free-list;
- the COPY-ON-WRITE rule is structural, not a runtime check: a slot maps
  shared pages READ-ONLY as the leading prefix of its page table and
  starts chunked prefill at ``pos = matched_tokens`` (page-aligned), so
  every scatter the slot ever issues — prefill chunks and decode steps
  alike — lands at page indices past the shared prefix, into pages the
  slot allocated privately. The partially-covered boundary page (a prompt
  whose last cached page would also hold the token that must be forwarded
  to sample) is handled by RECOMPUTING it into a private page (the
  "copy" is a deterministic re-prefill — bit-identical by the argument
  above) rather than ever writing into a shared page.

The tree is pure host bookkeeping: device code stays purely functional
and the page table is still just a jit input, so greedy decode through a
cache *hit* is token-exact vs a cold run (pinned by test, same
discipline as the paged-vs-dense parity pin).

Reference: the radix-tree prefix cache follows the public RadixAttention
pattern (SGLang) re-shaped for this repo's host-owned tables; no
inference stack exists in the reference (SURVEY.md §2).
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Sequence, Set, Tuple


class PrefixNode:
    """One radix-tree node: a page-granular span of cached tokens.

    ``tokens`` has length ``len(pages) * page_size``; ``pages`` are
    physical pool page indices the node OWNS (the pool's accounting
    oracle counts them as tree-owned). ``refcount`` counts live slots
    pinning this node as their deepest match; ``stamp`` is the LRU
    logical clock."""

    __slots__ = ("tokens", "pages", "children", "parent", "refcount",
                 "stamp")

    def __init__(self, tokens: Tuple[int, ...], pages: List[int],
                 parent: Optional["PrefixNode"]) -> None:
        self.tokens = tokens
        self.pages = list(pages)
        self.children: Dict[Tuple[int, ...], "PrefixNode"] = {}
        self.parent = parent
        self.refcount = 0
        self.stamp = 0


class RadixPrefixCache:
    """Radix tree of page-granular token prefixes over a shared page pool.

    The tree never touches device memory — it trades in physical page
    INDICES. Allocation/free of the underlying pages stays with the
    paged server's free-list; the tree only records ownership while a
    prefix is cached, and hands pages back via ``evict``/``clear``.

    ``max_pages`` bounds the tree's total owned pages (the
    ``prefix_cache_pages`` budget); ``insert`` refuses (truncates) past
    it — the caller evicts first if it wants room.
    """

    def __init__(self, page_size: int, max_pages: int) -> None:
        if page_size <= 0:
            raise ValueError("page_size must be positive")
        if max_pages <= 0:
            raise ValueError("max_pages must be positive (0 pages = "
                             "construct no cache at all)")
        self.page_size = page_size
        self.max_pages = max_pages
        self.root = PrefixNode((), [], None)
        self.total_pages = 0
        self._clock = 0

    # -- internal helpers ----------------------------------------------------

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _key(self, tokens: Sequence[int], i: int) -> Tuple[int, ...]:
        return tuple(tokens[i:i + self.page_size])

    @staticmethod
    def _common(a: Sequence[int], b: Sequence[int]) -> int:
        n = min(len(a), len(b))
        i = 0
        while i < n and a[i] == b[i]:
            i += 1
        return i

    def _walk(self, tokens: Sequence[int], stamp: bool):
        """The one greedy radix walk every operation shares — match,
        missing_pages and insert must agree on exactly which full pages
        of *tokens* the tree covers, or the budget math (plan with
        ``missing_pages``, consume with ``insert``) desynchronizes.

        Returns ``(node, i, pages, deepest, div_child, div_jp)``: the
        last FULLY-traversed node, the covered token count ``i`` (page-
        aligned), the physical pages covering ``tokens[:i]`` in order,
        the deepest node touched (``None`` on a zero match), and — when
        the walk stopped mid-child — that child plus how many of its
        pages matched (``None, 0`` otherwise). ``stamp`` refreshes the
        LRU clock on every node touched (a hit is a use)."""
        ps = self.page_size
        node = self.root
        i = 0
        pages: List[int] = []
        deepest: Optional[PrefixNode] = None
        while len(tokens) - i >= ps:
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            j = self._common(child.tokens, tokens[i:])
            jp = j // ps
            if jp == 0:  # defensive: keyed lookup guarantees jp >= 1
                break
            if stamp:
                child.stamp = self._tick()
            pages.extend(child.pages[:jp])
            i += jp * ps
            deepest = child
            if j < len(child.tokens):
                return node, i, pages, deepest, child, jp
            node = child
        return node, i, pages, deepest, None, 0

    # -- queries -------------------------------------------------------------

    def match(self, tokens: Sequence[int]):
        """Longest cached full-page prefix of *tokens*.

        Returns ``(matched_tokens, pages, node)`` where ``pages`` are the
        physical pages covering ``tokens[:matched_tokens]`` in order and
        ``node`` is the deepest node touched (``None`` on a zero match).
        Does NOT pin — callers that map the pages must ``pin(node)``
        before anything else can run. Every node on the path gets a fresh
        LRU stamp (a hit is a use, even of the ancestors)."""
        _, i, pages, deepest, _, _ = self._walk(tokens, stamp=True)
        return i, pages, deepest

    def missing_pages(self, tokens: Sequence[int]) -> int:
        """How many NEW pages ``insert(tokens, ...)`` would need — the
        budget/eviction planner's question. Read-only (no stamps)."""
        _, i, _, _, _, _ = self._walk(tokens, stamp=False)
        return (len(tokens) - i) // self.page_size

    # -- pinning -------------------------------------------------------------

    def pin(self, node: PrefixNode) -> None:
        node.refcount += 1

    def release(self, node: PrefixNode) -> None:
        if node.refcount <= 0:
            raise AssertionError("release without a matching pin")
        node.refcount -= 1

    # -- publication ---------------------------------------------------------

    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> Set[int]:
        """Publish ``tokens`` (full pages only: ``len(tokens)`` must be a
        multiple of ``page_size`` and equal ``len(pages) * page_size``)
        by donating the aligned physical *pages*.

        Returns the set of page indices the tree CONSUMED (took
        ownership of). Pages covering spans the tree already holds are
        not consumed — the caller frees them. Consumption is clamped to
        the remaining ``max_pages`` budget; the donated suffix is
        truncated to a contiguous prefix of it, never fragmented."""
        ps = self.page_size
        if len(tokens) != len(pages) * ps:
            raise ValueError("tokens must cover exactly len(pages) pages")
        node, i, _, _, div_child, div_jp = self._walk(tokens, stamp=True)
        if div_child is not None and len(tokens) - i >= ps:
            # diverged mid-child with a full page still to attach: split
            # at the page boundary so the shared span becomes its own
            # node and the new branch can attach beside the old suffix
            node = self._split(div_child, div_jp)
        remaining = (len(tokens) - i) // ps
        budget_room = self.max_pages - self.total_pages
        remaining = min(remaining, max(0, budget_room))
        if remaining <= 0:
            return set()
        new_tokens = tuple(tokens[i:i + remaining * ps])
        new_pages = list(pages[i // ps: i // ps + remaining])
        leaf = PrefixNode(new_tokens, new_pages, node)
        leaf.stamp = self._tick()
        node.children[self._key(new_tokens, 0)] = leaf
        self.total_pages += remaining
        return set(new_pages)

    def _split(self, child: PrefixNode, jp: int) -> PrefixNode:
        """Split *child* at page *jp* into (prefix mid, suffix child);
        returns the new mid node. Pure bookkeeping — no page moves, and
        the child keeps its identity so existing pins stay valid (a pin
        on the suffix protects the mid transitively: mid has a child)."""
        ps = self.page_size
        assert 0 < jp * ps < len(child.tokens)
        parent = child.parent
        mid = PrefixNode(child.tokens[:jp * ps], child.pages[:jp], parent)
        mid.stamp = child.stamp
        suffix_tokens = child.tokens[jp * ps:]
        child.tokens = suffix_tokens
        child.pages = child.pages[jp:]
        child.parent = mid
        mid.children[self._key(suffix_tokens, 0)] = child
        parent.children[self._key(mid.tokens, 0)] = mid
        return mid

    # -- eviction ------------------------------------------------------------

    def evict(self, n_pages: int) -> List[int]:
        """Reclaim >= *n_pages* pages by removing LRU refcount-0 LEAF
        nodes (oldest stamp first; removing a leaf can expose its parent
        as the next candidate). Returns the freed physical pages — the
        caller appends them to the pool free-list. May return fewer than
        asked when everything left is pinned or an ancestor of a pin.

        One DFS to seed the candidate heap, then O(log n) per victim —
        this runs on the admission path under pool pressure, where a
        per-victim full-tree rescan would stack host latency onto an
        already-stalling TTFT. Only a victim's parent can become newly
        evictable (nothing else changes), so it alone is re-examined."""
        heap: List[Tuple[int, int, PrefixNode]] = []
        seq = 0                      # tie-break: never compare nodes
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            if not n.children and n.refcount == 0:
                heap.append((n.stamp, seq, n))
                seq += 1
            stack.extend(n.children.values())
        heapq.heapify(heap)
        freed: List[int] = []
        while len(freed) < n_pages and heap:
            _, _, victim = heapq.heappop(heap)
            freed.extend(victim.pages)
            self.total_pages -= len(victim.pages)
            parent = victim.parent
            del parent.children[self._key(victim.tokens, 0)]
            victim.parent = None
            if (parent is not self.root and not parent.children
                    and parent.refcount == 0):
                heapq.heappush(heap, (parent.stamp, seq, parent))
                seq += 1
        return freed

    def clear(self) -> List[int]:
        """Drop the whole tree, returning every owned page. Only valid
        when nothing is pinned (asserted) — the paged server calls this
        from ``warmup``, whose contract already requires an idle server."""
        pages: List[int] = []
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            assert n.refcount == 0, "clear() under a live pin"
            pages.extend(n.pages)
            stack.extend(n.children.values())
        self.root.children.clear()
        self.total_pages = 0
        return pages

    # -- introspection / the accounting oracle -------------------------------

    def nodes(self) -> List[PrefixNode]:
        out: List[PrefixNode] = []
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            out.append(n)
            stack.extend(n.children.values())
        return out

    def n_nodes(self) -> int:
        return len(self.nodes())

    def owned_pages(self) -> Set[int]:
        pages: List[int] = []
        for n in self.nodes():
            pages.extend(n.pages)
        owned = set(pages)
        assert len(owned) == len(pages), "tree owns a page twice"
        return owned

    def check(self) -> None:
        """Structural invariants: span lengths page-exact, child keys
        consistent, page ownership disjoint, total_pages exact, and no
        negative refcounts. AssertionError on violation — the pool
        oracle's tree half."""
        ps = self.page_size
        total = 0
        seen: Set[int] = set()
        stack = [(self.root, True)]
        while stack:
            n, is_root = stack.pop()
            if not is_root:
                assert len(n.tokens) == len(n.pages) * ps, (
                    f"node span {len(n.tokens)} tokens != "
                    f"{len(n.pages)} pages * {ps}")
                assert len(n.tokens) >= ps, "empty non-root node"
                assert n.refcount >= 0, "negative refcount"
                for p in n.pages:
                    assert p not in seen, f"page {p} owned twice"
                    seen.add(p)
                total += len(n.pages)
            for key, child in n.children.items():
                assert key == tuple(child.tokens[:ps]), "mis-keyed child"
                assert child.parent is n, "broken parent link"
                stack.append((child, False))
        assert total == self.total_pages, (
            f"total_pages {self.total_pages} != counted {total}")
        assert total <= self.max_pages, "tree exceeds its page budget"
