"""Shared-prefix KV reuse: a host-side RADIX TREE over token-ID prefixes
whose nodes own page-granular spans of the paged server's page pool.

A fleet serving millions of users re-prefills the same system prompt /
few-shot preamble on every admission — the dominant share of prefill
FLOPs under mixed load, and the admission-stall tail BENCH_MODEL already
shows. The page pool + host-owned page tables (``kubetpu.jobs.paged``)
are exactly the substrate for cross-request sharing: KV at position ``p``
depends only on ``tokens[0..p]`` and the params (causal attention, RoPE
by absolute position), so two requests with the same token prefix compute
bit-identical KV for it — one of them can simply *map* the other's pages.

Design (the same "share hardware along the natural hierarchy" move the
reference makes for topology groups, applied to pool pages):

- the tree's unit of sharing is the PAGE: a node owns ``k`` physical pool
  pages covering ``k * page_size`` token positions. Children are keyed by
  their edge's FIRST PAGE of tokens (a ``page_size``-tuple), so sibling
  edges never collide and splits only ever happen at page boundaries —
  sub-page divergence is simply not shareable and never enters the tree;
- ``match(tokens)`` walks greedily and returns the longest FULL-PAGE
  cached prefix plus the deepest node, which the caller pins
  (``refcount += 1``) for the lifetime of the slot that maps the pages.
  Eviction only ever removes LEAF nodes with ``refcount == 0`` (LRU by a
  logical clock), so a pinned node protects itself and every ancestor
  (ancestors have children by construction) — mapped pages can never be
  reclaimed under a live reader;
- ``insert(tokens, pages)`` publishes a retiring slot's prompt KV by
  DONATING the slot's physical pages to the tree (ownership transfer, no
  device copy): the walk consumes existing coverage, splits a mid-node
  divergence at the page boundary, and attaches the uncovered suffix as a
  new branch. Pages the tree already covers are NOT consumed — the caller
  returns them to the pool free-list;
- the COPY-ON-WRITE rule is structural, not a runtime check: a slot maps
  shared pages READ-ONLY as the leading prefix of its page table and
  starts chunked prefill at ``pos = matched_tokens`` (page-aligned), so
  every scatter the slot ever issues — prefill chunks and decode steps
  alike — lands at page indices past the shared prefix, into pages the
  slot allocated privately. The partially-covered boundary page (a prompt
  whose last cached page would also hold the token that must be forwarded
  to sample) is handled by RECOMPUTING it into a private page (the
  "copy" is a deterministic re-prefill — bit-identical by the argument
  above) rather than ever writing into a shared page.

The tree is pure host bookkeeping: device code stays purely functional
and the page table is still just a jit input, so greedy decode through a
cache *hit* is token-exact vs a cold run (pinned by test, same
discipline as the paged-vs-dense parity pin).

Reference: the radix-tree prefix cache follows the public RadixAttention
pattern (SGLang) re-shaped for this repo's host-owned tables; no
inference stack exists in the reference (SURVEY.md §2).
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Sequence, Set, Tuple


class PrefixNode:
    """One radix-tree node: a page-granular span of cached tokens.

    ``tokens`` has length ``len(pages) * page_size``; ``pages`` are
    physical pool page indices the node OWNS (the pool's accounting
    oracle counts them as tree-owned). ``refcount`` counts live slots
    pinning this node as their deepest match; ``stamp`` is the LRU
    logical clock.

    Round-19 host tier: a node holds its span in exactly ONE tier —
    either ``pages`` (HBM, host is None) or ``host`` (a stored-layout
    dict of numpy arrays with the page axis at position 1, pages empty).
    Host-tier nodes always form the BOTTOM FRONTIER of the tree (no
    host node ever has an HBM descendant), so a match that reaches the
    host tier never strands mapped HBM pages below unmapped spans."""

    __slots__ = ("tokens", "pages", "children", "parent", "refcount",
                 "stamp", "host", "host_bytes")

    def __init__(self, tokens: Tuple[int, ...], pages: List[int],
                 parent: Optional["PrefixNode"]) -> None:
        self.tokens = tokens
        self.pages = list(pages)
        self.children: Dict[Tuple[int, ...], "PrefixNode"] = {}
        self.parent = parent
        self.refcount = 0
        self.stamp = 0
        self.host: Optional[Dict[str, "object"]] = None
        self.host_bytes = 0


class RadixPrefixCache:
    """Radix tree of page-granular token prefixes over a shared page pool.

    The tree never touches device memory — it trades in physical page
    INDICES. Allocation/free of the underlying pages stays with the
    paged server's free-list; the tree only records ownership while a
    prefix is cached, and hands pages back via ``evict``/``clear``.

    ``max_pages`` bounds the tree's total owned pages (the
    ``prefix_cache_pages`` budget); ``insert`` refuses (truncates) past
    it — the caller evicts first if it wants room.
    """

    def __init__(self, page_size: int, max_pages: int,
                 host_budget_bytes: int = 0) -> None:
        if page_size <= 0:
            raise ValueError("page_size must be positive")
        if max_pages <= 0:
            raise ValueError("max_pages must be positive (0 pages = "
                             "construct no cache at all)")
        if host_budget_bytes < 0:
            raise ValueError("host_budget_bytes must be >= 0")
        self.page_size = page_size
        self.max_pages = max_pages
        # Round-19: byte budget for the eviction-to-host tier (0 = off).
        # ``total_pages`` counts HBM pages only; host occupancy is
        # tracked in bytes because stored-layout page size depends on
        # the model config and kv_int8 (int8 + scales pairs).
        self.host_budget_bytes = host_budget_bytes
        self.host_bytes = 0
        self.spilled_pages = 0
        self.root = PrefixNode((), [], None)
        self.total_pages = 0
        self._clock = 0

    # -- internal helpers ----------------------------------------------------

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _key(self, tokens: Sequence[int], i: int) -> Tuple[int, ...]:
        return tuple(tokens[i:i + self.page_size])

    @staticmethod
    def _common(a: Sequence[int], b: Sequence[int]) -> int:
        n = min(len(a), len(b))
        i = 0
        while i < n and a[i] == b[i]:
            i += 1
        return i

    def _walk(self, tokens: Sequence[int], stamp: bool,
              through_host: bool = False):
        """The one greedy radix walk every operation shares — match,
        missing_pages and insert must agree on exactly which full pages
        of *tokens* the tree covers, or the budget math (plan with
        ``missing_pages``, consume with ``insert``) desynchronizes.

        Returns ``(node, i, pages, deepest, div_child, div_jp, segs)``:
        the last FULLY-traversed node, the covered token count ``i``
        (page-aligned), the physical pages covering ``tokens[:i]`` in
        order, the deepest node touched (``None`` on a zero match),
        when the walk stopped mid-child that child plus how many of its
        pages matched (``None, 0`` otherwise), and ``segs`` — the
        ``(node, pages_covered)`` trail in path order. ``stamp``
        refreshes the LRU clock on every node touched (a hit is a use).

        ``through_host=False`` (the HBM-only view every pre-Round-19
        caller keeps) stops BEFORE descending into a host-tier child;
        ``through_host=True`` walks across the tier boundary — host
        segs contribute to ``i`` but not to ``pages`` (they own no
        physical pages until filled)."""
        ps = self.page_size
        node = self.root
        i = 0
        pages: List[int] = []
        deepest: Optional[PrefixNode] = None
        segs: List[Tuple[PrefixNode, int]] = []
        while len(tokens) - i >= ps:
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            if child.host is not None and not through_host:
                break
            j = self._common(child.tokens, tokens[i:])
            jp = j // ps
            if jp == 0:  # defensive: keyed lookup guarantees jp >= 1
                break
            if stamp:
                child.stamp = self._tick()
            pages.extend(child.pages[:jp])
            segs.append((child, jp))
            i += jp * ps
            deepest = child
            if j < len(child.tokens):
                return node, i, pages, deepest, child, jp, segs
            node = child
        return node, i, pages, deepest, None, 0, segs

    # -- queries -------------------------------------------------------------

    def match(self, tokens: Sequence[int]):
        """Longest cached full-page prefix of *tokens*.

        Returns ``(matched_tokens, pages, node)`` where ``pages`` are the
        physical pages covering ``tokens[:matched_tokens]`` in order and
        ``node`` is the deepest node touched (``None`` on a zero match).
        Does NOT pin — callers that map the pages must ``pin(node)``
        before anything else can run. Every node on the path gets a fresh
        LRU stamp (a hit is a use, even of the ancestors). Coverage
        stops at the HBM/host tier boundary — only mappable pages count
        (use ``match_tiered`` for the cross-tier view)."""
        _, i, pages, deepest, _, _, _ = self._walk(tokens, stamp=True)
        return i, pages, deepest

    def match_tiered(self, tokens: Sequence[int]):
        """Longest cached full-page prefix of *tokens* across BOTH
        tiers. Returns ``(matched_tokens, segs)`` with ``segs`` the
        ``(node, pages_covered)`` trail in path order; a seg whose node
        has ``host is not None`` is a host-tier span the caller must
        FILL (allocate pool pages, upload, ``promote``) before it can
        be mapped. Host nodes form the bottom frontier, so the trail is
        always an HBM run followed by a host run."""
        _, i, _, _, _, _, segs = self._walk(tokens, stamp=True,
                                            through_host=True)
        return i, segs

    def missing_pages(self, tokens: Sequence[int]) -> int:
        """How many NEW pages ``insert(tokens, ...)`` would need — the
        budget/eviction planner's question. Read-only (no stamps).
        Host-covered spans COUNT as missing: insert adopts donated
        pages into them, which consumes HBM budget just like a fresh
        attach."""
        _, i, _, _, _, _, _ = self._walk(tokens, stamp=False)
        return (len(tokens) - i) // self.page_size

    # -- pinning -------------------------------------------------------------

    def pin(self, node: PrefixNode) -> None:
        node.refcount += 1

    def release(self, node: PrefixNode) -> None:
        if node.refcount <= 0:
            raise AssertionError("release without a matching pin")
        node.refcount -= 1

    # -- publication ---------------------------------------------------------

    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> Set[int]:
        """Publish ``tokens`` (full pages only: ``len(tokens)`` must be a
        multiple of ``page_size`` and equal ``len(pages) * page_size``)
        by donating the aligned physical *pages*.

        Returns the set of page indices the tree CONSUMED (took
        ownership of). Pages covering spans the tree already holds in
        HBM are not consumed — the caller frees them. Host-tier spans
        on the walk ADOPT the matching donated pages (the retiring slot
        recomputed bit-identical KV — causal attention over the same
        tokens and params) and drop their host buffers, so a
        re-published prefix re-enters the fast tier with no upload.
        Consumption is clamped to the remaining ``max_pages`` budget;
        the donated suffix is truncated to a contiguous prefix of it,
        never fragmented — and adoption stops at the first host node
        that no longer fits, so an HBM attach never lands below an
        unfilled host span (the frontier invariant)."""
        ps = self.page_size
        if len(tokens) != len(pages) * ps:
            raise ValueError("tokens must cover exactly len(pages) pages")
        node, i, _, _, div_child, div_jp, segs = self._walk(
            tokens, stamp=True, through_host=True)
        if div_child is not None and len(tokens) - i >= ps:
            # diverged mid-child with a full page still to attach: split
            # at the page boundary so the shared span becomes its own
            # node and the new branch can attach beside the old suffix
            node = self._split(div_child, div_jp)
            segs[-1] = (node, div_jp)
        consumed: Set[int] = set()
        off = 0
        for child, jp in segs:
            span_pages = len(child.tokens) // ps
            if child.host is not None:
                if (jp < span_pages
                        or self.total_pages + span_pages > self.max_pages):
                    # trailing partial host coverage (no donated pages
                    # for the tail) or out of budget: leave the rest of
                    # the path in the host tier and attach nothing
                    # below it
                    return consumed
                child.pages = list(pages[off:off + span_pages])
                self.host_bytes -= child.host_bytes
                child.host = None
                child.host_bytes = 0
                self.total_pages += span_pages
                consumed.update(child.pages)
            off += jp
        remaining = (len(tokens) - i) // ps
        budget_room = self.max_pages - self.total_pages
        remaining = min(remaining, max(0, budget_room))
        if remaining <= 0:
            return consumed
        new_tokens = tuple(tokens[i:i + remaining * ps])
        new_pages = list(pages[i // ps: i // ps + remaining])
        leaf = PrefixNode(new_tokens, new_pages, node)
        leaf.stamp = self._tick()
        node.children[self._key(new_tokens, 0)] = leaf
        self.total_pages += remaining
        consumed.update(new_pages)
        return consumed

    def _split(self, child: PrefixNode, jp: int) -> PrefixNode:
        """Split *child* at page *jp* into (prefix mid, suffix child);
        returns the new mid node. Pure bookkeeping — no page moves, and
        the child keeps its identity so existing pins stay valid (a pin
        on the suffix protects the mid transitively: mid has a child)."""
        ps = self.page_size
        assert 0 < jp * ps < len(child.tokens)
        parent = child.parent
        mid = PrefixNode(child.tokens[:jp * ps], child.pages[:jp], parent)
        mid.stamp = child.stamp
        if child.host is not None:
            # host-tier split: slice the stored-layout buffers along the
            # page axis (axis 1), copying so neither half keeps the full
            # base array alive — byte accounting must track real memory
            old = child.host_bytes
            mid.host = {k: v[:, :jp].copy() for k, v in child.host.items()}
            child.host = {k: v[:, jp:].copy()
                          for k, v in child.host.items()}
            mid.host_bytes = sum(a.nbytes for a in mid.host.values())
            child.host_bytes = sum(a.nbytes for a in child.host.values())
            self.host_bytes += mid.host_bytes + child.host_bytes - old
        suffix_tokens = child.tokens[jp * ps:]
        child.tokens = suffix_tokens
        child.pages = child.pages[jp:]
        child.parent = mid
        mid.children[self._key(suffix_tokens, 0)] = child
        parent.children[self._key(mid.tokens, 0)] = mid
        return mid

    # -- eviction ------------------------------------------------------------

    def evict(self, n_pages: int, gather=None) -> List[int]:
        """Reclaim >= *n_pages* HBM pages from LRU refcount-0 frontier
        nodes (oldest stamp first; evicting one can expose its parent as
        the next candidate). Returns the freed physical pages — the
        caller appends them to the pool free-list. May return fewer than
        asked when everything left is pinned or an ancestor of a pin.

        Round-19 spill: with *gather* set (``pages -> stored-layout
        dict``, the paged server's device->host barrier leg), a victim's
        KV is gathered into host buffers under ``host_budget_bytes``
        before its pages are freed — the node STAYS in the tree as a
        host-tier entry a later match can fill back. Without gather (or
        when the payload doesn't fit even after host-LRU eviction), the
        victim and its host-only subtree are dropped as before.

        One DFS to seed the candidate heap, then O(log n) per victim —
        this runs on the admission path under pool pressure, where a
        per-victim full-tree rescan would stack host latency onto an
        already-stalling TTFT. Only a victim's parent can become newly
        evictable (nothing else changes), so it alone is re-examined.
        A frontier victim is an HBM node with no HBM or pinned
        descendants — host children below it are fine (they spill with
        it, structurally) — which degenerates to the pre-tier "leaf"
        rule when the host tier is off."""
        hbm_below: Dict[int, int] = {}
        pins_below: Dict[int, int] = {}
        order: List[PrefixNode] = []
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            order.append(n)
            stack.extend(n.children.values())
        for n in reversed(order):   # children precede parents here
            hbm_below[id(n)] = sum(
                hbm_below[id(c)] + (1 if c.pages else 0)
                for c in n.children.values())
            pins_below[id(n)] = sum(
                pins_below[id(c)] + c.refcount
                for c in n.children.values())

        def eligible(n: PrefixNode) -> bool:
            return (bool(n.pages) and n.refcount == 0
                    and hbm_below[id(n)] == 0 and pins_below[id(n)] == 0)

        heap: List[Tuple[int, int, PrefixNode]] = []
        seq = 0                      # tie-break: never compare nodes
        for n in order:
            if eligible(n):
                heap.append((n.stamp, seq, n))
                seq += 1
        heapq.heapify(heap)
        freed: List[int] = []
        while len(freed) < n_pages and heap:
            _, _, victim = heapq.heappop(heap)
            if not victim.pages:
                continue            # stale entry: already processed
            parent = victim.parent
            spilled = False
            if gather is not None and self.host_budget_bytes > 0:
                payload = gather(victim.pages)
                nbytes = sum(a.nbytes for a in payload.values())
                if self._host_reserve(nbytes):
                    victim.host = payload
                    victim.host_bytes = nbytes
                    self.host_bytes += nbytes
                    self.spilled_pages += len(victim.pages)
                    spilled = True
            freed.extend(victim.pages)
            self.total_pages -= len(victim.pages)
            victim.pages = []
            if not spilled:
                self._drop_subtree(victim)
            up = parent
            while up is not None and up is not self.root:
                hbm_below[id(up)] -= 1
                up = up.parent
            if (parent is not None and parent is not self.root
                    and eligible(parent)):
                heapq.heappush(heap, (parent.stamp, seq, parent))
                seq += 1
        return freed

    def promote(self, node: PrefixNode, pages: Sequence[int]) -> None:
        """Host -> HBM fill commit: the paged server uploaded *node*'s
        host buffers into freshly-allocated pool *pages*; take ownership
        and drop the host copy. Callers promote TOP-DOWN along the match
        path so an HBM node never appears below a still-host ancestor,
        and must have made ``max_pages`` room first."""
        ps = self.page_size
        assert node.host is not None and not node.pages, \
            "promote() target is not a host-tier node"
        assert len(pages) * ps == len(node.tokens), \
            "promote() page count does not cover the node span"
        assert self.total_pages + len(pages) <= self.max_pages, \
            "promote() past the HBM page budget"
        node.pages = list(pages)
        self.host_bytes -= node.host_bytes
        node.host = None
        node.host_bytes = 0
        self.total_pages += len(pages)
        node.stamp = self._tick()

    def _host_reserve(self, nbytes: int) -> bool:
        """Make room for *nbytes* under ``host_budget_bytes`` by
        dropping LRU unpinned host-tier LEAVES (a dropped leaf can
        expose its host parent as the next candidate). Returns False —
        reserving nothing — when the payload can't fit even with the
        whole evictable host tier gone."""
        if self.host_budget_bytes <= 0 or nbytes > self.host_budget_bytes:
            return False
        while self.host_bytes + nbytes > self.host_budget_bytes:
            victim: Optional[PrefixNode] = None
            for n in self.nodes():
                if n.host is None or n.children or n.refcount != 0:
                    continue
                if victim is None or n.stamp < victim.stamp:
                    victim = n
            if victim is None:
                return False
            self.host_bytes -= victim.host_bytes
            victim.host = None
            victim.host_bytes = 0
            victim.parent.children.pop(self._key(victim.tokens, 0), None)
            victim.parent = None
        return True

    def _drop_subtree(self, node: PrefixNode) -> None:
        """Detach *node* and release the host buffers of its (host-only,
        unpinned — the eviction frontier guarantees both) subtree."""
        parent = node.parent
        if parent is not None:
            parent.children.pop(self._key(node.tokens, 0), None)
        node.parent = None
        stack = [node]
        while stack:
            n = stack.pop()
            if n.host is not None:
                self.host_bytes -= n.host_bytes
                n.host = None
                n.host_bytes = 0
            stack.extend(n.children.values())

    def clear(self) -> List[int]:
        """Drop the whole tree, returning every owned HBM page. Host
        buffers go with it (Round-19 warmup fix: a host entry surviving
        a flush would later fill pages into a tree path that no longer
        exists). Only valid when nothing is pinned (asserted) — the
        paged server calls this from ``warmup``, whose contract already
        requires an idle server."""
        pages: List[int] = []
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            assert n.refcount == 0, "clear() under a live pin"
            pages.extend(n.pages)
            n.host = None
            n.host_bytes = 0
            stack.extend(n.children.values())
        self.root.children.clear()
        self.total_pages = 0
        self.host_bytes = 0
        return pages

    # -- introspection / the accounting oracle -------------------------------

    def nodes(self) -> List[PrefixNode]:
        out: List[PrefixNode] = []
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            out.append(n)
            stack.extend(n.children.values())
        return out

    def n_nodes(self) -> int:
        return len(self.nodes())

    def owned_pages(self) -> Set[int]:
        pages: List[int] = []
        for n in self.nodes():
            pages.extend(n.pages)
        owned = set(pages)
        assert len(owned) == len(pages), "tree owns a page twice"
        return owned

    def host_nodes(self) -> List[PrefixNode]:
        return [n for n in self.nodes() if n.host is not None]

    def check(self) -> None:
        """Structural invariants: span lengths page-exact, child keys
        consistent, page ownership disjoint, total_pages exact, no
        negative refcounts — plus the Round-19 tier half: every node
        holds its span in exactly one tier, host spans are page-exact
        in stored layout, host bytes sum to the tracked total under
        budget, and no host node has an HBM descendant (the frontier).
        AssertionError on violation — the pool oracle's tree half."""
        ps = self.page_size
        total = 0
        hbytes = 0
        seen: Set[int] = set()
        stack = [(self.root, True, False)]
        while stack:
            n, is_root, under_host = stack.pop()
            if not is_root:
                assert len(n.tokens) >= ps, "empty non-root node"
                assert n.refcount >= 0, "negative refcount"
                if n.host is not None:
                    assert not n.pages, (
                        "node owns HBM pages AND host buffers for the "
                        "same span")
                    assert len(n.tokens) % ps == 0, "ragged host span"
                    npg = len(n.tokens) // ps
                    for name, arr in n.host.items():
                        assert arr.shape[1] == npg, (
                            f"host buffer {name} covers {arr.shape[1]} "
                            f"pages, span needs {npg}")
                    assert n.host_bytes == sum(
                        a.nbytes for a in n.host.values()), \
                        "stale per-node host_bytes"
                    hbytes += n.host_bytes
                else:
                    assert n.host_bytes == 0, "host_bytes without host"
                    assert len(n.tokens) == len(n.pages) * ps, (
                        f"node span {len(n.tokens)} tokens != "
                        f"{len(n.pages)} pages * {ps}")
                    assert not under_host, (
                        "HBM node below a host-tier ancestor")
                for p in n.pages:
                    assert p not in seen, f"page {p} owned twice"
                    seen.add(p)
                total += len(n.pages)
            for key, child in n.children.items():
                assert key == tuple(child.tokens[:ps]), "mis-keyed child"
                assert child.parent is n, "broken parent link"
                stack.append((child, False,
                              under_host or (not is_root
                                             and n.host is not None)))
        assert total == self.total_pages, (
            f"total_pages {self.total_pages} != counted {total}")
        assert total <= self.max_pages, "tree exceeds its page budget"
        assert hbytes == self.host_bytes, (
            f"host_bytes {self.host_bytes} != counted {hbytes}")
        assert hbytes <= max(self.host_budget_bytes, 0), \
            "host tier past its byte budget"
