"""JAX integration: turn a kubetpu allocation into a jax.sharding.Mesh and
run sharded training on it (dp x sp x tp, ring attention for long context).
The demonstration workload the scheduler arranges hardware for."""

from kubetpu.jobs.meshjob import (
    factor_axes,
    make_mesh,
    make_multislice_mesh,
    mesh_from_allocation,
    slice_groups,
)
from kubetpu.jobs.model import ModelConfig, forward, init_params, next_token_loss
from kubetpu.jobs.ring_attention import make_ring_attention
from kubetpu.jobs.train import TrainState, init_state, make_eval_step, make_train_step

__all__ = [
    "factor_axes",
    "make_mesh",
    "make_multislice_mesh",
    "mesh_from_allocation",
    "slice_groups",
    "ModelConfig",
    "forward",
    "init_params",
    "next_token_loss",
    "make_ring_attention",
    "TrainState",
    "init_state",
    "make_eval_step",
    "make_train_step",
]

# Submodules with heavier deps are imported lazily by users:
#   kubetpu.jobs.pipeline   (pp training), kubetpu.jobs.decode (KV-cache
#   generation), kubetpu.jobs.speculative (draft+verify decoding),
#   kubetpu.jobs.serving (continuous batching),
#   kubetpu.jobs.encoder (bidirectional masked-LM family),
#   kubetpu.jobs.vision (ViT classification family),
#   kubetpu.jobs.checkpoint (orbax), kubetpu.jobs.data,
#   kubetpu.jobs.tokenizer (HF tokenizer.json byte-level BPE loader),
#   kubetpu.jobs.distill (draft distillation for speculative decoding),
#   kubetpu.jobs.quant (int8 weights + int8 KV cache),
#   kubetpu.jobs.native_data (C++ mmap corpus loader),
#   kubetpu.jobs.launch (jax.distributed wiring)
