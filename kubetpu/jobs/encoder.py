"""Bidirectional encoder (BERT-style masked-LM) — the second model family.

Deliberately thin: the decoder's blocks, init, sharding specs and mesh
plumbing are reused verbatim — an encoder IS ``model.forward`` with a
full-visibility attention core instead of the causal one. The only new
code is the masked-token objective and the train-step wiring. On TPU the
bidirectional core is the same Pallas flash kernel with ``causal=False``
(``kubetpu.ops.flash_attention``), so encoder attention gets the identical
VMEM-tiled treatment as the decoder's.

Reference: the reference has no models at all (SURVEY.md §2) — family
breadth is a kubetpu extension.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubetpu.jobs import model as model_lib
from kubetpu.jobs.model import ModelConfig, Params
from kubetpu.jobs.train import (
    _filter_spec,
    make_optimizer,
    make_update_step,
)


def dense_bidirectional_attention(q, k, v):
    """Full-visibility softmax attention — the XLA reference core for the
    encoder ((B, S, H, D) in/out; ``model.dense_attention`` with the causal
    mask off). On TPU prefer ``flash_attention(causal=False)``."""
    return model_lib.dense_attention(q, k, v, causal=False)


def encoder_forward(
    params: Params,
    tokens: jnp.ndarray,
    cfg: ModelConfig,
    attn_fn=None,
    positions: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Logits with every position attending to ALL positions — the shared
    decoder blocks under a bidirectional core. tokens: (B, S) -> (B, S, V)."""
    return model_lib.forward(
        params, tokens, cfg,
        attn_fn=attn_fn or dense_bidirectional_attention,
        positions=positions,
    )


def masked_lm_loss(
    params: Params,
    tokens: jnp.ndarray,
    mask_positions: jnp.ndarray,
    mask_id: int,
    cfg: ModelConfig,
    attn_fn=None,
) -> jnp.ndarray:
    """BERT objective: corrupt the positions flagged in *mask_positions*
    (bool (B, S)) with *mask_id*, predict the ORIGINAL tokens there; only
    masked positions contribute to the loss. MoE configs get the same
    load-balance auxiliary term as the decoder's next_token_loss."""
    corrupted = jnp.where(mask_positions, mask_id, tokens)
    attn = attn_fn or dense_bidirectional_attention
    x, aux = model_lib.forward_hidden(params, corrupted, cfg, attn_fn=attn)
    loss = model_lib.lm_loss_tail(x, params["head"], tokens, cfg,
                                  weights=mask_positions)
    if cfg.n_experts > 0 and cfg.moe_aux_coeff > 0:
        loss = loss + cfg.moe_aux_coeff * aux
    return loss


def make_mlm_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    mask_id: int,
    optimizer=None,
    attention: str = "dense",
    interpret: bool = False,
):
    """Jitted masked-LM train step over the mesh. Encoder batches shard
    over dp ONLY (sequence replicated): there is no causal ring for
    encoders, so sp-sharding the sequence would just force per-layer
    all-gathers — and the opaque flash kernel cannot be sequence-partitioned
    at all. ``attention``: 'dense' or 'flash' (the Pallas kernel with
    causal=False)."""
    optimizer = optimizer or make_optimizer()
    if attention == "flash":
        from kubetpu.ops import flash_attention

        attn_fn = partial(flash_attention, block_q=128, block_k=128,
                          interpret=interpret, causal=False)
    elif attention == "dense":
        attn_fn = dense_bidirectional_attention
    else:
        raise ValueError(f"unknown encoder attention {attention!r}")

    def loss_fn(params, tokens, mask_positions):
        return masked_lm_loss(params, tokens, mask_positions, mask_id, cfg,
                              attn_fn=attn_fn)

    # dp-only batch sharding (see docstring) — NOT the decoder's P(dp, sp)
    bspec = NamedSharding(mesh, _filter_spec(mesh, P("dp", None)))
    return jax.jit(
        make_update_step(loss_fn, optimizer),
        in_shardings=(None, bspec, bspec),
        donate_argnums=(0,),
    )
