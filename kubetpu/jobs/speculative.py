"""Speculative decoding: a small draft model proposes ``gamma`` tokens, the
target model verifies them in ONE batched cached forward, and the longest
agreeing prefix is accepted plus one correction/bonus token.

TPU-shaped throughout: generation is a single jitted ``lax.while_loop`` of
fixed-shape rounds (static shapes, no host round-trips) that exits as soon
as every sequence has its tokens — rounds with high acceptance finish the
job in ~num_steps/(gamma+1) iterations, which is the entire speedup (decode
is memory-bound: the target's weights stream once per ROUND instead of once
per token). The verification pass is a (gamma+1)-token CHUNK forward
through the target's KV cache (``decode.forward_chunk`` — the same block
implementation as plain decoding, so the two can never diverge).

Greedy acceptance makes the output EXACTLY equal to target-only greedy
decoding — token j is accepted iff the draft's choice equals the target's
argmax given the same prefix, and the first disagreement is replaced by the
target's own choice (when all gamma agree, the target's next argmax is the
bonus token). Rejected cache slots need no rollback: positions rewind and
later rounds overwrite them, and every attention mask is position-bounded
so stale entries are never read.

Reference: the reference framework has no inference stack at all
(SURVEY.md §2 "parallelism" note) — this is a TPU-first extension, like the
rest of kubetpu's jobs layer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubetpu.jobs.decode import forward_chunk_at as _forward_chunk_at
from kubetpu.jobs.decode import init_kv_cache, prefill
from kubetpu.jobs.model import ModelConfig


def draft_propose(draft_cfg, gamma, draft_params, dk, dv, last, pos):
    """Draft ``gamma`` greedy tokens sequentially through the draft's
    dense cache at per-sequence positions — the proposal half of a round,
    shared by ``draft_and_verify`` and the paged speculative server (a
    draft-cache fix lands in all three paths). Returns
    ``(dk, dv, drafts (B, gamma))``."""

    def draft_step(c, _):
        dk, dv, tok, p = c
        logits, dk, dv = _forward_chunk_at(
            draft_cfg, draft_params, tok[:, None], dk, dv, p
        )
        nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
        return (dk, dv, nxt, p + 1), nxt

    (dk, dv, last_draft, _), drafts = jax.lax.scan(
        draft_step, (dk, dv, last, pos), None, length=gamma
    )
    drafts = drafts.transpose(1, 0)                     # (B, gamma)

    # write the LAST draft's K/V too (position pos+gamma): the scan fed
    # only [last, d_0..d_{gamma-2}] — without this, a fully-accepted round
    # leaves a hole the draft attends every later round, silently decaying
    # acceptance. A rejected d_{gamma-1}'s entry is overwritten when that
    # position is next fed.
    _lg, dk, dv = _forward_chunk_at(
        draft_cfg, draft_params, last_draft[:, None], dk, dv, pos + gamma
    )
    return dk, dv, drafts


def draft_and_verify(target_cfg, draft_cfg, gamma, target_params,
                     draft_params, tk, tv, dk, dv, last, pos):
    """One speculative round's device math, shared by the batch generate
    loop and the continuous-batching server (a fix here lands in both):
    draft ``gamma`` tokens sequentially through the draft cache, verify
    them in ONE (gamma+1)-chunk target forward, and compute the longest
    agreeing prefix. Returns
    ``(tk, tv, dk, dv, target_tok (B, gamma+1), accepted (B,), t_logits)``
    — per sequence, tokens ``target_tok[:, :accepted+1]`` are the round's
    greedy-exact emissions."""
    dk, dv, drafts = draft_propose(
        draft_cfg, gamma, draft_params, dk, dv, last, pos
    )

    # verify: ONE (gamma+1)-chunk forward of [last, d_0..d_{gamma-1}]
    chunk = jnp.concatenate([last[:, None], drafts], axis=1)
    t_logits, tk, tv = _forward_chunk_at(
        target_cfg, target_params, chunk, tk, tv, pos
    )                                                   # (B, gamma+1, V)
    target_tok = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)

    # longest agreeing prefix
    agree = (drafts == target_tok[:, :gamma]).astype(jnp.int32)
    accepted = jnp.sum(jnp.cumprod(agree, axis=1), axis=1)   # (B,)
    return tk, tv, dk, dv, target_tok, accepted, t_logits


def make_speculative_generate(
    target_cfg: ModelConfig,
    draft_cfg: ModelConfig,
    gamma: int = 4,
):
    """Jitted ``generate(target_params, draft_params, prompt, num_steps)``
    -> ((B, S_p + num_steps) tokens, mean accepted-per-live-round) — greedy
    speculative decoding, output identical to target-only greedy decode.

    Both models must share the vocab; the draft is typically a few-layer
    shrink of the target. ``gamma`` drafts per round; each round emits
    between 1 and gamma+1 tokens per sequence.
    """
    if target_cfg.vocab != draft_cfg.vocab:
        raise ValueError("target and draft must share a vocabulary")

    def generate(target_params, draft_params, prompt, num_steps: int):
        b, s_prompt = prompt.shape
        max_seq = s_prompt + num_steps + gamma + 1
        tk, tv = init_kv_cache(target_cfg, b, max_seq)
        dk, dv = init_kv_cache(draft_cfg, b, max_seq)

        t_logits, tk, tv = prefill(target_cfg, target_params, prompt, tk, tv)
        _d_logits, dk, dv = prefill(draft_cfg, draft_params, prompt, dk, dv)

        # first emitted token: the target's own choice after the prompt
        last = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)       # (B,)
        out = jnp.zeros((b, num_steps + gamma + 2), jnp.int32)
        out = out.at[:, 0].set(last)

        pos0 = jnp.full((b,), s_prompt, jnp.int32)  # index of `last` in seq
        count0 = jnp.ones((b,), jnp.int32)          # emitted so far
        stats0 = jnp.zeros((2,), jnp.float32)       # (live tokens, live rounds)

        def round_step(carry):
            tk, tv, dk, dv, last, out, pos, count, stats = carry
            live = count < num_steps                            # (B,)

            tk, tv, dk, dv, target_tok, accepted, _tl = draft_and_verify(
                target_cfg, draft_cfg, gamma, target_params, draft_params,
                tk, tv, dk, dv, last, pos,
            )
            n_emit = accepted + 1                           # 1..gamma+1

            # emit target_tok[:, :n_emit] at out[count:count+n_emit]; writes
            # past num_steps (and whole post-completion rounds) route to a
            # sacrificial last column
            idx = jnp.arange(gamma + 1)[None, :]
            write_pos = count[:, None] + idx                # (B, gamma+1)
            valid = (idx < n_emit[:, None]) & (write_pos < num_steps)
            write_pos = jnp.where(valid, write_pos, out.shape[1] - 1)
            out = _scatter_rows(out, write_pos, target_tok, valid)

            new_last = jnp.take_along_axis(
                target_tok, (n_emit - 1)[:, None], axis=1
            )[:, 0]
            new_pos = jnp.minimum(pos + n_emit, s_prompt + num_steps)
            new_count = jnp.minimum(count + n_emit, num_steps)
            # stats count only tokens actually WRITTEN (valid), so a final
            # round clipped at num_steps doesn't inflate tokens-per-round
            n_written = jnp.sum(valid.astype(jnp.int32), axis=1)
            stats = stats + jnp.array(
                [jnp.sum(jnp.where(live, n_written, 0)).astype(jnp.float32),
                 jnp.sum(live.astype(jnp.float32))]
            )
            return (tk, tv, dk, dv, new_last, out, new_pos, new_count, stats)

        def not_done(carry):
            count = carry[7]
            return jnp.any(count < num_steps)

        (tk, tv, dk, dv, last, out, pos, count, stats) = jax.lax.while_loop(
            not_done, round_step,
            (tk, tv, dk, dv, last, out, pos0, count0, stats0),
        )
        tokens = jnp.concatenate([prompt, out[:, :num_steps]], axis=1)
        mean_accept = stats[0] / jnp.maximum(stats[1], 1.0)
        return tokens, mean_accept

    return jax.jit(generate, static_argnums=(3,))


def _scatter_rows(out, write_pos, values, valid):
    """out[b, write_pos[b, j]] = values[b, j] where valid[b, j] (invalid
    writes are routed by the caller to a sacrificial last column)."""
    rows = jnp.arange(out.shape[0])[:, None] * out.shape[1]
    flat_idx = (rows + write_pos).reshape(-1)
    flat_val = values.reshape(-1)
    keep = valid.reshape(-1)
    base = out.reshape(-1)
    cur = base[flat_idx]
    upd = jnp.where(keep, flat_val, cur)
    return base.at[flat_idx].set(upd).reshape(out.shape)
