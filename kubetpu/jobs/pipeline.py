"""Pipeline parallelism: GPipe-style microbatched execution over a ``pp``
mesh axis, composing with dp/sp/tp/ep.

TPU-native shape of the idea: the stacked layer axis of the model's
parameters is sharded over ``pp`` (each stage holds ``n_layers / pp``
contiguous blocks); a *partial-manual* ``shard_map`` runs the classic GPipe
schedule — ``M + pp - 1`` uniform ticks, each tick computing one stage on
one microbatch and rotating activations one ICI hop forward with
``lax.ppermute``. Batch (dp) and heads/ff/experts (tp/ep) stay automatic
GSPMD *inside* the manual region.

Sequence parallelism composes by making the region manual over {pp, sp}
jointly: nested shard_maps cannot rebind a parent's manual axes, so the
ring-attention body runs *directly* inside the region (its ``sp``
collectives bind the region's manual axis) and RoPE positions arrive as a
``P('sp')``-sharded operand so each device rotates with its global
positions.

Uniform static control flow (a ``lax.fori_loop`` over ticks, bubble ticks
included as masked work) is deliberate: TPUs want every device executing
the same program; the (pp-1)/M bubble is the standard GPipe cost,
amortized by more microbatches.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubetpu.jobs import model as model_lib
from kubetpu.jobs.model import ModelConfig, Params
from kubetpu.jobs.ring_attention import make_ring_local, shard_map_compat
from kubetpu.jobs.train import (
    TrainState,
    _filter_spec,
    batch_spec,
    init_state,
    make_optimizer,
    param_specs,
)


def _stage_forward(cfg: ModelConfig, attn_fn, positions, blocks_local, x):
    """Run this stage's contiguous chunk of blocks (a lax.scan, as in the
    non-pipelined forward)."""
    body = partial(model_lib._block, cfg, attn_fn, positions)

    def scan_body(carry, layer):
        return body(carry, layer), None

    if cfg.remat:
        scan_body = jax.checkpoint(scan_body, policy=model_lib.remat_xla_policy(cfg))
    x, _ = jax.lax.scan(scan_body, x, blocks_local)
    return x


def make_pipeline_forward(
    cfg: ModelConfig,
    mesh: Mesh,
    n_microbatches: int,
    use_ring: bool = True,
    ring_impl: str = "dense",
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """(params, tokens (M*B, S)) -> logits (M*B, S, V) through the pipeline.

    Embedding and head are replicated (cheap) and run outside the manual
    region; only the block stack is staged. ``ring_impl="flash"`` runs the
    Pallas flash kernels inside every ring step (the {pp, sp} region is
    already manual, so the flash-ring LOCAL body drops in directly —
    no nested shard_map); ``interpret=True`` for CPU tests of it.
    """
    axis_name, sp_axis = "pp", "sp"
    manual_axes = {axis_name} | ({sp_axis} if use_ring else set())
    seq_spec = sp_axis if use_ring else None
    # built (and impl-validated) eagerly — even when use_ring is False, so
    # a typo'd ring_impl raises here, not when the caller later flips
    # use_ring on; binds the sp axis only when traced. cfg.window selects
    # the banded ring (window x sp compose via one boundary ppermute).
    ring_local = make_ring_local(ring_impl, sp_axis, block_q, block_k,
                                 interpret, window=cfg.window)
    attn = ring_local if use_ring else model_lib.default_attn_fn(cfg)

    def region(blocks, h_stack, positions):
        pp_size = jax.lax.psum(1, axis_name)
        my_idx = jax.lax.axis_index(axis_name)
        last = pp_size - 1
        m, b, s, d = h_stack.shape  # s is the sp-local length under use_ring
        ticks = n_microbatches + pp_size - 1
        stage = partial(_stage_forward, cfg, attn, positions, blocks)

        def tick(t, carry):
            recv, out_stack = carry
            mb_in = jnp.clip(t, 0, m - 1)
            inject = jax.lax.dynamic_index_in_dim(h_stack, mb_in, 0, keepdims=False)
            x_in = jnp.where(my_idx == 0, inject, recv)
            y = stage(x_in)
            # the last stage finishes microbatch t - (pp-1) on this tick
            mb_out = jnp.clip(t - last, 0, m - 1)
            valid = jnp.logical_and(my_idx == last, t >= last)
            cur = jax.lax.dynamic_index_in_dim(out_stack, mb_out, 0, keepdims=False)
            out_stack = jax.lax.dynamic_update_index_in_dim(
                out_stack, jnp.where(valid, y, cur), mb_out, 0
            )
            # rotate activations one hop toward the next stage
            perm = [(i, (i + 1) % pp_size) for i in range(pp_size)]
            recv = jax.lax.ppermute(y, axis_name, perm)
            return recv, out_stack

        recv0 = jnp.zeros((b, s, d), h_stack.dtype)
        out0 = jnp.zeros_like(h_stack)
        _, out_stack = jax.lax.fori_loop(0, ticks, tick, (recv0, out0))
        # only the last stage holds real outputs; psum over pp replicates
        # them so the region's output is uniform across pp (out_spec None)
        mask = (my_idx == last).astype(out_stack.dtype)
        return jax.lax.psum(out_stack * mask, axis_name)

    region_sm = shard_map_compat(
        region,
        mesh=mesh,
        in_specs=(
            _blocks_pp_specs(cfg),
            P(None, None, seq_spec, None),
            P(seq_spec),
        ),
        out_specs=P(None, None, seq_spec, None),
        axis_names=manual_axes,
        check_vma=False,
    )

    def forward_hidden(params: Params, tokens: jnp.ndarray) -> jnp.ndarray:
        mb, seq = tokens.shape
        assert mb % n_microbatches == 0, (mb, n_microbatches)
        b = mb // n_microbatches
        positions = jnp.arange(seq, dtype=jnp.int32)

        h = params["embed"][tokens]                        # (M*B, S, D)
        h_stack = h.reshape(n_microbatches, b, seq, -1)    # (M, B, S, D)
        out_stack = region_sm(params["blocks"], h_stack, positions)

        x = out_stack.reshape(mb, seq, -1)
        return model_lib.rms_norm(x, params["ln_f"])

    def forward(params: Params, tokens: jnp.ndarray) -> jnp.ndarray:
        x = forward_hidden(params, tokens)
        return jnp.einsum("bsd,dv->bsv", x, params["head"])

    forward.hidden = forward_hidden
    return forward


def _blocks_pp_specs(cfg: ModelConfig):
    """In-specs for the block stack inside the manual region: only the
    leading (layer, "pp") axis is manual; tp/ep shardings stay automatic."""
    full = param_specs(cfg, pp=True)["blocks"]

    def keep_pp(spec):
        return P(*(a if a == "pp" else None for a in spec))

    return jax.tree.map(keep_pp, full, is_leaf=lambda x: isinstance(x, P))


def make_pipeline_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    n_microbatches: int,
    optimizer=None,
    use_ring: bool = True,
    ring_impl: str = "dense",
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """Full pipelined training step: GPipe forward/backward + adamw.
    ``ring_impl="flash"`` puts the Pallas flash kernels inside the ring."""
    optimizer = optimizer or make_optimizer()
    fwd = make_pipeline_forward(cfg, mesh, n_microbatches, use_ring=use_ring,
                                ring_impl=ring_impl, block_q=block_q,
                                block_k=block_k, interpret=interpret)

    def loss_fn(params, tokens, targets):
        # the head runs outside the manual pp region, so the shared loss
        # tail (materialized or chunked per cfg.loss_chunk) drops in as-is
        x = fwd.hidden(params, tokens)
        return model_lib.lm_loss_tail(x, params["head"], targets, cfg)

    bspec = NamedSharding(mesh, _filter_spec(mesh, batch_spec(mesh)))
    from kubetpu.jobs.train import make_update_step

    return jax.jit(make_update_step(loss_fn, optimizer),
                   in_shardings=(None, bspec, bspec), donate_argnums=(0,))


def init_pipeline_state(
    rng: jax.Array, cfg: ModelConfig, mesh: Mesh, optimizer=None
) -> Tuple[TrainState, Any]:
    """train.init_state with the layer axis sharded over pp."""
    return init_state(rng, cfg, mesh, optimizer, pp=True)
