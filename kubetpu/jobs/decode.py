"""Autoregressive decoding with a KV cache — the inference path.

TPU-shaped decoding: the whole generation loop is ONE ``lax.scan`` inside a
single jit (no per-token dispatch); the KV cache is a preallocated static
(L, B, S_max, H_kv, D) buffer (kv heads only under grouped-query
attention) updated with ``dynamic_update_slice`` (static shapes — XLA
requirement), and the cache shards over the mesh like activations (batch
on dp, heads on tp; the sequence axis of the *cache* stays unsharded — sp
is a training-time axis).

The core is the T-token CHUNK forward through the cache
(``forward_chunk``): plain decoding is its T == 1 case, speculative
verification (``kubetpu.jobs.speculative``) its T == gamma+1 case — one
block implementation for both, so they cannot diverge. Prefill processes
the prompt in one batched forward (MXU-friendly), then the decode scan
consumes/extends the cache one token per step.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubetpu.jobs import model as model_lib
from kubetpu.jobs.model import ModelConfig, Params


def init_kv_cache(cfg: ModelConfig, batch: int, max_seq: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(k_cache, v_cache), each (L, B, S_max, H_kv, D) — with grouped-query
    attention the cache holds only the kv heads, an n_heads/n_kv_heads HBM
    saving (the reason GQA exists)."""
    shape = (cfg.n_layers, batch, max_seq, cfg.kv_heads, cfg.head_dim)
    return jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype)


def kv_cache_specs() -> P:
    """Cache sharding: batch on dp, (kv) heads on tp — under grouped-query
    attention tp must divide n_kv_heads (ModelConfig docs)."""
    return P(None, "dp", None, "tp", None)


def _attend_cached(q, k_cache, v_cache, pos, window: int = 0):
    """Chunk attention through the cache: query t (of T new positions
    starting at *pos*) sees cache entries 0..pos+t — bounded below by the
    sliding ``window`` when set (cfg.window; the cache still stores all
    positions, only the read is banded). Grouped-query aware:
    the query's H heads attend against H_kv cached heads in groups of
    G = H/H_kv WITHOUT expanding the cache (expansion would materialize the
    full-head cache per step and erase GQA's memory win).
    q: (B, T, H, D); caches: (B, S_max, H_kv, D)."""
    b, t, h, d = q.shape
    h_kv = k_cache.shape[2]
    g = h // h_kv
    scale = d ** -0.5
    qg = q.reshape(b, t, h_kv, g, d).astype(jnp.float32)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg,
                        k_cache.astype(jnp.float32)) * scale
    k_pos = jnp.arange(k_cache.shape[1])
    q_pos = pos + jnp.arange(t)
    mask = k_pos[None, :] <= q_pos[:, None]            # (T, S_max)
    if window > 0:  # sliding window: band the cache read
        mask &= q_pos[:, None] - k_pos[None, :] < window
    scores = jnp.where(mask[None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v_cache.astype(jnp.float32))
    return out.reshape(b, t, h, d).astype(q.dtype)


def _lora_in_delta(h, a, b, scale):
    """Per-example LoRA delta for an input-projection target: h (B, T, D)
    through a (B, D, r) then b (B, r, H, hd) — the rank-r bottleneck makes
    this a near-free pair of skinny matmuls per step."""
    t = jnp.einsum("btd,bdr->btr", h, a)
    return jnp.einsum("btr,brhk->bthk", t, b) * scale


def _decode_block_core(cfg, layer, x, cache, pos, cache_io, lora_l=None,
                       lora_scale=1.0):
    """THE transformer block body of every cached decode path — dense
    cache, ring cache, seq2seq — parameterized on the cache strategy so a
    numerics or LoRA fix can never land in one cache layout and silently
    miss another. ``cache_io(q, k, v, cache, pos) -> (attn, cache)`` owns
    the write + banded read; everything else (norms, projections with
    optional per-example LoRA deltas, absolute-position rope, MLP) is
    shared. x: (B, T, D)."""
    def proj(name, hh, base):
        out = jnp.einsum("bsd,dhk->bshk", hh, base)
        if lora_l is not None and f"{name}_a" in lora_l:
            out = out + _lora_in_delta(
                hh, lora_l[f"{name}_a"], lora_l[f"{name}_b"], lora_scale
            ).astype(out.dtype)
        return out

    h = model_lib.rms_norm(x, layer["ln1"])
    q = proj("wq", h, layer["wq"])
    k = proj("wk", h, layer["wk"])
    v = proj("wv", h, layer["wv"])
    positions = pos + jnp.arange(x.shape[1], dtype=jnp.int32)[None, :]
    positions = jnp.broadcast_to(positions, (x.shape[0], x.shape[1]))
    q = model_lib.rope(q, positions, cfg.rope_theta, cfg.rope_llama3_scaling)
    k = model_lib.rope(k, positions, cfg.rope_theta, cfg.rope_llama3_scaling)

    attn, cache = cache_io(q, k, v, cache, pos)
    o = jnp.einsum("bshk,hkd->bsd", attn, layer["wo"])
    if lora_l is not None and "wo_a" in lora_l:
        t = jnp.einsum("bshk,bhkr->bsr", attn, lora_l["wo_a"])
        o = o + (jnp.einsum("bsr,brd->bsd", t, lora_l["wo_b"])
                 * lora_scale).astype(o.dtype)
    x = x + o

    h = model_lib.rms_norm(x, layer["ln2"])
    delta, _aux = model_lib._mlp(cfg, h, layer)
    return x + delta, cache


def _dense_cache_io(window):
    """The (L, B, S_max, ...) contiguous-cache strategy: write the chunk
    at *pos*, attend through the whole (banded) cache."""
    def io(q, k, v, cache, pos):
        k_l, v_l = cache
        k_l = jax.lax.dynamic_update_slice(k_l, k, (0, pos, 0, 0))
        v_l = jax.lax.dynamic_update_slice(v_l, v, (0, pos, 0, 0))
        return _attend_cached(q, k_l, v_l, pos, window=window), (k_l, v_l)

    return io


def init_kv_cache_int8(cfg: ModelConfig, batch: int, max_seq: int):
    """int8 KV cache: ((k_q, k_scale), (v_q, v_scale)) with values
    (L, B, S_max, H_kv, D) int8 and per-token per-head scales
    (L, B, S_max, H_kv, 1) f32 — resident cache bytes drop to
    ~(1 + 4/D) / 2 of the bf16 cache (D=64: 0.53x), which is the
    difference between a serving batch fitting HBM or not. Entries are
    quantized at write time (``quant.quantize_kv_chunk``) and dequantized
    on read inside the attention core's f32 math."""
    shape = (cfg.n_layers, batch, max_seq, cfg.kv_heads, cfg.head_dim)
    sshape = shape[:-1] + (1,)
    return (
        (jnp.zeros(shape, jnp.int8), jnp.zeros(sshape, jnp.float32)),
        (jnp.zeros(shape, jnp.int8), jnp.zeros(sshape, jnp.float32)),
    )


def _int8_cache_io(window):
    """The int8 contiguous-cache strategy: quantize the chunk's K/V per
    token per head on write; dequantize on read (the convert+mul chain
    fuses into the attention einsum's operand read — no f32 cache copy is
    ever resident). Same banded read as ``_dense_cache_io``."""
    from kubetpu.jobs.quant import quantize_kv_chunk

    def io(q, k, v, cache, pos):
        (kq, ksc), (vq, vsc) = cache
        k8, ks = quantize_kv_chunk(k)
        v8, vs = quantize_kv_chunk(v)
        kq = jax.lax.dynamic_update_slice(kq, k8, (0, pos, 0, 0))
        ksc = jax.lax.dynamic_update_slice(ksc, ks, (0, pos, 0, 0))
        vq = jax.lax.dynamic_update_slice(vq, v8, (0, pos, 0, 0))
        vsc = jax.lax.dynamic_update_slice(vsc, vs, (0, pos, 0, 0))
        attn = _attend_cached(
            q,
            kq.astype(jnp.float32) * ksc,
            vq.astype(jnp.float32) * vsc,
            pos, window=window,
        )
        return attn, ((kq, ksc), (vq, vsc))

    return io


def _decode_block(cfg, layer, x, k_cache_l, v_cache_l, pos, lora_l=None,
                  lora_scale=1.0):
    """One transformer block over a T-token chunk at positions
    pos..pos+T-1, writing the chunk's K/V into this layer's cache.
    x: (B, T, D); caches: (B, S_max, H_kv, D). T == 1 is plain
    token-at-a-time decoding; T > 1 is speculative verification.

    ``lora_l``: PER-EXAMPLE adapter factors for this layer (the multi-LoRA
    serving path, ``kubetpu.jobs.multi_lora``): a dict of (B, ...) tensors
    keyed ``<target>_a`` / ``<target>_b`` for attention targets — each
    example in the batch applies ITS OWN adapter while the base matmuls
    stay batched."""
    x, (k_cache_l, v_cache_l) = _decode_block_core(
        cfg, layer, x, (k_cache_l, v_cache_l), pos,
        _dense_cache_io(cfg.window), lora_l, lora_scale,
    )
    return x, k_cache_l, v_cache_l


def forward_chunk_io(cfg: ModelConfig, params: Params, tokens, cache, pos,
                     cache_io, lora=None, adapter_ids=None, lora_scale=1.0):
    """THE chunk forward over an arbitrary cache strategy — dense bf16,
    int8, or any future layout plugs in via ``cache_io`` while the outer
    scan, per-layer dequant, LoRA selection, final norm, and head stay
    shared (a tail fix can never land in one cache layout and miss
    another). *cache* is a pytree whose every leaf leads with the layer
    axis. tokens: (B, T) -> (logits (B, T, V) float32, cache)."""
    from kubetpu.jobs.quant import maybe_dequantize

    x = params["embed"][tokens]                        # (B, T, D)

    # per-example factor selection: (N, L, ...) -> (L, B, ...), the layer
    # axis leading so the factors ride the scan with the blocks. An empty
    # dict is a valid leafless scan xs (length comes from the blocks), so
    # the no-lora path shares the ONE scan body.
    sel = {} if lora is None else {
        k: jnp.moveaxis(v[adapter_ids], 1, 0)
        for k, v in lora["blocks"].items()
    }

    def layer_body(carry, inputs):
        x = carry
        layer, cache_l, lora_l = inputs
        # int8 params dequantize PER LAYER here (the scan slices QTensors
        # along the layer axis): the bf16 weights are a loop-body
        # temporary fused into the matmuls, never a whole-tree copy
        layer = maybe_dequantize(layer)
        x, cache_l = _decode_block_core(cfg, layer, x, cache_l, pos,
                                        cache_io, lora_l or None, lora_scale)
        return x, cache_l

    x, cache = jax.lax.scan(layer_body, x, (params["blocks"], cache, sel))
    x = model_lib.rms_norm(x, params["ln_f"])
    head = maybe_dequantize(params["head"])            # per-use dequant
    # float32 logits: matches prefill's and keeps the decode scan carry
    # dtype-stable for bfloat16 model configs
    logits = jnp.einsum("bsd,dv->bsv", x, head).astype(jnp.float32)
    return logits, cache


def forward_chunk(cfg: ModelConfig, params: Params, tokens, k_cache, v_cache,
                  pos, lora=None, adapter_ids=None, lora_scale=1.0):
    """Logits for a T-token chunk fed at positions pos..pos+T-1 through the
    KV cache (T == 1: one decode step; T > 1: speculative verification in a
    single MXU-friendly pass). tokens: (B, T) -> logits (B, T, V) float32;
    caches are updated with the chunk's K/V.

    ``lora`` + ``adapter_ids`` (B,): STACKED adapters (leaves (N, L, ...),
    ``multi_lora.stack_adapters``) with a per-example adapter choice — the
    batched multi-tenant serving path. The (N, ...) gather happens once
    per chunk, then the per-layer factors ride the layer scan."""
    logits, (k_cache, v_cache) = forward_chunk_io(
        cfg, params, tokens, (k_cache, v_cache), pos,
        _dense_cache_io(cfg.window), lora, adapter_ids, lora_scale,
    )
    return logits, k_cache, v_cache


def _forward_one(cfg: ModelConfig, params: Params, token, k_cache, v_cache, pos):
    """Logits for one new token at *pos*, updating the cache.
    token: (B,) int32 -> logits (B, V). (A T=1 chunk — one shared block
    implementation for decode and speculative verification.)"""
    logits, k_cache, v_cache = forward_chunk(
        cfg, params, token[:, None], k_cache, v_cache, pos
    )
    return logits[:, 0], k_cache, v_cache


def _forward_one_with_io(cfg: ModelConfig, params: Params, token, cache, pos,
                         cache_io):
    """One-token forward through an arbitrary cache strategy — a T=1
    ``forward_chunk_io`` (shared tail; nothing re-spelled here)."""
    logits, cache = forward_chunk_io(
        cfg, params, token[:, None], cache, pos, cache_io
    )
    return logits[:, 0], cache


def _prefill_with(cfg: ModelConfig, params: Params, tokens, cache, write,
                  attn_fn=None):
    """THE prefill body: one batched forward over the whole prompt, K/V
    landing in the cache through the *write* hook — the dense and int8
    layouts share everything else (the attn_fn ring hook, the padding
    invariant, the dequant policy), mirroring ``cache_io`` on the decode
    side. Quantized params are dequantized WHOLE here: prefill is one
    compute-bound batched pass through the training forward (which knows
    nothing of QTensors); the bandwidth-critical steady-state decode loop
    keeps its own policy."""
    from kubetpu.jobs.quant import maybe_dequantize

    params = maybe_dequantize(params)
    logits, ks, vs = model_lib.forward_with_kv(params, tokens, cfg,
                                               attn_fn=attn_fn)
    return logits, write(cache, ks, vs)


def prefill(cfg: ModelConfig, params: Params, tokens, k_cache, v_cache,
            attn_fn=None):
    """Fill the cache from one batched forward over the whole prompt (a
    single MXU-friendly pass, not a per-token loop), returning last-position
    logits. tokens: (B, S_prompt). *attn_fn* swaps the attention core —
    pass ``make_ring_attention(mesh)`` (or its flash impl) to shard a LONG
    prompt's prefill over sp; the cache write then gathers the sharded K/V
    into the (unsharded-seq) decode cache automatically under GSPMD.
    NOTE: the ring requires S_prompt to divide evenly by the sp axis size
    (shard_map partitions the sequence axis) — pad the prompt to a multiple
    of sp (pad K/V positions are overwritten before any real query can
    attend them, the serving-bucketing invariant)."""
    def write(cache, ks, vs):
        k_cache, v_cache = cache
        z = (0, 0, 0, 0, 0)
        return (
            jax.lax.dynamic_update_slice(k_cache, ks.astype(k_cache.dtype), z),
            jax.lax.dynamic_update_slice(v_cache, vs.astype(v_cache.dtype), z),
        )

    logits, (k_cache, v_cache) = _prefill_with(
        cfg, params, tokens, (k_cache, v_cache), write, attn_fn
    )
    return logits, k_cache, v_cache


def prefill_int8(cfg: ModelConfig, params: Params, tokens, cache,
                 attn_fn=None):
    """``prefill`` for the int8 cache: the shared ``_prefill_with`` body
    (attn_fn ring hook, padding invariant, dequant policy) with the
    prompt's K/V quantizing into the cache in one shot."""
    from kubetpu.jobs.quant import quantize_kv_chunk

    def write(cache, ks, vs):
        (kq, ksc), (vq, vsc) = cache
        k8, kscale = quantize_kv_chunk(ks)
        v8, vscale = quantize_kv_chunk(vs)
        z = (0, 0, 0, 0, 0)
        return (
            (jax.lax.dynamic_update_slice(kq, k8, z),
             jax.lax.dynamic_update_slice(ksc, kscale, z)),
            (jax.lax.dynamic_update_slice(vq, v8, z),
             jax.lax.dynamic_update_slice(vsc, vscale, z)),
        )

    return _prefill_with(cfg, params, tokens, cache, write, attn_fn)


def make_generate(
    cfg: ModelConfig,
    mesh: Optional[Mesh] = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    kv_int8: bool = False,
):
    """Jitted generate(params, prompt (B, S_p), rng, num_steps) ->
    (B, S_p + num_steps) tokens. Greedy when temperature == 0; top-k /
    nucleus truncation compose with temperature (kubetpu.jobs.sampling).
    ``kv_int8=True`` stores the KV cache in int8 with per-token per-head
    scales (~2x effective cache capacity; ``init_kv_cache_int8``) —
    composable with int8 WEIGHTS (``quant.quantize_params``), which
    quantize the other half of decode's HBM traffic."""
    from kubetpu.jobs.sampling import make_sampler

    sampler = make_sampler(temperature, top_k=top_k, top_p=top_p)

    def _constrain_cache(cache):
        if mesh is None:
            return cache
        # pin the cache layout (batch on dp, kv heads on tp) so the
        # decode scan's cache updates stay local instead of whatever
        # layout GSPMD happens to infer from the prompt; int8 scale
        # leaves share the spec (their head axis is axis 3 too)
        from kubetpu.jobs.train import _filter_spec

        cspec = NamedSharding(mesh, _filter_spec(mesh, kv_cache_specs()))
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, cspec), cache
        )

    def generate(params, prompt, rng, num_steps: int):
        # ONE loop body for both cache layouts: only the (init, prefill,
        # cache_io) triple differs — a sampling/carry fix cannot land in
        # one layout and miss the other (review r5)
        b, s_prompt = prompt.shape
        max_seq = s_prompt + num_steps
        if kv_int8:
            cache = _constrain_cache(init_kv_cache_int8(cfg, b, max_seq))
            logits, cache = prefill_int8(cfg, params, prompt, cache)
            cache_io = _int8_cache_io(cfg.window)
        else:
            cache = _constrain_cache(init_kv_cache(cfg, b, max_seq))
            logits, k_cache, v_cache = prefill(cfg, params, prompt, *cache)
            cache = (k_cache, v_cache)
            cache_io = _dense_cache_io(cfg.window)

        def step(carry, i):
            cache, prev_logits, rng = carry
            rng, sub = jax.random.split(rng)
            token = sampler(prev_logits, sub)
            logits, cache = _forward_one_with_io(
                cfg, params, token, cache, s_prompt + i, cache_io
            )
            return (cache, logits, rng), token

        (_, _, _), generated = jax.lax.scan(
            step, (cache, logits, rng), jnp.arange(num_steps)
        )
        return jnp.concatenate([prompt, generated.T.astype(prompt.dtype)], axis=1)

    jitted = jax.jit(generate, static_argnums=(3,))
    if mesh is None:
        return jitted

    bspec = NamedSharding(mesh, P("dp", None) if "dp" in mesh.axis_names else P())
    return jax.jit(generate, static_argnums=(3,), in_shardings=(None, bspec, None))


def _attend_ring(q, k_ring, v_ring, q_pos, window, first_pos):
    """One-token-chunk attention over a RING-buffer cache. q: (B, 1, H, D);
    rings: (B, W, H_kv, D). Slot j's global position is derivable from
    arithmetic alone — the unique p ≡ j (mod W) in (q_pos - W, q_pos] —
    so no per-slot position buffer rides the scan; a slot is visible iff
    that p has actually been written (p >= *first_pos*, the earliest
    position the ring ever held). Grouped-query aware like
    ``_attend_cached``."""
    b, t, h, d = q.shape
    h_kv = k_ring.shape[2]
    g = h // h_kv
    scale = d ** -0.5
    qg = q.reshape(b, h_kv, g, d).astype(jnp.float32)
    scores = jnp.einsum("bkgd,bskd->bkgs", qg,
                        k_ring.astype(jnp.float32)) * scale
    slots = jnp.arange(window)
    p = q_pos - ((q_pos - slots) % window)     # slot -> global position
    visible = p >= first_pos
    scores = jnp.where(visible[None, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", probs, v_ring.astype(jnp.float32))
    return out.reshape(b, t, h, d).astype(q.dtype)


def _ring_cache_io(window, first_pos):
    """The O(window) ring strategy: write at ``pos % window`` (the
    overwritten entry is by construction outside every later band),
    attend over the W slots. T == 1 chunks only."""
    def io(q, k, v, cache, pos):
        k_l, v_l = cache
        slot = pos % window
        k_l = jax.lax.dynamic_update_slice(k_l, k, (0, slot, 0, 0))
        v_l = jax.lax.dynamic_update_slice(v_l, v, (0, slot, 0, 0))
        return _attend_ring(q, k_l, v_l, pos, window, first_pos), (k_l, v_l)

    return io


def make_rolling_generate(
    cfg: ModelConfig,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
):
    """``make_generate`` for sliding-window models with an O(window) cache:
    the per-layer K/V live in a RING of ``cfg.window`` slots, so
    generation memory is bounded by the window, not the sequence —
    arbitrarily long windowed generation in constant cache memory.
    Token-exact vs ``make_generate`` on the same windowed config (pinned
    by test; keys are roped with ABSOLUTE positions before entering the
    ring, so wraparound changes nothing). The block body is the shared
    ``_decode_block_core`` — only the cache strategy differs from the
    dense path.

    Prefill runs the normal batched forward (compute-bound, its own
    O(S_p) activations) and keeps only the last ``min(S_p, window)``
    roped K/V in the ring."""
    from kubetpu.jobs.quant import maybe_dequantize
    from kubetpu.jobs.sampling import make_sampler

    if cfg.window <= 0:
        raise ValueError("make_rolling_generate needs cfg.window > 0")
    W = cfg.window
    sampler = make_sampler(temperature, top_k=top_k, top_p=top_p)

    def forward_one_ring(params, token, k_rings, v_rings, pos, first_pos):
        x = params["embed"][token][:, None]            # (B, 1, D)
        cache_io = _ring_cache_io(W, first_pos)

        def layer_body(carry, inputs):
            x = carry
            layer, k_l, v_l = inputs
            layer = maybe_dequantize(layer)
            x, (k_l, v_l) = _decode_block_core(
                cfg, layer, x, (k_l, v_l), pos, cache_io
            )
            return x, (k_l, v_l)

        x, (k_rings, v_rings) = jax.lax.scan(
            layer_body, x, (params["blocks"], k_rings, v_rings)
        )
        x = model_lib.rms_norm(x, params["ln_f"])
        head = maybe_dequantize(params["head"])
        logits = jnp.einsum("bsd,dv->bsv", x, head).astype(jnp.float32)
        return logits[:, 0], k_rings, v_rings

    def generate(params, prompt, rng, num_steps: int):
        b, s_p = prompt.shape
        # prefill: one batched windowed forward over DEQUANTIZED params
        # (the training forward knows nothing of QTensors — same contract
        # as prefill()); keep the last min(S_p, W) roped K/V
        logits, ks, vs = model_lib.forward_with_kv(
            maybe_dequantize(params), prompt, cfg
        )
        L = cfg.n_layers
        k_rings = jnp.zeros((L, b, W, cfg.kv_heads, cfg.head_dim), cfg.dtype)
        v_rings = jnp.zeros_like(k_rings)
        keep = min(s_p, W)
        first_pos = s_p - keep  # earliest position the ring ever holds
        src_pos = jnp.arange(first_pos, s_p)           # global positions kept
        slots = src_pos % W
        k_rings = k_rings.at[:, :, slots].set(
            ks[:, :, first_pos:].astype(cfg.dtype))
        v_rings = v_rings.at[:, :, slots].set(
            vs[:, :, first_pos:].astype(cfg.dtype))

        def step(carry, i):
            k_rings, v_rings, prev_logits, rng = carry
            rng, sub = jax.random.split(rng)
            token = sampler(prev_logits, sub)
            logits, k_rings, v_rings = forward_one_ring(
                params, token, k_rings, v_rings, s_p + i, first_pos
            )
            return (k_rings, v_rings, logits, rng), token

        (_, _, _, _), generated = jax.lax.scan(
            step, (k_rings, v_rings, logits, rng), jnp.arange(num_steps)
        )
        return jnp.concatenate([prompt, generated.T.astype(prompt.dtype)],
                               axis=1)

    return jax.jit(generate, static_argnums=(3,))


def forward_chunk_at_io(cfg, params, chunk, cache, pos, cache_io, lora=None,
                        adapter_ids=None, lora_scale=1.0):
    """``forward_chunk_io`` with PER-BATCH positions (vmapped over the
    batch: speculative rounds / serving slots advance each sequence
    unevenly, so the cache offset differs per example). The integer
    ``in_axes`` applies to every leaf of the cache pytree, so any cache
    layout (dense, int8) rides the same vmap."""
    sel = None if lora is None else jax.tree.map(
        lambda t: t[adapter_ids], lora["blocks"]
    )  # (B, L, ...)

    def one(params, chunk, cache_b, p, lsel):
        lora1 = (
            None if lsel is None
            else {"blocks": jax.tree.map(lambda t: t[None], lsel)}
        )
        logits, cache_b = forward_chunk_io(
            cfg, params, chunk[None],
            jax.tree.map(lambda x: x[:, None], cache_b), p, cache_io,
            lora=lora1,
            adapter_ids=None if lora1 is None else jnp.zeros((1,), jnp.int32),
            lora_scale=lora_scale,
        )
        return logits[0], jax.tree.map(lambda x: x[:, 0], cache_b)

    return jax.vmap(
        one,
        in_axes=(None, 0, 1, 0, None if sel is None else 0),
        out_axes=(0, 1),
    )(params, chunk, cache, pos, sel)


def forward_chunk_at(cfg, params, chunk, k_cache, v_cache, pos, lora=None,
                     adapter_ids=None, lora_scale=1.0):
    """``forward_chunk`` with PER-BATCH positions — the dense-cache
    spelling of ``forward_chunk_at_io``."""
    logits, (k_cache, v_cache) = forward_chunk_at_io(
        cfg, params, chunk, (k_cache, v_cache), pos,
        _dense_cache_io(cfg.window), lora, adapter_ids, lora_scale,
    )
    return logits, k_cache, v_cache
