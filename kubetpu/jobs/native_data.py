"""Python side of the native data loader: ctypes over
``_output/libkubetpu_dataio.so`` (see ``kubetpu/dataio/loader.cc``).

``TokenFile`` wraps an mmap'd flat binary corpus of little-endian token
ids; ``batches`` yields (tokens, targets) int32 arrays with targets
shifted by one (reading seq+1-token windows — the same contract as
``jobs.data``'s synthetic corpus, so a train loop swaps sources without
changes). Window offsets are drawn by a seeded numpy RNG on the host; the
gather itself is C-speed over the OS page cache.
"""

from __future__ import annotations

import ctypes
import os
import weakref
from typing import Iterator, Optional, Tuple

import numpy as np

_LIB: Optional[ctypes.CDLL] = None


def _lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        path = os.environ.get(
            "KUBETPU_DATAIO_PATH",
            os.path.join(repo, "_output", "libkubetpu_dataio.so"),
        )
        lib = ctypes.CDLL(path)
        lib.ktpu_open.restype = ctypes.c_void_p
        lib.ktpu_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.ktpu_num_tokens.restype = ctypes.c_longlong
        lib.ktpu_num_tokens.argtypes = [ctypes.c_void_p]
        lib.ktpu_gather.restype = ctypes.c_int
        lib.ktpu_gather.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.ktpu_close.argtypes = [ctypes.c_void_p]
        _LIB = lib
    return _LIB


def write_token_file(path: str, tokens: np.ndarray, dtype=np.uint16) -> None:
    """Serialize a 1-D token array into the loader's flat binary format.
    Refuses ids outside the dtype's range — a silent wraparound would
    produce a corpus that loads fine and trains on scrambled tokens."""
    tokens = np.asarray(tokens)
    info = np.iinfo(dtype)
    if tokens.size and (tokens.min() < info.min or tokens.max() > info.max):
        raise ValueError(
            f"token ids outside {np.dtype(dtype).name} range "
            f"[{info.min}, {info.max}]: min={tokens.min()}, max={tokens.max()}"
        )
    np.ascontiguousarray(tokens, dtype=dtype).tofile(path)


class TokenFile:
    """An mmap'd token corpus served by the native loader."""

    def __init__(self, path: str, dtype_bytes: int = 2):
        if dtype_bytes not in (2, 4):
            raise ValueError("dtype_bytes must be 2 (uint16) or 4 (uint32)")
        self._handle = _lib().ktpu_open(path.encode(), dtype_bytes)
        if not self._handle:
            raise OSError(f"cannot open token file {path!r}")
        self.num_tokens = int(_lib().ktpu_num_tokens(self._handle))
        # GC backstop: a dropped TokenFile must not leak the mmap (a loop
        # over many shards without close() would exhaust address space)
        self._finalizer = weakref.finalize(
            self, _lib().ktpu_close, self._handle
        )

    def close(self) -> None:
        if self._handle:
            self._finalizer.detach()
            _lib().ktpu_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def gather(self, offsets: np.ndarray, seq: int) -> np.ndarray:
        """Rows of ``seq`` tokens at the given token offsets -> (n, seq)
        int32. Out-of-range offsets raise (the C side would skip them —
        silent row loss is worse than an error)."""
        if not self._handle:
            raise ValueError("TokenFile is closed")
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        if offsets.ndim != 1:
            raise ValueError("offsets must be 1-D")
        if ((offsets < 0) | (offsets + seq > self.num_tokens)).any():
            raise ValueError("offset window out of range")
        out = np.empty((len(offsets), seq), np.int32)
        n = _lib().ktpu_gather(
            self._handle,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
            len(offsets),
            seq,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if n != len(offsets):
            raise RuntimeError(f"native gather wrote {n}/{len(offsets)} rows")
        return out

    def batches(
        self, batch: int, seq: int, seed: int = 0,
        worker: int = 0, num_workers: int = 1,
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Endless (tokens, targets) int32 batches; targets are tokens
        shifted by one (seq+1-token windows). Deterministic per seed.

        ``(worker, num_workers)`` shards the corpus for multi-process data
        parallelism: each worker draws windows only from its contiguous
        1/num_workers span of the token stream (disjoint data, not just
        different seeds), with the worker id folded into the RNG. Pass
        ``jax.process_index()/jax.process_count()`` after
        ``initialize_distributed`` (jobs.launch) — the gang launcher's
        workers then read disjoint shards of one corpus file."""
        if not 0 <= worker < num_workers:
            raise ValueError(f"worker {worker} not in [0, {num_workers})")
        # plain `seed` for the single-worker default keeps pre-sharding
        # streams byte-identical (replays of old runs stay reproducible)
        rng = np.random.default_rng(
            seed if num_workers == 1 else (seed, worker))
        span = self.num_tokens // num_workers
        lo = worker * span
        hi = lo + span - (seq + 1)
        if hi < lo:
            raise ValueError("corpus shard shorter than one sequence")
        while True:
            offsets = rng.integers(lo, hi + 1, size=batch)
            rows = self.gather(offsets, seq + 1)
            yield rows[:, :-1], rows[:, 1:]
