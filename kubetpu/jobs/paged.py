"""Paged KV cache: serving memory proportional to LIVE tokens.

The dense serving cache allocates ``(L, n_slots, max_seq, H_kv, D)`` per
slot — a 64-slot x 8k-seq server holds mostly-empty cache (VERDICT r2 weak
#4). The paged design splits the cache into fixed-size PAGES drawn from one
shared pool:

- pool: ``k_pages/v_pages (L, n_pages, page_size, H_kv, D)``;
- per-slot page table ``(n_slots, max_pages_per_slot)`` int32 mapping a
  slot's logical page to a physical pool page (-1 = unmapped);
- the HOST owns allocation (free-list): admission maps just enough pages
  for the prompt, and each decode step maps one more page only when a
  sequence actually crosses a page boundary. Device code stays purely
  functional — the table is just another jit input.

Attention gathers a slot's pages on the fly (XLA gather; the score math is
bit-identical to the dense `_attend_cached`, so greedy decode through
pages matches the dense server EXACTLY — the parity test pins this).
An optional Pallas paged-attention kernel family
(kubetpu.ops.paged_attention, Round-15) streams pages through VMEM
without materializing the gathered cache — or, for kv_int8 pools, the
host-side dequantized f32 copy (the dequant happens per-tile in VMEM):
``use_kernel=True`` now covers f32 AND int8 pools, the banded
(window > 0) decode step, the chunked-prefill chunk, and the
speculative verify chunk. Interpret-mode tests and the ``make
spec-check``/``prefix-check`` kernel arms pin its parity; compiled
validation runs on real TPU via scripts/tpu_smoke.py.

Memory math: a slot costs ``ceil(live_tokens / page_size)`` pages instead
of ``max_seq`` rows — a server provisions the pool for the EXPECTED total
live tokens, not the worst case per slot. ``PagedDecodeServer`` refuses
admission (returns None / parks the queue) when the pool cannot cover a
request's worst case, so decoding never deadlocks mid-sequence.

Reference: none (the reference has no inference stack, SURVEY.md §2);
design follows the public paged-attention pattern (vLLM), re-shaped for
TPU: static shapes, one jitted step, host-side tables.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubetpu.jobs import model as model_lib
from kubetpu.jobs.decode import _lora_in_delta, forward_chunk_io
from kubetpu.jobs.model import ModelConfig, Params
from kubetpu.jobs.prefix_cache import RadixPrefixCache
from kubetpu.jobs.quant import maybe_dequantize, quantize_kv_chunk
from kubetpu.jobs.sampling import chosen_logprob
from kubetpu.jobs.serving import SlotServerBase, _cached_legs


def init_page_pool(
    cfg: ModelConfig, n_pages: int, page_size: int, kv_int8: bool = False
):
    """(k_pages, v_pages), each (L, n_pages, page_size, H_kv, D) — or,
    with ``kv_int8``, each a (values int8, scales f32 (..., H_kv, 1))
    pair: the page pool stores quantized entries (per-token per-head
    scales, ``quant.quantize_kv_chunk``), compounding the pool's
    live-token provisioning with another ~2x per page."""
    shape = (cfg.n_layers, n_pages, page_size, cfg.kv_heads, cfg.head_dim)
    if kv_int8:
        sshape = shape[:-1] + (1,)
        return (
            (jnp.zeros(shape, jnp.int8), jnp.zeros(sshape, jnp.float32)),
            (jnp.zeros(shape, jnp.int8), jnp.zeros(sshape, jnp.float32)),
        )
    return jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype)


def _gather_pages(pages_l, safe):
    """Gather a slot's pages from a dense array or an int8 (values,
    scales) pair — dequant happens on the GATHERED slice only (the
    convert+mul fuses into the attention einsum's read; the full pool is
    never materialized in f32)."""
    if isinstance(pages_l, tuple):
        q8, sc = pages_l
        return q8[safe].astype(jnp.float32) * sc[safe]
    return pages_l[safe]


def _attend_paged(q, k_pages_l, v_pages_l, table, pos, window: int = 0):
    """Attention of a 1-token query per slot against that slot's pages.

    q: (B, H, D); pages: (P, ps, H_kv, D); table: (B, max_pages) int32
    (-1 = unmapped; clamped to 0 for the gather, then masked); pos: (B,)
    index of the query position. Math mirrors decode._attend_cached
    (f32 scores/softmax, grouped-query groups) so paged and dense greedy
    decode agree exactly.

    ``window > 0`` adds the banded mask (key visible iff
    ``0 <= pos - k_pos < window``, the repo-wide convention) — and makes
    the RING page table sound: logical pages aliased onto the same
    physical page differ by >= window positions, so at most one aliased
    copy is ever inside the band; everything else is masked here.
    """
    b, h, d = q.shape
    vals_k = k_pages_l[0] if isinstance(k_pages_l, tuple) else k_pages_l
    ps = vals_k.shape[1]
    h_kv = vals_k.shape[2]
    g = h // h_kv
    max_pages = table.shape[1]
    scale = d ** -0.5

    safe = jnp.maximum(table, 0)
    k = _gather_pages(k_pages_l, safe).reshape(b, max_pages * ps, h_kv, d)
    v = _gather_pages(v_pages_l, safe).reshape(b, max_pages * ps, h_kv, d)

    qg = q.reshape(b, h_kv, g, d).astype(jnp.float32)
    scores = jnp.einsum("bkgd,bskd->bkgs", qg, k.astype(jnp.float32)) * scale
    k_pos = jnp.arange(max_pages * ps)
    mask = k_pos[None, :] <= pos[:, None]                     # (B, S_v)
    if window > 0:
        mask = mask & (pos[:, None] - k_pos[None, :] < window)
    mask = mask & (jnp.repeat(table, ps, axis=1) >= 0)        # unmapped pages
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", probs, v.astype(jnp.float32))
    return out.reshape(b, h, d).astype(q.dtype)


def _write_token_kv(pages_l, new, phys_page, offset):
    """Scatter one token's K or V per slot into its page.
    pages_l: (P, ps, H_kv, D) — or the int8 (values, scales) pair, where
    the token quantizes at write time; new: (B, H_kv, D); phys_page/
    offset: (B,). (The speculative verify chunk reuses this with (B, T)
    index arrays and (B, T, H_kv, D) payloads — the advanced-index
    scatter and the per-token quantization are shape-generic.)
    mode="drop": an INACTIVE slot's table row is -1 (mapped
    to the out-of-bounds sentinel by the caller) — without drop, the
    negative index would wrap and scribble on the last pool page, which
    may belong to a live request."""
    if isinstance(pages_l, tuple):
        q8, sc = pages_l
        n8, ns = quantize_kv_chunk(new)
        return (
            q8.at[phys_page, offset].set(n8, mode="drop"),
            sc.at[phys_page, offset].set(ns, mode="drop"),
        )
    return pages_l.at[phys_page, offset].set(new, mode="drop")


def paged_forward_one(
    cfg: ModelConfig, params: Params, token, k_pages, v_pages, table, pos,
    attend=_attend_paged, write_enable=None, lora=None, adapter_ids=None,
    lora_scale=1.0,
):
    """One decode step for all slots through the page pool.
    token: (B,) int32; pos: (B,) per-slot position of this token;
    table: (B, max_pages). Returns (logits (B, V), k_pages, v_pages).
    *attend* swaps the page-attention core (the Pallas kernel plugs in
    here). The pools may be dense arrays or int8 (values, scales) pairs —
    the write/gather helpers branch, the layer scan carries either.
    *write_enable* (B,) bool drops the K/V write for masked slots — the
    serving step passes ``active`` so an inactive slot never scribbles
    on pages a mid-prefill neighbor has already filled.

    ``lora`` + ``adapter_ids`` (B,): STACKED adapters (leaves (N, L, ...),
    ``multi_lora.stack_adapters``) with a per-example adapter choice — the
    multi-tenant paged serving path. The base matmuls stay batched; each
    example adds its own rank-r delta via two skinny einsums around them
    (``decode._lora_in_delta``'s math, applied OUTSIDE the attention core
    so the fused Pallas kernel path is untouched). The (N, ...) gather
    happens once per call, then per-layer factors ride the scan."""
    vals = k_pages[0] if isinstance(k_pages, tuple) else k_pages
    ps = vals.shape[2]
    n_pool = vals.shape[1]
    phys = jnp.take_along_axis(table, (pos // ps)[:, None], axis=1)[:, 0]
    phys = jnp.where(phys >= 0, phys, n_pool)  # unmapped -> dropped write
    if write_enable is not None:
        phys = jnp.where(write_enable, phys, n_pool)
    offset = pos % ps
    x = params["embed"][token][:, None]                       # (B, 1, D)

    # per-example factor selection, exactly forward_chunk_io's: (N, L, ...)
    # -> (L, B, ...) so the factors ride the scan with the blocks; an empty
    # dict is a valid leafless scan xs, so the no-lora path shares the body
    sel = {} if lora is None else {
        k: jnp.moveaxis(v[adapter_ids], 1, 0)
        for k, v in lora["blocks"].items()
    }

    def proj(name, hh, base, lora_l):
        out = jnp.einsum("bsd,dhk->bshk", hh, base)
        if lora_l is not None and f"{name}_a" in lora_l:
            out = out + _lora_in_delta(
                hh, lora_l[f"{name}_a"], lora_l[f"{name}_b"], lora_scale
            ).astype(out.dtype)
        return out

    def layer_body(carry, inputs):
        x = carry
        layer, k_l, v_l, lora_l = inputs
        lora_l = lora_l or None
        layer = maybe_dequantize(layer)   # per-layer int8 dequant (see quant.py)
        h = model_lib.rms_norm(x, layer["ln1"])
        q = proj("wq", h, layer["wq"], lora_l)
        k = proj("wk", h, layer["wk"], lora_l)
        v = proj("wv", h, layer["wv"], lora_l)
        positions = pos[:, None]
        q = model_lib.rope(q, positions, cfg.rope_theta, cfg.rope_llama3_scaling)
        k = model_lib.rope(k, positions, cfg.rope_theta, cfg.rope_llama3_scaling)
        k_l = _write_token_kv(k_l, k[:, 0], phys, offset)
        v_l = _write_token_kv(v_l, v[:, 0], phys, offset)
        attn = attend(q[:, 0], k_l, v_l, table, pos)
        o = jnp.einsum("bhk,hkd->bd", attn, layer["wo"])
        if lora_l is not None and "wo_a" in lora_l:
            t = jnp.einsum("bhk,bhkr->br", attn, lora_l["wo_a"])
            o = o + (jnp.einsum("br,brd->bd", t, lora_l["wo_b"])
                     * lora_scale).astype(o.dtype)
        x = x + o[:, None]
        h2 = model_lib.rms_norm(x, layer["ln2"])
        delta, _aux = model_lib._mlp(cfg, h2, layer)
        return x + delta, (k_l, v_l)

    x, (k_pages, v_pages) = jax.lax.scan(
        layer_body, x, (params["blocks"], k_pages, v_pages, sel)
    )
    x = model_lib.rms_norm(x, params["ln_f"])
    head = maybe_dequantize(params["head"])
    logits = jnp.einsum("bsd,dv->bsv", x, head).astype(jnp.float32)
    return logits[:, 0], k_pages, v_pages


def _attend_paged_chunk(q, k_pages_l, v_pages_l, table, pos):
    """``_attend_paged`` for a T-token chunk of queries per slot at
    per-slot positions ``pos..pos+T-1`` — the speculative VERIFY read.
    q: (B, T, H, D); pos: (B,) position of q[:, 0]. Per query the score
    math (f32 scores/softmax over the gathered logical view, grouped-
    query groups, positional + unmapped masks) is exactly
    ``_attend_paged``'s, so the verify chunk stays token-exact against
    one-token paged decode. No ``window``: the speculative server
    refuses windowed configs (ring aliasing vs overshoot writes)."""
    b, t, h, d = q.shape
    vals_k = k_pages_l[0] if isinstance(k_pages_l, tuple) else k_pages_l
    ps = vals_k.shape[1]
    h_kv = vals_k.shape[2]
    g = h // h_kv
    max_pages = table.shape[1]
    scale = d ** -0.5

    safe = jnp.maximum(table, 0)
    k = _gather_pages(k_pages_l, safe).reshape(b, max_pages * ps, h_kv, d)
    v = _gather_pages(v_pages_l, safe).reshape(b, max_pages * ps, h_kv, d)

    qg = q.reshape(b, t, h_kv, g, d).astype(jnp.float32)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg,
                        k.astype(jnp.float32)) * scale
    k_pos = jnp.arange(max_pages * ps)
    q_pos = pos[:, None] + jnp.arange(t)                       # (B, T)
    mask = k_pos[None, None, :] <= q_pos[:, :, None]           # (B, T, S)
    mask = mask & (jnp.repeat(table, ps, axis=1) >= 0)[:, None, :]
    scores = jnp.where(mask[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v.astype(jnp.float32))
    return out.reshape(b, t, h, d).astype(q.dtype)


def paged_forward_chunk(
    cfg: ModelConfig, params: Params, tokens, k_pages, v_pages, table, pos,
    write_enable=None, attend_chunk=None, lora=None, adapter_ids=None,
    lora_scale=1.0,
):
    """T-token chunk forward per slot through the page pool at PER-SLOT
    positions ``pos..pos+T-1`` — the speculative VERIFY leg (T = gamma+1;
    ``paged_forward_one`` is the T == 1 decode sibling). tokens: (B, T);
    pos: (B,). Returns (logits (B, T, V) f32, k_pages, v_pages).

    The chunk's K/V scatter COMMITS to the pool first, then the gathered
    logical view is attended under the positional mask — the same
    write-then-read order as one-token decode, so in-chunk causality is
    the mask's job and, with an int8 pool, every query reads the
    DEQUANTIZED QUANTIZED in-chunk entries — exactly what a plain decode
    step would read back — keeping kv_int8 verify token-exact. Rejected
    tokens' entries are never rolled back: positions rewind and the
    position-bounded mask never reads past ``pos``, until the position is
    re-fed and overwritten (jobs.speculative's argument, through pages).
    *write_enable* (B,) bool drops an inactive slot's writes entirely
    (phys -> out-of-bounds sentinel), protecting mid-prefill neighbors'
    pages like the decode step does. *attend_chunk* swaps the chunk
    attention core (``ops.paged_attention_chunk`` plugs in here — same
    write-then-read order, so the kernel reads the committed in-chunk
    entries exactly as the gather core does).

    ``lora`` + ``adapter_ids`` (B,): per-example stacked-adapter deltas,
    exactly ``paged_forward_one``'s — applied around the attention core,
    so the Pallas verify kernel is untouched and the multi-tenant verify
    chunk stays token-exact against multi-tenant one-token decode."""
    if attend_chunk is None:
        attend_chunk = _attend_paged_chunk
    vals = k_pages[0] if isinstance(k_pages, tuple) else k_pages
    ps = vals.shape[2]
    n_pool = vals.shape[1]
    t = tokens.shape[1]
    tpos = pos[:, None] + jnp.arange(t)                        # (B, T)
    phys = jnp.take_along_axis(table, tpos // ps, axis=1)      # (B, T)
    phys = jnp.where(phys >= 0, phys, n_pool)  # unmapped -> dropped write
    if write_enable is not None:
        phys = jnp.where(write_enable[:, None], phys, n_pool)
    offset = tpos % ps
    x = params["embed"][tokens]                                # (B, T, D)

    sel = {} if lora is None else {
        k: jnp.moveaxis(v[adapter_ids], 1, 0)
        for k, v in lora["blocks"].items()
    }

    def proj(name, hh, base, lora_l):
        out = jnp.einsum("bsd,dhk->bshk", hh, base)
        if lora_l is not None and f"{name}_a" in lora_l:
            out = out + _lora_in_delta(
                hh, lora_l[f"{name}_a"], lora_l[f"{name}_b"], lora_scale
            ).astype(out.dtype)
        return out

    def layer_body(carry, inputs):
        x = carry
        layer, k_l, v_l, lora_l = inputs
        lora_l = lora_l or None
        layer = maybe_dequantize(layer)
        h = model_lib.rms_norm(x, layer["ln1"])
        q = proj("wq", h, layer["wq"], lora_l)
        k = proj("wk", h, layer["wk"], lora_l)
        v = proj("wv", h, layer["wv"], lora_l)
        q = model_lib.rope(q, tpos, cfg.rope_theta, cfg.rope_llama3_scaling)
        k = model_lib.rope(k, tpos, cfg.rope_theta, cfg.rope_llama3_scaling)
        k_l = _write_token_kv(k_l, k, phys, offset)   # (B, T) scatter
        v_l = _write_token_kv(v_l, v, phys, offset)
        attn = attend_chunk(q, k_l, v_l, table, pos)
        o = jnp.einsum("bshk,hkd->bsd", attn, layer["wo"])
        if lora_l is not None and "wo_a" in lora_l:
            tt = jnp.einsum("bshk,bhkr->bsr", attn, lora_l["wo_a"])
            o = o + (jnp.einsum("bsr,brd->bsd", tt, lora_l["wo_b"])
                     * lora_scale).astype(o.dtype)
        x = x + o
        h2 = model_lib.rms_norm(x, layer["ln2"])
        delta, _aux = model_lib._mlp(cfg, h2, layer)
        return x + delta, (k_l, v_l)

    x, (k_pages, v_pages) = jax.lax.scan(
        layer_body, x, (params["blocks"], k_pages, v_pages, sel)
    )
    x = model_lib.rms_norm(x, params["ln_f"])
    head = maybe_dequantize(params["head"])
    logits = jnp.einsum("bsd,dv->bsv", x, head).astype(jnp.float32)
    return logits, k_pages, v_pages


def _paged_prefill_io(write_phys, gather_row, ps: int, window: int,
                      attend_chunk=None):
    """The PAGE-POOL cache strategy for a prefill chunk: scatter the
    chunk's K/V into its (page-aligned) physical pages, then attend the
    chunk's queries through the slot's gathered logical pages — so
    ``decode.forward_chunk_io`` (THE chunk forward) runs unchanged over
    the pool, and chunked paged prefill shares one block implementation
    with every other cache layout. The attention math is the dense
    ``_attend_cached`` over the logical-order gather, which is exactly
    the masked score math ``_attend_paged`` computes — paged prefill
    stays token-exact against the dense server.

    Attention order matters on a ring: the pool is gathered BEFORE the
    chunk's writes (so the pre-chunk window tail is still resident — the
    chunk's pages would evict it), the chunk's own K/V is PATCHED into
    the gathered contiguous view at its positions, and only then does the
    scatter commit the chunk to the pool for later chunks and decode.
    The attended view is therefore a contiguous position-ordered cache —
    the dense ``_attend_cached`` math, so paged prefill stays token-exact
    against the dense server.

    *write_phys* (n_write,): physical page per chunk page, with dropped
    pages (pad-only, or ring-aliased duplicates — the host keeps only the
    last logical occurrence) pointed out of bounds so the scatter drops
    them. *gather_row*: a PREFIX of the slot's logical table just
    covering the chunk's visible positions (the host rounds it to a
    power-of-two page count so compile entries stay bounded) — attending
    the full max_seq view would charge every admission for the slot's
    worst case. Unmapped (-1) rows gather page 0 and are killed by the
    positional mask (their logical positions exceed every chunk query),
    aliased stale ring rows by the window band — the same soundness
    argument the decode-side ring table relies on. int8 pools quantize at write with
    the same per-token per-head scales as ``_int8_cache_io`` — and the
    patched in-chunk view is the DEQUANTIZED quantized chunk, exactly
    what the int8 dense server's attention reads — so the pool receives
    bit-identical entries and emits bit-identical attention.

    *attend_chunk* (non-windowed configs only): the fused Pallas chunk
    kernel. The scatter COMMITS first and the chunk's queries attend
    THROUGH the gathered-prefix table — sound off a ring because the
    chunk's pages are disjoint from every earlier page, so the committed
    view at every position a real query can see is exactly the patched
    contiguous view (int8: the kernel's in-VMEM dequant of the committed
    chunk IS the dequantized-quantized patch). Windowed (ring) configs
    keep the gather-before-write order and never take this path."""
    from kubetpu.jobs.decode import _attend_cached

    n_write = write_phys.shape[0]

    def split(pages_l, new):
        """(pool write payload, contiguous attend payload) for one chunk."""
        if isinstance(pages_l, tuple):
            n8, ns = quantize_kv_chunk(new)
            return (n8, ns), (n8.astype(jnp.float32) * ns)
        return new.astype(pages_l.dtype), new

    def scatter(pages_l, payload):
        if isinstance(pages_l, tuple):
            q8, sc = pages_l
            n8, ns = payload
            return (
                q8.at[write_phys].set(
                    n8[0].reshape(n_write, ps, *n8.shape[2:]), mode="drop"),
                sc.at[write_phys].set(
                    ns[0].reshape(n_write, ps, *ns.shape[2:]), mode="drop"),
            )
        return pages_l.at[write_phys].set(
            payload[0].reshape(n_write, ps, *payload.shape[2:]), mode="drop")

    if attend_chunk is not None:
        def io(q, k, v, cache, pos):
            k_l, v_l = cache
            k_pool, _k_att = split(k_l, k)
            v_pool, _v_att = split(v_l, v)
            k_l = scatter(k_l, k_pool)
            v_l = scatter(v_l, v_pool)
            attn = attend_chunk(q, k_l, v_l, gather_row[None],
                                jnp.reshape(pos, (1,)).astype(jnp.int32))
            return attn, (k_l, v_l)

        return io

    def io(q, k, v, cache, pos):
        k_l, v_l = cache
        k_pool, k_att = split(k_l, k)
        v_pool, v_att = split(v_l, v)
        safe = jnp.maximum(gather_row, 0)
        kk = _gather_pages(k_l, safe)       # (max_pages, ps, H_kv, D)
        vv = _gather_pages(v_l, safe)
        kk = kk.reshape(1, -1, *kk.shape[2:])
        vv = vv.reshape(1, -1, *vv.shape[2:])
        kk = jax.lax.dynamic_update_slice(
            kk, k_att.astype(kk.dtype), (0, pos, 0, 0))
        vv = jax.lax.dynamic_update_slice(
            vv, v_att.astype(vv.dtype), (0, pos, 0, 0))
        attn = _attend_cached(q, kk, vv, pos, window=window)
        return attn, (scatter(k_l, k_pool), scatter(v_l, v_pool))

    return io


def _build_paged_legs(cfg_, page_size, attend, attend_chunk=None,
                      lora_scale=1.0):
    """(prefill_chunk, step_all) jits for the page-pool server — shared
    across same-key servers via ``serving._cached_legs`` (the legs are
    pure functions of their arguments). *attend_chunk* (use_kernel,
    non-windowed) fuses the prefill chunk's attention through the page
    table too. The trailing (lora, aid/aids) pair is the multi-LoRA hook
    (``multi_lora.PagedMultiLoraDecodeServer``): None/zeros for the plain
    server — an empty pytree arg, zero trace cost — mirroring
    ``serving._build_dense_legs``."""
    from kubetpu.jobs.sampling import make_slot_sampler

    sampler = make_slot_sampler()
    ps_ = page_size
    window_ = cfg_.window

    @partial(jax.jit, donate_argnums=(1, 2))
    def step_all(params, k_pages, v_pages, table, last, pos, active,
                 reqkeys, temp, tk, tp, lora, aids):
        logits, k_pages, v_pages = paged_forward_one(
            cfg_, params, last, k_pages, v_pages, table, pos,
            attend=attend, write_enable=active,
            lora=lora, adapter_ids=aids, lora_scale=lora_scale,
        )
        keys = jax.vmap(jax.random.fold_in)(reqkeys, pos)
        nxt = sampler(logits, keys, temp, tk, tp)
        nxt = jnp.where(active, nxt, last)
        lp = chosen_logprob(logits, nxt)
        pos = pos + active.astype(jnp.int32)
        return k_pages, v_pages, nxt, pos, lp

    @partial(jax.jit, donate_argnums=(1, 2))
    def prefill_chunk(params, k_pages, v_pages, chunk, write_phys, row,
                      pos, last_idx, reqkey, temp, tk, tp, lora, aid):
        # the chunk forward THROUGH the pool: forward_chunk_io over
        # the paged cache strategy (module docstring) — one compile
        # per chunk length serves every offset and every slot
        io = _paged_prefill_io(write_phys, row, ps_, window_,
                               attend_chunk=attend_chunk)
        logits, (k_pages, v_pages) = forward_chunk_io(
            cfg_, params, chunk[None], (k_pages, v_pages), pos, io,
            lora=lora, adapter_ids=None if lora is None else aid[None],
            lora_scale=lora_scale,
        )
        r = jnp.take(logits[0], last_idx, axis=0)
        tok = sampler(r, jax.random.fold_in(reqkey, pos + last_idx),
                      temp, tk, tp)
        return k_pages, v_pages, tok, chosen_logprob(r, tok)

    return prefill_chunk, step_all


class PagedDecodeServer(SlotServerBase):
    """Continuous batching over a paged KV cache — same public surface as
    ``serving.DecodeServer`` (the request lifecycle IS serving's
    ``SlotServerBase``; only the device legs differ), cache memory
    proportional to live tokens.

    ``n_pages`` provisions the shared pool; a DECODING request always
    holds its worst case (prompt + max_new_tokens), so it never starves
    mid-flight — and a request whose worst case exceeds the WHOLE pool is
    rejected up front by ``_check_prompt`` (otherwise it would park the
    queue head forever). ``pages_in_use()`` and ``pool_pages`` expose the
    accounting the memory test pins.

    With ``prefill_budget > 0`` the prompt streams in as page-aligned
    chunks and the reservation is CHUNK-GRANULAR during the prefill
    phase: a mid-prefill slot holds pages only for the tokens written so
    far (the final chunk upgrades to the decode worst case), so a long
    admission no longer locks worst-case pages away from its decoding
    neighbors. A chunk that cannot get its pages parks until retirements
    free some; if every holder is itself a parked prefill (nothing will
    ever free), the scheduler sends all but the oldest back to the queue
    with their pages released — no deadlock, no leak.

    ``prefix_cache_pages > 0`` turns on SHARED-PREFIX KV REUSE
    (``kubetpu.jobs.prefix_cache``): on admission the server matches the
    longest cached full-page prefix of the prompt in a host-side radix
    tree, maps the shared physical pages into the slot's page table
    READ-ONLY (they form the leading prefix of the table; every write the
    slot ever issues lands past them — the structural copy-on-write
    rule), and starts prefill at ``pos = matched_tokens``. On retire the
    slot's full prompt pages are PUBLISHED into the tree (ownership
    donated — no device copy), bounded by the ``prefix_cache_pages``
    budget with LRU eviction of unpinned branches; under pool pressure
    ``_alloc_pages`` reclaims evictable tree pages before refusing, so
    admission never deadlocks while the tree holds reclaimable pages.
    Greedy decode through a cache hit is token-exact vs a cold run
    (pinned by test); ``check_invariants()`` is the pool accounting
    oracle (free + slot-owned + tree-owned == n_pages, refcounts
    consistent). Incompatible with windowed (``cfg.window > 0``) serving:
    the ring table aliases logical pages onto a per-slot physical ring,
    which cannot be shared across slots.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params: Params,
        n_slots: int = 8,
        max_seq: int = 512,
        max_new_tokens: int = 64,
        page_size: int = 16,
        n_pages: Optional[int] = None,
        eos_id: Optional[int] = None,
        use_kernel: bool = False,
        interpret: bool = False,
        pages_per_block: int = 1,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        seed: int = 0,
        mesh=None,
        kv_int8: bool = False,
        prefill_budget: int = 0,
        overlap: bool = False,
        queue_ttl: Optional[float] = None,
        prefix_cache_pages: int = 0,
        pool_frac: float = 1.0,
        host_tier_bytes: int = 0,
    ) -> None:
        if not 0.0 < pool_frac <= 1.0:
            raise ValueError("pool_frac must be in (0, 1]")
        if prefix_cache_pages < 0:
            raise ValueError("prefix_cache_pages must be >= 0 (0 = off)")
        if host_tier_bytes < 0:
            raise ValueError("host_tier_bytes must be >= 0 (0 = off)")
        if host_tier_bytes and not prefix_cache_pages:
            raise ValueError(
                "host_tier_bytes needs prefix_cache_pages > 0 — the host "
                "tier spills FROM the HBM prefix tree")
        if prefix_cache_pages and cfg.window > 0:
            raise ValueError(
                "prefix_cache_pages is incompatible with windowed serving: "
                "the ring table aliases logical pages onto a per-slot "
                "physical ring, which cannot be shared across slots"
            )
        super().__init__(cfg, params, n_slots, max_seq, max_new_tokens,
                         eos_id, temperature=temperature, top_k=top_k,
                         top_p=top_p, seed=seed,
                         prefill_budget=prefill_budget, overlap=overlap,
                         queue_ttl=queue_ttl)
        self.page_size = page_size
        self._min_bucket = page_size  # bucket >= one page keeps shapes few
        # _seq_margin(): extra positions past max_seq a slot's table must
        # cover (0 here; the speculative server's verify chunk overshoots
        # by up to gamma_max tokens per round)
        self.max_pages_per_slot = (
            max_seq + self._seq_margin() + page_size - 1) // page_size
        # Windowed (banded) serving: a slot's LOGICAL pages map onto a
        # small physical RING of ceil(window/ps) + 1 pages (table entry
        # lp -> ring[lp % ring]). Soundness: ring * ps >= window + ps, so
        # the token overwritten at position p sits at p - ring*ps <=
        # p - window - 1 — already outside every future band — and any
        # aliased stale read is outside the band too, killed by the
        # windowed mask in _attend_paged. Cache memory per slot becomes
        # O(window) however long the sequence runs — the paged pool and
        # the O(window) cache COMPOUND (VERDICT r4 #4/#5).
        self._ring_pages = (
            self._pages_needed(cfg.window) + 1 if cfg.window > 0 else 0
        )
        # default pool: HALF the dense equivalent — the win is configurable,
        # callers size it to expected live tokens.
        # Round-18 vChips: ``pool_frac`` is this replica's share of the
        # chip's HBM budget (KUBETPU_VCHIP_MILLI / 1000 when launched on a
        # fractional allocation) — the pool is SIZED to the share, so N
        # packed replicas on one chip partition the page budget honestly
        # and the router's /load free-pages signal reflects the partition.
        self.pool_frac = float(pool_frac)
        base_pages = n_pages or (n_slots * self.max_pages_per_slot + 1) // 2
        self.pool_pages = max(1, int(base_pages * self.pool_frac))
        self.kv_int8 = kv_int8
        self.k_pages, self.v_pages = init_page_pool(
            cfg, self.pool_pages, page_size, kv_int8=kv_int8
        )
        if mesh is not None:
            # Multi-chip paged serving: params tensor-parallel (training's
            # specs), pool pages sharded on kv heads over tp. The PAGE axis
            # stays unsharded — the host allocator hands pages to any slot,
            # so a page split would turn every table gather cross-device;
            # the kv-head split keeps gathers local (pairs with the dense
            # server's layout, serving.DecodeServer).
            from jax.sharding import NamedSharding, PartitionSpec as P

            from kubetpu.jobs.train import _filter_spec, _shardings, param_specs

            self.params = jax.device_put(
                params, _shardings(mesh, param_specs(cfg)))
            psh = NamedSharding(
                mesh, _filter_spec(mesh, P(None, None, None, "tp", None)))
            # int8 pools are (values, scales) pairs; the scale leaves'
            # head axis is axis 3 too, so one spec serves every leaf
            self.k_pages = jax.tree.map(
                lambda x: jax.device_put(x, psh), self.k_pages)
            self.v_pages = jax.tree.map(
                lambda x: jax.device_put(x, psh), self.v_pages)
        self._free: List[int] = list(range(self.pool_pages))
        self._table = np.full((n_slots, self.max_pages_per_slot), -1, np.int32)
        self._host_len = [0] * n_slots          # tokens stored per slot
        # pool-pressure gauges (Round-8): scraped alongside the base
        # class's slot/queue gauges via metrics_text / the obs exporter
        self.obs.gauge_fn("kubetpu_serving_pool_pages",
                          lambda: self.pool_pages)
        self.obs.gauge_fn("kubetpu_serving_pages_in_use",
                          lambda: self.pages_in_use())
        self.obs.gauge_fn("kubetpu_serving_pages_free",
                          lambda: len(self._free))
        # Round-18: this replica's vChip share of the chip pool (1.0 =
        # whole-chip replica) — lets federated dashboards tell a small
        # pool from a starved one
        self.obs.gauge_fn("kubetpu_serving_pool_frac",
                          lambda: self.pool_frac)
        # -- shared-prefix KV reuse (Round-9): host-side radix tree over
        # token prefixes whose nodes OWN pool pages; per-slot: how many
        # leading table rows are shared (read-only) mappings, the pinned
        # deepest-match node, and the prompt to publish at retirement
        self.prefix_cache_pages = int(prefix_cache_pages)
        # Round-19: byte budget for the eviction-to-host DRAM tier (0 =
        # off) — LRU victims spill their stored-layout pages into host
        # buffers instead of dropping, and a later match fills them back
        self.host_tier_bytes = int(host_tier_bytes)
        self._prefix_cache = (
            RadixPrefixCache(page_size, self.prefix_cache_pages,
                             host_budget_bytes=self.host_tier_bytes)
            if self.prefix_cache_pages else None
        )
        self._slot_shared = [0] * n_slots
        self._slot_pin = [None] * n_slots
        self._slot_prompt: List[Optional[List[int]]] = [None] * n_slots
        # (matched, start) from the slot's LAST _prefill_start, committed
        # to the reuse counters only when the admission completes
        self._slot_pending_stats: List[Optional[Tuple[int, int, int]]] = (
            [None] * n_slots)
        if self._prefix_cache is not None:
            self._c_hit_tokens = self.obs.counter(
                "kubetpu_prefix_hit_tokens_total",
                "full-page prefix tokens found cached at admission")
            self._c_saved_tokens = self.obs.counter(
                "kubetpu_prefill_tokens_saved_total",
                "prompt tokens whose prefill was skipped via mapped "
                "shared pages")
            self._c_req_hit = self.obs.counter(
                "kubetpu_prefix_requests_total", result="hit")
            self._c_req_miss = self.obs.counter(
                "kubetpu_prefix_requests_total", result="miss")
            self._c_evicted = self.obs.counter(
                "kubetpu_prefix_evicted_pages_total")
            self._c_inserted = self.obs.counter(
                "kubetpu_prefix_inserted_pages_total")
            self.obs.gauge_fn("kubetpu_prefix_tree_pages",
                              lambda: self._prefix_cache.total_pages)
            self.obs.gauge_fn("kubetpu_prefix_tree_nodes",
                              lambda: self._prefix_cache.n_nodes())
            # Round-19 tier counters: per-tier pages hit at admission,
            # pages filled back into the pool, pages spilled out of it,
            # and bytes moved across each tier boundary
            self._c_tier_hits = {
                t: self.obs.counter("kubetpu_prefix_tier_hits_total",
                                    tier=t)
                for t in ("hbm", "host", "peer")}
            self._c_tier_fills = {
                t: self.obs.counter("kubetpu_prefix_tier_fills_total",
                                    tier=t)
                for t in ("host", "peer")}
            self._c_tier_spills = {
                "host": self.obs.counter(
                    "kubetpu_prefix_tier_spills_total", tier="host")}
            self._c_tier_bytes = {
                t: self.obs.counter("kubetpu_prefix_tier_bytes_total",
                                    tier=t)
                for t in ("hbm", "host", "peer")}
            self._c_tier_saved = {
                t: self.obs.counter(
                    "kubetpu_prefix_tier_tokens_saved_total", tier=t)
                for t in ("hbm", "host", "peer")}
            self.obs.gauge_fn("kubetpu_prefix_host_bytes",
                              lambda: self._prefix_cache.host_bytes)
            self.obs.gauge_fn("kubetpu_prefix_host_nodes",
                              lambda: len(self._prefix_cache.host_nodes()))

        # -- attention cores (Round-15): under use_kernel the decode step
        # AND the chunk paths (prefill, speculative verify) walk the page
        # table in one fused Pallas kernel — f32 or int8 pools, banded
        # (window > 0) decode included. Windowed chunked prefill keeps
        # the gather core: its gather-before-write order is what makes
        # the ring sound, and prefill is not the per-token hot path.
        if pages_per_block < 1:
            raise ValueError("pages_per_block must be >= 1")
        self.use_kernel = bool(use_kernel)
        self.interpret = bool(interpret)
        # the pagedtune-swept VMEM tile: pages walked per kernel grid
        # step (applies only under use_kernel; 1 is the shipped default)
        self.pages_per_block = int(pages_per_block)
        attend = partial(_attend_paged, window=cfg.window)
        attend_chunk = None
        if use_kernel:
            from kubetpu.ops.paged_attention import (
                paged_attention,
                paged_attention_chunk,
            )

            attend = partial(paged_attention, window=cfg.window,
                             pages_per_block=self.pages_per_block,
                             interpret=interpret)
            if cfg.window == 0:
                attend_chunk = partial(paged_attention_chunk,
                                       pages_per_block=self.pages_per_block,
                                       interpret=interpret)
        self._attend_chunk = attend_chunk
        if use_kernel:
            # kernel adoption + the HBM win, on the serving registry: the
            # gather core materializes (B, max_pages*ps, H_kv, D) f32 x2
            # (K, V) x L per attention call; the kernel streams pages
            # through VMEM instead — count that buffer as saved per leg
            self._kernel_bytes_saved = (
                2 * cfg.n_layers * n_slots * self.max_pages_per_slot
                * page_size * cfg.kv_heads * cfg.head_dim * 4
            )
            self._c_kernel_steps = self.obs.counter(
                "kubetpu_paged_kernel_steps_total",
                "decode/verify legs served by the fused paged-attention "
                "kernel")
            self._c_kernel_bytes = self.obs.counter(
                "kubetpu_paged_kernel_hbm_bytes_saved_total",
                "gathered-KV materialization bytes the kernel did not "
                "write+read (f32 gather buffer per attention leg)")

        lora_scale = getattr(self, "_lora_scale", 1.0)
        self._prefill_chunk, self._step_all = _cached_legs(
            ("paged", cfg, page_size, kv_int8, use_kernel, interpret,
             self.pages_per_block, float(lora_scale)),
            lambda: _build_paged_legs(cfg, page_size, attend, attend_chunk,
                                      lora_scale),
        )

    # -- page accounting -----------------------------------------------------

    def pages_in_use(self) -> int:
        return self.pool_pages - len(self._free)

    def _pages_needed(self, n_tokens: int) -> int:
        return (n_tokens + self.page_size - 1) // self.page_size

    def _seq_margin(self) -> int:
        """Positions past ``max_seq`` every slot's reservation (and the
        table width) must additionally cover — 0 for one-token decode;
        the speculative subclass returns ``gamma_max`` (a verify chunk
        writes up to gamma tokens past the final accepted position)."""
        return 0

    def _worst_case_tokens(self, prompt_len: int) -> int:
        return prompt_len + self.max_new_tokens + 1 + self._seq_margin()

    def _alloc_pages(self, slot: int, upto_tokens: int) -> bool:
        """Map pages so slot can hold *upto_tokens* tokens; False if the
        pool is exhausted (caller must not admit). Windowed configs map a
        physical ring and alias every logical page onto it (see
        ``_ring_pages``) — the pool cost per slot is the ring, not the
        sequence length. With a prefix cache, pool pressure first
        RECLAIMS evictable (unpinned, LRU) tree pages into the free list
        — admission must never park while the tree is hoarding
        reclaimable pages. Shared (tree-owned) pages already mapped into
        the slot count toward ``have``: the slot only allocates the
        uncached suffix."""
        need = self._pages_needed(upto_tokens)
        if self._ring_pages:
            if (self._table[slot] >= 0).any():
                # the slot already holds its mapped ring (a resumed
                # chunked prefill, or a buggy re-admission) — popping a
                # fresh ring here would LEAK the mapped physical pages;
                # the existing aliased mapping already covers every
                # logical page, so this is a no-op, mirroring the
                # non-ring branch's `have` handling
                return True
            phys_need = min(need, self._ring_pages)
            if phys_need > len(self._free):
                return False
            ring = [self._free.pop() for _ in range(phys_need)]
            for lp in range(need):
                self._table[slot, lp] = ring[lp % phys_need]
            self._invalidate_dev("table")
            return True
        have = int((self._table[slot] >= 0).sum())
        short = (need - have) - len(self._free)
        if short > 0 and self._prefix_cache is not None:
            self._tree_reclaim(short, reason="pool_pressure")
        if need - have > len(self._free):
            return False
        if need > have:
            # only a real mapping dirties the device mirror: the no-op
            # paths (pages already cover the chunk, pool-exhausted False)
            # must not force the next step to re-upload the table
            for lp in range(have, need):
                self._table[slot, lp] = self._free.pop()
            self._invalidate_dev("table")
        return True

    def _release_pages(self, slot: int, keep=()) -> None:
        """Unmap the slot's table; slot-OWNED pages return to the free
        list. Leading shared rows (``_slot_shared``) are tree property —
        cleared from the table but never freed here; *keep* pages were
        just DONATED to the tree by ``_publish_prefix`` (ownership moved,
        not freed)."""
        shared = self._slot_shared[slot]
        self._invalidate_dev("table")
        freed = set()  # ring tables alias: free each physical page once
        for lp in range(self.max_pages_per_slot):
            phys = int(self._table[slot, lp])
            if (phys >= 0 and phys not in freed and lp >= shared
                    and phys not in keep):
                self._free.append(phys)
                freed.add(phys)
            self._table[slot, lp] = -1
        self._slot_shared[slot] = 0

    # -- lifecycle hooks -----------------------------------------------------

    def _check_prompt(self, prompt: List[int]) -> None:
        super()._check_prompt(prompt)
        need = self._pages_needed(self._worst_case_tokens(len(prompt)))
        if self._ring_pages:
            need = min(need, self._ring_pages)
        if need > self.pool_pages:
            # accepted-but-never-admittable would park the queue head
            # forever and starve everything behind it
            raise ValueError(
                f"request needs {need} pages worst-case but the pool has "
                f"only {self.pool_pages} — raise n_pages or lower "
                f"max_new_tokens"
            )

    def _note_admitted(self, slot: int, prompt: List[int]) -> None:
        self._host_len[slot] = len(prompt) + 1
        # prompt held for retirement-time PUBLICATION into the prefix
        # tree; only set once the prefill COMPLETED (an aborted/parked
        # prefill never reaches here, so its half-written pages are
        # never published)
        self._slot_prompt[slot] = list(prompt)
        # reuse counters COMMIT here — once per completed admission, not
        # per attempt (a pool-starved monolithic admission re-runs
        # ``_prefill_start`` every step until it fits; counting attempts
        # would inflate saved-token/hit-rate numbers with work that was
        # never actually skipped)
        pending = self._slot_pending_stats[slot]
        if pending is not None:
            matched, start, host_tokens = pending
            if start > 0:
                self._c_req_hit.inc()
                self._c_hit_tokens.inc(matched)
                self._c_saved_tokens.inc(start)
                # Round-19 tier attribution: tokens promoted from the
                # host tier DURING this admission are host-tier savings;
                # the rest of the mapped prefix was already HBM-resident
                ps = self.page_size
                self._c_tier_hits["host"].inc(host_tokens // ps)
                self._c_tier_saved["host"].inc(host_tokens)
                self._c_tier_hits["hbm"].inc((start - host_tokens) // ps)
                self._c_tier_saved["hbm"].inc(start - host_tokens)
            else:
                self._c_req_miss.inc()
            self._slot_pending_stats[slot] = None

    def _note_emitted(self, slot: int) -> None:
        self._host_len[slot] += 1

    def _on_retire(self, slot: int) -> None:
        self._host_len[slot] = 0
        published = self._publish_prefix(slot)
        self._release_pages(slot, keep=published)  # rest back to the pool
        if self._slot_pin[slot] is not None:
            self._prefix_cache.release(self._slot_pin[slot])
            self._slot_pin[slot] = None
        self._slot_prompt[slot] = None
        self._slot_pending_stats[slot] = None   # parked prefill: no commit

    # -- shared-prefix KV reuse (Round-9) ------------------------------------

    def _prefill_start(self, prompt: List[int], slot: int) -> int:
        """Prefix-cache admission hook (base: 0): match the longest
        cached full-page prefix, map its physical pages READ-ONLY as the
        slot's leading table rows, pin the deepest matched node for the
        slot's lifetime, and return the matched token count — prefill
        starts there. The match is capped one token short of the prompt:
        the last prompt token must be FORWARDED (not just cached) to
        produce the logits that sample the first new token — its page, if
        cached, is recomputed into a private page instead of written into
        (the COW boundary rule).

        Round-19: host-tier spans covering the prompt are FILLED back
        into the pool first (``_fill_host_prefix``, a barrier leg), so
        the HBM match below sees them — a warm-host admission starts at
        the same ``pos`` a warm-HBM one would, token-exact vs cold."""
        if self._prefix_cache is None:
            return 0
        ps = self.page_size
        host_tokens = self._fill_host_prefix(prompt)
        matched, pages, node = self._prefix_cache.match(prompt)
        start = min(matched, ((len(prompt) - 1) // ps) * ps)
        if start <= 0:
            self._slot_pending_stats[slot] = (matched, 0, 0)
            return 0
        use = start // ps
        self._table[slot, :use] = np.asarray(pages[:use], np.int32)
        self._invalidate_dev("table")
        self._slot_shared[slot] = use
        self._prefix_cache.pin(node)
        self._slot_pin[slot] = node
        self._slot_pending_stats[slot] = (matched, start,
                                          min(host_tokens, start))
        self.events.emit("prefix_hit", slot=slot, matched_tokens=matched,
                         prefill_start=start, pages=use,
                         host_filled_tokens=min(host_tokens, start))
        return start

    def _prefix_unmap(self, slot: int) -> None:
        """Roll back a ``_prefill_start`` mapping after a FAILED
        monolithic admission (nothing may stay mutated — the request
        returns to the queue and the slot must read as empty)."""
        self._release_pages(slot)   # shared rows cleared, nothing freed
        if self._slot_pin[slot] is not None:
            self._prefix_cache.release(self._slot_pin[slot])
            self._slot_pin[slot] = None
        self._slot_pending_stats[slot] = None

    def _publish_prefix(self, slot: int):
        """Donate the retiring slot's full prompt pages into the tree
        (the pages already hold exactly the prompt's KV — publication is
        pure host bookkeeping). Budget-bounded: evicts LRU unpinned
        branches to make room, then truncates the donation to what fits.
        Returns the set of donated physical pages (``_release_pages``
        must not free them)."""
        prompt = self._slot_prompt[slot]
        if self._prefix_cache is None or not prompt:
            return ()
        ps = self.page_size
        full = len(prompt) // ps
        if full <= 0:
            return ()
        tokens = prompt[:full * ps]
        pages = [int(self._table[slot, j]) for j in range(full)]
        if any(p < 0 for p in pages):   # defensive: never publish holes
            return ()
        tree = self._prefix_cache
        need = tree.missing_pages(tokens)
        over = tree.total_pages + need - tree.max_pages
        if over > 0:
            self._tree_reclaim(over, reason="budget")
        consumed = tree.insert(tokens, pages)
        if consumed:
            self._c_inserted.inc(len(consumed))
            self.events.emit("prefix_publish", slot=slot,
                             pages=len(consumed))
        return consumed

    # -- tiered KV cache: HBM -> host DRAM -> peer replicas (Round-19) -------

    def _tree_reclaim(self, n_pages: int, reason: str) -> List[int]:
        """Evict >= *n_pages* from the prefix tree into the free list —
        the one reclaim path pool pressure and the publish budget share.
        With the host tier on, victims SPILL: their stored-layout KV is
        gathered into host buffers under ``host_tier_bytes`` before the
        pages free, so the prefix survives eviction at host-DRAM cost.
        A BARRIER leg — the spill gather is its designed device->host
        sync; steady-state ``step()`` never reaches here."""
        tree = self._prefix_cache
        gather = None
        if self.host_tier_bytes > 0:
            def gather(phys):
                payload = self._gather_phys_pages(phys)
                self._c_tier_bytes["host"].inc(
                    sum(a.nbytes for a in payload.values()))
                return payload
        before = tree.spilled_pages
        reclaimed = tree.evict(n_pages, gather=gather)
        spilled = tree.spilled_pages - before
        if spilled:
            self._c_tier_spills["host"].inc(spilled)
            self.events.emit("prefix_spill", pages=spilled, reason=reason)
        if reclaimed:
            self._free.extend(reclaimed)
            self._c_evicted.inc(len(reclaimed))
            self.events.emit("prefix_evict", pages=len(reclaimed),
                             reason=reason)
        return reclaimed

    def _fill_host_prefix(self, prompt: List[int]) -> int:
        """Promote host-tier spans covering *prompt* back into the pool
        (top-down along the match path, keeping the tier frontier) so
        the ordinary HBM match that follows sees them. Best-effort: a
        span that cannot get pool pages or tree budget stays host and
        the match simply stops shorter — admission degrades to a colder
        start, never deadlocks. Returns tokens promoted NOW (the host
        tier's contribution to this admission). A BARRIER leg — each
        fill pays its designed host->device upload."""
        tree = self._prefix_cache
        if tree is None or self.host_tier_bytes <= 0:
            return 0
        _, segs = tree.match_tiered(prompt)
        filled_tokens = 0
        for node, _jp in segs:
            if node.host is None:
                continue
            if not self._fill_host_node(node):
                break
            filled_tokens += len(node.tokens)
        return filled_tokens

    def _fill_host_node(self, node) -> bool:
        """Fill ONE host-tier node back into the pool: make tree budget
        and pool-page room (the ``_alloc_pages`` reclaim discipline —
        reclaim evictable tree pages before giving up, so a fill under
        pool pressure converges instead of deadlocking admission), pop
        pages, upload the stored-layout host buffers, and commit via
        ``tree.promote``. The node is PINNED across the reclaim so the
        reclaim can neither drop it nor spill its ancestors out from
        under the path being rebuilt. False = no room; the node stays
        host-tier, untouched."""
        tree = self._prefix_cache
        n = len(node.tokens) // self.page_size
        tree.pin(node)
        try:
            over = tree.total_pages + n - tree.max_pages
            if over > 0:
                self._tree_reclaim(over, reason="fill_budget")
            if tree.total_pages + n > tree.max_pages:
                return False
            if n > len(self._free):
                self._tree_reclaim(n - len(self._free),
                                   reason="fill_pressure")
            if n > len(self._free):
                return False
            nbytes = sum(a.nbytes for a in node.host.values())
            phys = [self._free.pop() for _ in range(n)]
            self._upload_host_pages(node.host, phys)
            tree.promote(node, phys)
            self._c_tier_fills["host"].inc(n)
            self._c_tier_bytes["host"].inc(nbytes)
            self.events.emit("prefix_fill", tier="host", pages=n)
            return True
        finally:
            tree.release(node)

    def _upload_host_pages(self, pages: dict, phys_list) -> None:
        """Upload a stored-layout page dict (page axis 1; kv_int8 ships
        the quantized quadruple as stored — never dequantized) into the
        pool at physical pages *phys_list*. The fill/inject commit's
        designed host->device transfer (a barrier leg)."""
        phys = np.asarray(phys_list, np.int64)

        def put(pool, names):
            if isinstance(pool, tuple):
                q8, sc = pool
                return (
                    q8.at[:, phys].set(jnp.asarray(pages[names[0]])),
                    sc.at[:, phys].set(jnp.asarray(pages[names[1]])),
                )
            return pool.at[:, phys].set(jnp.asarray(pages[names[0]]))

        if self.kv_int8:
            self.k_pages = put(self.k_pages, ("k_q", "k_s"))
            self.v_pages = put(self.v_pages, ("v_q", "v_s"))
        else:
            self.k_pages = put(self.k_pages, ("k",))
            self.v_pages = put(self.v_pages, ("v",))

    def _page_field_names(self) -> Tuple[str, ...]:
        return (("k_q", "k_s", "v_q", "v_s") if self.kv_int8
                else ("k", "v"))

    def prefix_local_pages(self, prompt: List[int]) -> int:
        """Full pages of *prompt* this server covers across BOTH local
        tiers (HBM + host) — the replica's peer-fetch gate: only a
        genuinely cold prompt is worth a network round-trip. Host
        bookkeeping only; no device work."""
        if self._prefix_cache is None or not prompt:
            return 0
        matched, _segs = self._prefix_cache.match_tiered(prompt)
        return matched // self.page_size

    def export_prefix_span(self, prompt: List[int],
                           from_page: int = 0) -> Optional[dict]:
        """Gather this server's cached coverage of *prompt* for a PEER
        replica (the cross-replica tier's read side). Host-tier spans
        ship straight from their host buffers (no device work); HBM
        spans pay the designed gather barrier. Read-only — the tree is
        not mutated beyond LRU stamps — so a retried fetch is naturally
        idempotent. Returns ``{matched_tokens, from_page, n_pages,
        pages}`` (stored layout, page axis 1, pages ``[from_page,
        n_pages)``) or None when coverage does not reach past
        *from_page*."""
        if self._prefix_cache is None or not prompt or from_page < 0:
            return None
        matched, segs = self._prefix_cache.match_tiered(prompt)
        n_pages = matched // self.page_size
        if n_pages <= from_page:
            return None
        parts = []
        for node, jp in segs:
            if node.host is not None:
                parts.append({k: v[:, :jp] for k, v in node.host.items()})
            else:
                parts.append(self._gather_phys_pages(node.pages[:jp]))
        full = {name: np.concatenate([p[name] for p in parts], axis=1)
                for name in self._page_field_names()}
        out = {name: np.ascontiguousarray(arr[:, from_page:n_pages])
               for name, arr in full.items()}
        self._c_tier_bytes["peer"].inc(
            sum(a.nbytes for a in out.values()))
        self.events.emit("prefix_export", pages=n_pages - from_page,
                         from_page=from_page)
        return {
            "matched_tokens": n_pages * self.page_size,
            "from_page": int(from_page),
            "n_pages": int(n_pages),
            "pages": out,
        }

    def inject_prefix(self, tokens: List[int], pages: dict,
                      from_page: int = 0) -> int:
        """Adopt a PEER-fetched stored-layout span into the local prefix
        tree (the peer tier's fill commit): make tree budget and pool
        room (the ``_alloc_pages`` reclaim discipline), upload the
        uncovered pages, and insert — after which the requesting
        admission maps them like any local hit. *pages* covers logical
        pages ``[from_page, n)`` of *tokens* (the fetch skipped what
        this server reported covered); local coverage that RECEDED
        below *from_page* while the fetch was in flight leaves a hole —
        refused (return 0, the caller cold-prefills), never inserted.
        Idempotent at the tree level: spans the tree already covers
        consume nothing, so a replayed fetch commits once. Returns
        pages adopted. A BARRIER leg — the upload is its designed
        host->device transfer."""
        tree = self._prefix_cache
        if tree is None or not tokens or from_page < 0:
            return 0
        ps = self.page_size
        n = len(tokens) // ps
        if n <= from_page:
            return 0
        tokens = [int(t) for t in tokens[:n * ps]]
        for name in self._page_field_names():
            arr = pages.get(name)
            if arr is None or arr.shape[1] != n - from_page:
                raise ValueError(
                    f"injected span field {name!r} covers "
                    f"{None if arr is None else arr.shape[1]} pages, "
                    f"want {n - from_page}")
        # promote local host-tier coverage FIRST: the insert below
        # adopts host nodes by consuming donated pages, and a donated
        # page below from_page carries no peer bytes — after the fill,
        # every adoptable position is >= the HBM coverage mark
        self._fill_host_prefix(tokens)
        hbm_cov = tree.match(tokens)[0] // ps
        if hbm_cov < from_page:
            return 0            # coverage receded under the fetch: hole
        need = tree.missing_pages(tokens)
        if need <= 0:
            return 0
        over = tree.total_pages + need - tree.max_pages
        if over > 0:
            self._tree_reclaim(over, reason="inject_budget")
        if tree.total_pages + need > tree.max_pages:
            return 0
        if need > len(self._free):
            self._tree_reclaim(need - len(self._free),
                               reason="inject_pressure")
        if need > len(self._free):
            return 0
        # donate real pool pages only for positions past the local HBM
        # coverage (the walk cannot consume covered-prefix donations);
        # upload those columns, insert, free whatever was not consumed
        alloc = [self._free.pop() for _ in range(n - hbm_cov)]
        col0 = hbm_cov - from_page
        if alloc:
            self._upload_host_pages(
                {name: np.ascontiguousarray(arr[:, col0:])
                 for name, arr in pages.items()}, alloc)
        donated = [-1] * hbm_cov + alloc
        consumed = tree.insert(tokens, donated)
        assert all(p >= 0 for p in consumed), \
            "inject consumed a placeholder page"
        for p in alloc:
            if p not in consumed:
                self._free.append(p)
        if consumed:
            self._c_inserted.inc(len(consumed))
            self._c_tier_hits["peer"].inc(len(consumed))
            self._c_tier_fills["peer"].inc(len(consumed))
            self._c_tier_saved["peer"].inc(len(consumed) * ps)
            self._c_tier_bytes["peer"].inc(sum(
                arr[:, col0:].nbytes for arr in pages.values()))
            self.events.emit("prefix_inject", pages=len(consumed),
                             from_page=int(from_page))
        return len(consumed)

    def tier_stats(self) -> dict:
        """Per-tier reuse stats (Round-19): pages hit / filled /
        spilled, bytes moved, tokens saved per tier, and host-tier
        occupancy — the ``kubetpu_prefix_tier_*`` series as a dict.
        Host counters only; no device work."""
        if self._prefix_cache is None:
            return {"enabled": False}
        tree = self._prefix_cache
        return {
            "enabled": True,
            "host_tier_bytes": self.host_tier_bytes,
            "host_bytes": tree.host_bytes,
            "host_nodes": len(tree.host_nodes()),
            "spilled_pages": tree.spilled_pages,
            "hits": {t: int(c.value)
                     for t, c in self._c_tier_hits.items()},
            "fills": {t: int(c.value)
                      for t, c in self._c_tier_fills.items()},
            "spills": {t: int(c.value)
                       for t, c in self._c_tier_spills.items()},
            "bytes": {t: int(c.value)
                      for t, c in self._c_tier_bytes.items()},
            "tokens_saved": {t: int(c.value)
                             for t, c in self._c_tier_saved.items()},
        }

    def prefix_cache_stats(self) -> dict:
        """Host-side reuse stats (0s when the cache is off): requests
        hit/miss, hit rate, tokens matched/saved, tree pages/nodes,
        evicted + inserted pages — the same numbers the obs registry
        exports as ``kubetpu_prefix_*`` series."""
        if self._prefix_cache is None:
            return {"enabled": False}
        hits = int(self._c_req_hit.value)
        misses = int(self._c_req_miss.value)
        total = hits + misses
        return {
            "enabled": True,
            "requests_hit": hits,
            "requests_miss": misses,
            "hit_rate": (hits / total) if total else 0.0,
            "hit_tokens": int(self._c_hit_tokens.value),
            "prefill_tokens_saved": int(self._c_saved_tokens.value),
            "tree_pages": self._prefix_cache.total_pages,
            "tree_nodes": self._prefix_cache.n_nodes(),
            "evicted_pages": int(self._c_evicted.value),
            "inserted_pages": int(self._c_inserted.value),
            "host_bytes": self._prefix_cache.host_bytes,
            "host_nodes": len(self._prefix_cache.host_nodes()),
            "spilled_pages": self._prefix_cache.spilled_pages,
        }

    def load_info(self) -> dict:
        """Base snapshot + the paged pressure signals (Round-14 router
        food): pool size / free pages, and — with the prefix cache on —
        the hit rate and tree size, so the data plane can see which
        replica is page-starved or cache-warm without a /metrics
        parse. Host counters only; no device work."""
        info = super().load_info()
        info["pool_pages"] = self.pool_pages
        info["pages_free"] = len(self._free)
        info["pages_in_use"] = self.pages_in_use()
        if self.pool_frac < 1.0:
            info["pool_frac"] = self.pool_frac
        if self._prefix_cache is not None:
            stats = self.prefix_cache_stats()
            info["prefix_hit_rate"] = stats["hit_rate"]
            info["prefix_tree_pages"] = stats["tree_pages"]
            if self.host_tier_bytes > 0:
                tier = self.tier_stats()
                info["tier_host_bytes"] = tier["host_bytes"]
                info["tier_host_nodes"] = tier["host_nodes"]
                info["tier_hits"] = tier["hits"]
                info["tier_fills"] = tier["fills"]
                info["tier_spills"] = tier["spills"]
        return info

    def check_invariants(self) -> None:
        """The pool accounting ORACLE (``Cluster.check_invariants``'s
        serving sibling): every physical page is owned by exactly one of
        {free list, a slot's private mapping, the prefix tree}; shared
        table rows point only at tree-owned pages; node refcounts equal
        the live pins; the tree's own structure checks out — including
        the Round-19 tier half (host bytes <= budget, pages-XOR-host
        per node, host frontier). Fill-in-flight pages are counted
        exactly once BY CONSTRUCTION: a fill pops pages from the free
        list and commits them to the tree inside one synchronous
        barrier leg, so at every point this oracle can observe, each
        page sits in exactly one owner set and the pool equation below
        catches any double-count. AssertionError on any violation —
        tests and the ``make prefix-check`` storm assert it after every
        scenario."""
        free = list(self._free)
        free_set = set(free)
        assert len(free) == len(free_set), "free list holds a page twice"
        assert free_set <= set(range(self.pool_pages)), \
            "free list holds an out-of-range page"
        tree_pages = (self._prefix_cache.owned_pages()
                      if self._prefix_cache is not None else set())
        assert not (free_set & tree_pages), \
            "page both free and tree-owned"
        slot_owned = set()
        for slot in range(self.n_slots):
            shared = self._slot_shared[slot]
            seen_ring = set()   # ring tables alias the same physical page
            for lp in range(self.max_pages_per_slot):
                phys = int(self._table[slot, lp])
                if phys < 0:
                    continue
                if lp < shared:
                    assert phys in tree_pages, (
                        f"slot {slot} shared row {lp} -> page {phys} "
                        f"not tree-owned")
                    continue
                if self._ring_pages:
                    seen_ring.add(phys)
                    continue
                assert phys not in slot_owned, \
                    f"page {phys} mapped privately by two slots"
                assert phys not in tree_pages, (
                    f"slot {slot} private row {lp} -> tree-owned "
                    f"page {phys}")
                assert phys not in free_set, \
                    f"page {phys} both mapped and free"
                slot_owned.add(phys)
            slot_owned |= seen_ring
        assert len(free_set) + len(slot_owned) + len(tree_pages) \
            == self.pool_pages, (
                f"pages leaked or double-owned: free {len(free_set)} + "
                f"slots {len(slot_owned)} + tree {len(tree_pages)} != "
                f"pool {self.pool_pages}")
        if self._prefix_cache is not None:
            self._prefix_cache.check()
            pins: dict = {}
            for slot in range(self.n_slots):
                node = self._slot_pin[slot]
                if node is not None:
                    pins[id(node)] = pins.get(id(node), 0) + 1
                    assert self._slot_shared[slot] > 0, (
                        f"slot {slot} pins a node but maps no shared "
                        f"pages")
                else:
                    assert self._slot_shared[slot] == 0, (
                        f"slot {slot} maps shared pages without a pin")
            for node in self._prefix_cache.nodes():
                assert node.refcount == pins.get(id(node), 0), (
                    f"node refcount {node.refcount} != "
                    f"{pins.get(id(node), 0)} live pins")

    # -- live KV migration (Round-16) ----------------------------------------

    def _migration_kind(self) -> str:
        """Compatibility tag a snapshot carries; restore refuses a
        mismatch (a plain-paged snapshot must not land on a speculative
        server whose table width includes the gamma margin)."""
        return "paged"

    def _gather_page_span(self, slot: int, from_page: int,
                          to_page: int) -> dict:
        """Host copies of the slot's logical pages ``[from_page,
        to_page)`` gathered through the table, in their STORED layout
        (f32: k/v; kv_int8: the quantized k_q/k_s/v_q/v_s quadruple —
        never dequantized). The one designed device->host sync a handoff
        span pays; shared by the full-slot snapshot and the Round-17
        streaming leg."""
        row = self._table[slot, from_page:to_page]
        assert (row >= 0).all(), "live pages unmapped under a gather"
        return self._gather_phys_pages(row)

    def _gather_phys_pages(self, phys_list) -> dict:
        """Host copies of arbitrary PHYSICAL pool pages in their stored
        layout — the table-indirected ``_gather_page_span`` above and
        the Round-19 spill/peer-export legs share this one designed
        device->host sync."""
        phys = np.asarray(phys_list, np.int64)

        def gather(pool):
            if isinstance(pool, tuple):
                return tuple(np.asarray(jax.device_get(p[:, phys]))
                             for p in pool)
            return np.asarray(jax.device_get(pool[:, phys]))

        k = gather(self.k_pages)
        v = gather(self.v_pages)
        if self.kv_int8:
            return {"k_q": k[0], "k_s": k[1], "v_q": v[0], "v_s": v[1]}
        return {"k": k, "v": v}

    def snapshot_pages(self, rid: int, from_page: int,
                       to_page: int) -> dict:
        """Gather a COMPLETED page span of *rid*'s slot — the
        disaggregated-prefill streaming leg (Round-17): page-aligned
        chunk starts make every full page below ``prefill_progress``
        final, so a prefill replica ships spans to the decode replica
        while later chunks are still computing. Valid for mid-prefill
        AND decoding slots (the caller owns the stability argument: only
        ship pages below the progress mark / the decode position's
        page). A BARRIER leg — the device gather is its designed
        sync."""
        if self._ring_pages:
            raise NotImplementedError(
                "windowed (ring) slots have no shippable logical page "
                "view")
        if not 0 <= from_page < to_page:
            raise ValueError(f"bad page span [{from_page}, {to_page})")
        try:
            slot = self._slot_rid.index(rid)
        except ValueError:
            raise ValueError(f"request {rid} holds no slot") from None
        return self._gather_page_span(slot, from_page, to_page)

    def snapshot_slot(self, rid: int, from_page: int = 0,
                      allow_frozen: bool = False) -> dict:
        """Capture everything needed to resume *rid* token-exactly on
        another replica: the request state (``_snapshot_request`` — raw
        request key included, so even SEEDED sampling continues
        identically), and the slot's LIVE page contents gathered through
        the page table. kv_int8 pools ship the (int8 values, f32 scales)
        pairs AS STORED — no dequantize/requantize round-trip, so the
        restored pool is bit-identical to the source's. Only pages
        holding live tokens ship (positions 0..pos; the page at pos may
        be partially stale — decode rewrites position pos before any
        read, the standard overwrite-before-read invariant).
        *from_page* skips pages the caller already shipped (the Round-17
        streaming handoff gathers only the tail here); ``n_live_pages``
        stays ABSOLUTE either way. *allow_frozen* lets the handoff
        owner snapshot a slot it froze itself (freeze-then-gather keeps
        the stream from decoding past the snapshot on the source) —
        third parties must keep getting the refusal, or two racing
        policies would ship the same epoch to different targets.

        Migration happens only between steps/rounds: raises ValueError
        for queued / mid-chunked-prefill / deferred-first-token /
        already-frozen streams and under an unflushed overlap pipeline.
        Windowed (ring) configs are refused — aliased rings are a
        per-slot layout, not a shippable logical view. This is a BARRIER
        leg: the device gather is its designed sync."""
        if self._ring_pages:
            raise NotImplementedError(
                "windowed (ring) slots cannot migrate: the ring aliases "
                "logical pages per slot; there is no shippable logical "
                "page view")
        if self._inflight is not None:
            raise ValueError(
                "snapshot requires the overlap pipeline flushed — an "
                "un-materialized step may still move this stream")
        if any(qrid == rid for qrid, _p, _d in self._queue):
            raise ValueError(f"request {rid} is still queued — nothing "
                             f"to migrate; route the prompt instead")
        try:
            slot = self._slot_rid.index(rid)
        except ValueError:
            raise ValueError(f"request {rid} holds no slot") from None
        if slot in self._prefills:
            raise ValueError(
                f"request {rid} is mid-chunked-prefill — migration "
                f"only between rounds (let the admission finish)")
        if slot in self._pending_first:
            raise ValueError(
                f"request {rid}'s first token is still deferred — "
                f"step once before migrating")
        if slot in self._frozen and not allow_frozen:
            # two concurrent policies (drain sweep + suspect sweep)
            # racing for the same stream: the second must refuse, or
            # both would ship epoch N+1 to DIFFERENT targets and each
            # target's per-replica fence would admit its copy
            raise ValueError(
                f"request {rid} is already frozen for another handoff")
        if not self.active[slot] and slot not in self._frozen:
            raise ValueError(f"request {rid} is not decoding")
        snap = self._snapshot_request(rid, slot)
        n_live = self._pages_needed(self._host_len[slot])
        if not 0 <= from_page <= n_live:
            raise ValueError(
                f"from_page {from_page} outside the live span "
                f"[0, {n_live}]")
        pages = self._gather_page_span(slot, from_page, n_live)
        snap.update({
            "kind": self._migration_kind(),
            "cfg_fp": repr(self.cfg),
            "page_size": self.page_size,
            "kv_int8": bool(self.kv_int8),
            "max_seq": self.max_seq,
            "n_live_pages": int(n_live),
            "pages": pages,
        })
        self.events.emit("snapshot", rid=rid, slot=slot, pages=int(n_live))
        return snap

    def migration_prefix_hint(self, prompt: List[int]) -> int:
        """Full pages of *prompt* this server could map read-only from
        its prefix cache RIGHT NOW — the ``/migrate_in`` begin phase
        advertises this so the source ships only the uncached suffix
        (matched pages never cross the wire at all). A HINT, never a
        promise: eviction between begin and commit can shrink the real
        match, and ``restore_slot`` refuses a receded match instead of
        restoring with holes (the source then resumes and re-ships).
        Round-19: host-tier coverage counts — the restore-path
        ``_prefill_start`` fills it before matching, and a fill that
        fails is exactly the receded-match refusal."""
        if self._prefix_cache is None or not prompt:
            return 0
        matched, _segs = self._prefix_cache.match_tiered(prompt)
        start = min(matched, ((len(prompt) - 1) // self.page_size)
                    * self.page_size)
        return max(0, start // self.page_size)

    def restore_slot(self, snap: dict, reason: str = "migrate"):
        """Rebuild a snapshot stream into a free slot and resume decode
        -> the new LOCAL rid, or None when resources (slot / pool pages)
        are unavailable — nothing mutated, the caller may retry another
        replica. Prefix-cache matched pages map READ-ONLY instead of
        shipping bytes (the Round-9 admission path — COW rules
        unchanged: every future write lands past the shared rows); the
        snapshot's ``pages`` may therefore START at logical page
        ``ship_from_page`` (the begin-phase hint the source honored) —
        and only the still-uncached suffix uploads into the pool. A
        match that RECEDED below the shipped offset (eviction between
        hint and commit) refuses with ValueError rather than restore
        with holes. The restored stream's remaining tokens are greedy-
        (and seeded-sampling-) identical to an unmigrated run:
        identical page bytes, position, last token and request key. A
        BARRIER leg — the page upload is its designed host->device
        transfer."""
        if self._ring_pages:
            raise NotImplementedError(
                "windowed (ring) servers cannot accept migrated slots")
        if snap.get("kind") != self._migration_kind():
            raise ValueError(
                f"snapshot kind {snap.get('kind')!r} does not match this "
                f"server ({self._migration_kind()!r})")
        for field, mine in (("cfg_fp", repr(self.cfg)),
                            ("page_size", self.page_size),
                            ("kv_int8", bool(self.kv_int8)),
                            ("max_seq", self.max_seq),
                            ("max_new_tokens", self.max_new_tokens),
                            ("eos_id", self.eos_id)):
            if snap.get(field) != mine:
                raise ValueError(
                    f"snapshot {field}={snap.get(field)!r} does not match "
                    f"this server's {mine!r} — migration requires "
                    f"config-identical replicas")
        prompt = [int(t) for t in snap["prompt"]]
        emitted = [int(t) for t in snap["emitted"]]
        if not emitted:
            raise ValueError("snapshot carries no emitted tokens — the "
                             "stream never started decoding")
        if len(emitted) >= self.max_new_tokens or (
                self.eos_id is not None and emitted[-1] == self.eos_id):
            raise ValueError("snapshot stream is already finished")
        free = self._free_slots()
        if not free:
            return None
        slot = free[0]
        # Round-9 reuse on the RESTORE path: map this server's cached
        # prefix pages read-only (never copied — the bytes are already
        # here); the uncached suffix uploads from the snapshot
        ship_from = int(snap.get("ship_from_page", 0))
        start = self._prefill_start(prompt, slot)
        use = start // self.page_size if start else 0
        if use < ship_from:
            # the begin-phase hint promised pages the cache has since
            # evicted: the shipped suffix has a HOLE — refuse (the
            # source resumes and re-ships with a fresh hint) rather
            # than restore a slot with missing KV
            self._prefix_unmap(slot)
            raise ValueError(
                f"prefix receded: snapshot pages start at logical page "
                f"{ship_from} but only {use} pages matched locally — "
                f"re-ship with a fresh hint")
        if not self._alloc_pages(slot, self._worst_case_tokens(len(prompt))):
            self._prefix_unmap(slot)
            return None
        n_live = int(snap["n_live_pages"])
        for name, arr in snap.get("pages", {}).items():
            if arr.shape[1] != n_live - ship_from:
                self._prefix_unmap(slot)
                raise ValueError(
                    f"snapshot page array {name!r} holds {arr.shape[1]} "
                    f"pages, want {n_live - ship_from} "
                    f"(n_live {n_live} - shipped-from {ship_from})")
        rows = list(range(use, n_live))
        if rows:
            phys = np.asarray(
                [int(self._table[slot, lp]) for lp in rows], np.int64)
            cols = [lp - ship_from for lp in rows]
            pages = snap["pages"]

            def put(pool, names):
                # upload-on-restore is this barrier leg's job (the
                # mirror-cache rationale does not apply: each handoff
                # ships fresh bytes exactly once)
                if isinstance(pool, tuple):
                    q8, sc = pool
                    return (
                        q8.at[:, phys].set(jnp.asarray(pages[names[0]][:, cols])),
                        sc.at[:, phys].set(jnp.asarray(pages[names[1]][:, cols])),
                    )
                return pool.at[:, phys].set(
                    jnp.asarray(pages[names[0]][:, cols]))

            if self.kv_int8:
                self.k_pages = put(self.k_pages, ("k_q", "k_s"))
                self.v_pages = put(self.v_pages, ("v_q", "v_s"))
            else:
                self.k_pages = put(self.k_pages, ("k",))
                self.v_pages = put(self.v_pages, ("v",))
        rid = self._restore_request(snap, slot)
        self.pos = self.pos.at[slot].set(int(snap["pos"]))
        self.last = self.last.at[slot].set(int(snap["last"]))
        self.active[slot] = True
        self._invalidate_dev("active")
        self._note_admitted(slot, prompt)   # prompt held for publication
        # host length counts prompt + every emitted token (the last
        # token's KV is written by the NEXT step, like any decode)
        self._host_len[slot] = len(prompt) + len(emitted)
        self.obs.counter(
            "kubetpu_migration_pages_remapped_total",
            "snapshot pages satisfied read-only by the local prefix "
            "cache instead of shipped bytes").inc(use)
        self.obs.counter(
            "kubetpu_migration_pages_shipped_total",
            "snapshot pages written into the pool from shipped "
            "bytes").inc(len(rows))
        self.events.emit("migrate_in", rid=rid, slot=slot, reason=reason,
                         epoch=int(snap.get("epoch", 0)),
                         pages_shipped=len(rows), pages_remapped=use)
        return rid

    # -- device legs ---------------------------------------------------------

    def _chunk_quantum(self) -> int:
        return self.page_size       # chunk starts stay page-aligned

    def _chunk_bucket(self, pos: int, take: int, final: bool) -> int:
        """Padded length of a prefill chunk: FINAL chunks bucket-pad
        (finish-the-tail, ``_chunk_take``) — pad K/V land at positions
        decode overwrites before any read, pad-only pages are dropped by
        the write row; non-final chunks are grid-sized, page-rounded so
        starts stay page-aligned. Shared with the speculative server's
        draft prefill so both caches see the identical chunk."""
        ps = self.page_size
        if final:
            # page-round the grid bucket: _bucket caps at max_seq, which
            # need not be a page multiple, but the pool scatter writes
            # whole pages — the rounded tail stays inside the table (its
            # width is page-aligned and >= max_seq)
            bucket = ((self._bucket(take) + ps - 1) // ps) * ps
            if pos + bucket > self.max_pages_per_slot * ps:
                bucket = ((take + ps - 1) // ps) * ps   # defensive clamp
            return bucket
        return ((take + ps - 1) // ps) * ps

    def _gather_prefix(self, upto_tokens: int) -> int:
        """Power-of-two page count covering *upto_tokens* positions
        (capped at the slot's table) — the attend-prefix shape rule,
        shared by the live path and warmup so a warmed shape is exactly
        a served shape."""
        n = 1
        while n * self.page_size < upto_tokens:
            n *= 2
        return min(n, self.max_pages_per_slot)

    def _admit_device(self, prompt: List[int], slot: int):
        """Whole-prompt prefill as one final chunk — starting at the
        prefix-cache match (pos 0 on a miss); the chunk leg owns the
        worst-case page reservation (its ``final`` branch) and returns
        None on pool exhaustion. A failed admission unmaps the shared
        prefix too: the request goes back to the queue and NOTHING may
        stay mutated (the slot must read as empty for the next
        occupant's ``_alloc_pages`` row count)."""
        start = self._prefill_start(prompt, slot)
        res = self._prefill_chunk_device(
            prompt, slot, start, len(prompt) - start, True)
        if res is None and start:
            self._prefix_unmap(slot)
        return res

    def _prefill_chunk_device(self, prompt: List[int], slot: int, pos: int,
                              take: int, final: bool):
        """One (page-aligned) prefill chunk through the pool, with
        CHUNK-GRANULAR page reservation: a mid-prefill slot holds pages
        for the tokens written so far, not the worst case — the pool
        serves decoding neighbors while a long prompt streams in. The
        FINAL chunk upgrades the reservation to the decode worst case
        (prompt + max_new_tokens + 1), so the invariant decode relies on
        — boundary crossings never fail — holds from the first emitted
        token. Ring (windowed) slots map their whole O(window) ring up
        front instead: it is already the worst case, and chunk-granular
        aliasing bookkeeping would buy nothing."""
        if self._ring_pages:
            if not self._alloc_pages(
                    slot, self._worst_case_tokens(len(prompt))):
                return None
        else:
            upto = (self._worst_case_tokens(len(prompt)) if final
                    else pos + take)
            if not self._alloc_pages(slot, upto):
                return None
        ps = self.page_size
        bucket = self._chunk_bucket(pos, take, final)
        chunk = prompt[pos:pos + take] + [0] * (bucket - take)
        n_write = (bucket + ps - 1) // ps
        p0 = pos // ps
        row = self._table[slot]
        write_row = row[p0:p0 + n_write].astype(np.int64)
        # Pad-only pages (no real token) are dropped: a pad write must
        # never win an aliased ring slot over live prompt data, nor land
        # on an unreserved page (review r5's bucket-padding hazard).
        last_real = (pos + take - 1) // ps - p0
        write_row[last_real + 1:] = -1
        if self._ring_pages:
            # one scatter must not carry duplicate physical indices
            # (undefined winner): keep only the LAST logical occurrence
            # of each ring page — earlier aliased pages are superseded
            # (outside every future band), the monolithic dance's rule
            # applied per chunk
            seen = set()
            for i in range(len(write_row) - 1, -1, -1):
                p = int(write_row[i])
                if p < 0:
                    continue
                if p in seen:
                    write_row[i] = -1
                else:
                    seen.add(p)
        write_phys = np.where(write_row >= 0, write_row,
                              self.pool_pages).astype(np.int32)
        # attend only the pages the chunk can SEE (positions <= pos +
        # bucket), prefix rounded to a power of two so a handful of
        # compilations serves every offset — not the slot's whole
        # max_seq view (a ~max_seq/bucket x cost on every admission)
        n_gather = self._gather_prefix(pos + bucket)
        lora, aid = self._admit_lora(slot)
        self.k_pages, self.v_pages, first, first_lp = self._prefill_chunk(
            self.params, self.k_pages, self.v_pages,
            jnp.asarray(chunk, jnp.int32),
            jnp.asarray(write_phys), jnp.asarray(row[:n_gather]),
            jnp.int32(pos), jnp.int32(take - 1),
            jnp.asarray(self._slot_reqkey[slot]),
            jnp.float32(self._slot_temp[slot]),
            jnp.int32(self._slot_topk[slot]),
            jnp.float32(self._slot_topp[slot]),
            lora, aid,
        )
        return (first, first_lp) if final else True

    def _note_kernel_step(self) -> None:
        """Kernel-adoption bookkeeping on the hot path (KTP001-clean:
        host counter writes only, no device sync): one fused decode/
        verify leg ran instead of the gather core's materialized
        (B, max_pages*ps, H_kv, D) buffer."""
        if self.use_kernel:
            self._c_kernel_steps.inc()
            self._c_kernel_bytes.inc(self._kernel_bytes_saved)

    def _device_step(self):
        # worst-case pages were reserved by admission / the final prefill
        # chunk, so boundary crossings never fail; the REAL table (with
        # -1 sentinels) flows to the device — the attention core masks
        # unmapped pages. Table and slot state ride the device-resident
        # upload cache: a steady-state step re-uploads nothing.
        self._note_kernel_step()
        lora, aids = self._step_lora()
        self.k_pages, self.v_pages, nxt, self.pos, lp = self._step_all(
            self.params, self.k_pages, self.v_pages,
            self._dev("table", lambda: self._table),
            self.last, self.pos,
            self._dev("active", lambda: self.active),
            self._dev("reqkey", lambda: self._slot_reqkey),
            self._dev("temp", lambda: self._slot_temp),
            self._dev("topk", lambda: self._slot_topk),
            self._dev("topp", lambda: self._slot_topp),
            lora, aids,
        )
        self.last = nxt
        return nxt, lp

    def warmup(self) -> None:
        """Pre-compile every prompt bucket + the step (serving.warmup's
        rationale). Only valid while NO request is active: the dummy
        prefill scribbles on pool pages a live sequence may have mapped —
        including tree-owned ones, so the prefix cache is FLUSHED first
        (idle server => nothing pinned; the pages return to the free
        list and the tree repopulates from live traffic). The flush
        takes the HOST TIER with it (Round-19): a host buffer surviving
        a warmup would later fill KV computed under whatever state the
        warmup scribbled over."""
        if self._prefix_cache is not None:
            self._free.extend(self._prefix_cache.clear())
        d_temp, d_tk, d_tp = self._default_sampling
        row = np.full((self.max_pages_per_slot,), -1, np.int32)
        row[: self._pages_needed(self.max_seq)] = np.arange(
            self._pages_needed(self.max_seq)
        ) % self.pool_pages

        def prefill_dummy(padded, n_gather=None):
            n_write = (len(padded) + self.page_size - 1) // self.page_size
            if n_gather is None:
                n_gather = self._gather_prefix(len(padded))
            lora, aid = self._admit_lora(0)
            self.k_pages, self.v_pages, _f, _lp = self._prefill_chunk(
                self.params, self.k_pages, self.v_pages,
                jnp.asarray(padded, jnp.int32),
                jnp.asarray(row[:n_write]), jnp.asarray(row[:n_gather]),
                jnp.int32(0), jnp.int32(0),
                jnp.asarray(self._slot_reqkey[0]),
                jnp.float32(d_temp), jnp.int32(d_tk), jnp.float32(d_tp),
                lora, aid,
            )

        self._warmup_buckets(prefill_dummy)
        if self.prefill_budget > 0:
            # A RESUMED chunk pairs a small chunk length with a LARGER
            # gather prefix (the already-written prefix grows with pos;
            # pos itself is traced, so only the shape pair matters). Warm
            # every (chunk, prefix) signature the budget can produce —
            # a compile at chunk 2, 3, ... of the first long admission is
            # exactly the mid-serving stall prefill_budget exists to
            # bound.
            b = self.page_size
            max_b = self._bucket(max(self.prefill_budget, self.page_size))
            while b <= max_b:
                g = self._gather_prefix(b)
                while g < self.max_pages_per_slot:
                    g = min(g * 2, self.max_pages_per_slot)
                    prefill_dummy([0] * b, n_gather=g)
                b *= 2
        lora, aids = self._step_lora()
        self.k_pages, self.v_pages, _n, _p, _lps = self._step_all(
            self.params, self.k_pages, self.v_pages,
            jnp.asarray(self._table), self.last, self.pos,
            jnp.asarray(np.zeros((self.n_slots,), bool)),
            jnp.asarray(self._slot_reqkey),
            jnp.asarray(self._slot_temp), jnp.asarray(self._slot_topk),
            jnp.asarray(self._slot_topp),
            lora, aids,
        )
        # drain the dispatch queue so the first live admission doesn't pay
        # (and record) the queued warmup executions as admission stall —
        # same rationale as serving.DecodeServer.warmup
        jax.block_until_ready((self.k_pages, self.v_pages))
