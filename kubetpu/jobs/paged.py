"""Paged KV cache: serving memory proportional to LIVE tokens.

The dense serving cache allocates ``(L, n_slots, max_seq, H_kv, D)`` per
slot — a 64-slot x 8k-seq server holds mostly-empty cache (VERDICT r2 weak
#4). The paged design splits the cache into fixed-size PAGES drawn from one
shared pool:

- pool: ``k_pages/v_pages (L, n_pages, page_size, H_kv, D)``;
- per-slot page table ``(n_slots, max_pages_per_slot)`` int32 mapping a
  slot's logical page to a physical pool page (-1 = unmapped);
- the HOST owns allocation (free-list): admission maps just enough pages
  for the prompt, and each decode step maps one more page only when a
  sequence actually crosses a page boundary. Device code stays purely
  functional — the table is just another jit input.

Attention gathers a slot's pages on the fly (XLA gather; the score math is
bit-identical to the dense `_attend_cached`, so greedy decode through
pages matches the dense server EXACTLY — the parity test pins this).
An optional Pallas paged-attention kernel (kubetpu.ops.paged_attention)
streams pages through VMEM without materializing the gathered cache;
interpret-mode tests pin its parity, compiled validation runs on real TPU
via scripts/tpu_smoke.py.

Memory math: a slot costs ``ceil(live_tokens / page_size)`` pages instead
of ``max_seq`` rows — a server provisions the pool for the EXPECTED total
live tokens, not the worst case per slot. ``PagedDecodeServer`` refuses
admission (returns None / parks the queue) when the pool cannot cover a
request's worst case, so decoding never deadlocks mid-sequence.

Reference: none (the reference has no inference stack, SURVEY.md §2);
design follows the public paged-attention pattern (vLLM), re-shaped for
TPU: static shapes, one jitted step, host-side tables.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubetpu.jobs import model as model_lib
from kubetpu.jobs.model import ModelConfig, Params
from kubetpu.jobs.quant import maybe_dequantize, quantize_kv_chunk
from kubetpu.jobs.sampling import chosen_logprob
from kubetpu.jobs.serving import SlotServerBase


def init_page_pool(
    cfg: ModelConfig, n_pages: int, page_size: int, kv_int8: bool = False
):
    """(k_pages, v_pages), each (L, n_pages, page_size, H_kv, D) — or,
    with ``kv_int8``, each a (values int8, scales f32 (..., H_kv, 1))
    pair: the page pool stores quantized entries (per-token per-head
    scales, ``quant.quantize_kv_chunk``), compounding the pool's
    live-token provisioning with another ~2x per page."""
    shape = (cfg.n_layers, n_pages, page_size, cfg.kv_heads, cfg.head_dim)
    if kv_int8:
        sshape = shape[:-1] + (1,)
        return (
            (jnp.zeros(shape, jnp.int8), jnp.zeros(sshape, jnp.float32)),
            (jnp.zeros(shape, jnp.int8), jnp.zeros(sshape, jnp.float32)),
        )
    return jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype)


def _gather_pages(pages_l, safe):
    """Gather a slot's pages from a dense array or an int8 (values,
    scales) pair — dequant happens on the GATHERED slice only (the
    convert+mul fuses into the attention einsum's read; the full pool is
    never materialized in f32)."""
    if isinstance(pages_l, tuple):
        q8, sc = pages_l
        return q8[safe].astype(jnp.float32) * sc[safe]
    return pages_l[safe]


def _attend_paged(q, k_pages_l, v_pages_l, table, pos, window: int = 0):
    """Attention of a 1-token query per slot against that slot's pages.

    q: (B, H, D); pages: (P, ps, H_kv, D); table: (B, max_pages) int32
    (-1 = unmapped; clamped to 0 for the gather, then masked); pos: (B,)
    index of the query position. Math mirrors decode._attend_cached
    (f32 scores/softmax, grouped-query groups) so paged and dense greedy
    decode agree exactly.

    ``window > 0`` adds the banded mask (key visible iff
    ``0 <= pos - k_pos < window``, the repo-wide convention) — and makes
    the RING page table sound: logical pages aliased onto the same
    physical page differ by >= window positions, so at most one aliased
    copy is ever inside the band; everything else is masked here.
    """
    b, h, d = q.shape
    vals_k = k_pages_l[0] if isinstance(k_pages_l, tuple) else k_pages_l
    ps = vals_k.shape[1]
    h_kv = vals_k.shape[2]
    g = h // h_kv
    max_pages = table.shape[1]
    scale = d ** -0.5

    safe = jnp.maximum(table, 0)
    k = _gather_pages(k_pages_l, safe).reshape(b, max_pages * ps, h_kv, d)
    v = _gather_pages(v_pages_l, safe).reshape(b, max_pages * ps, h_kv, d)

    qg = q.reshape(b, h_kv, g, d).astype(jnp.float32)
    scores = jnp.einsum("bkgd,bskd->bkgs", qg, k.astype(jnp.float32)) * scale
    k_pos = jnp.arange(max_pages * ps)
    mask = k_pos[None, :] <= pos[:, None]                     # (B, S_v)
    if window > 0:
        mask = mask & (pos[:, None] - k_pos[None, :] < window)
    mask = mask & (jnp.repeat(table, ps, axis=1) >= 0)        # unmapped pages
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", probs, v.astype(jnp.float32))
    return out.reshape(b, h, d).astype(q.dtype)


def _write_token_kv(pages_l, new, phys_page, offset):
    """Scatter one token's K or V per slot into its page.
    pages_l: (P, ps, H_kv, D) — or the int8 (values, scales) pair, where
    the token quantizes at write time; new: (B, H_kv, D); phys_page/
    offset: (B,). mode="drop": an INACTIVE slot's table row is -1 (mapped
    to the out-of-bounds sentinel by the caller) — without drop, the
    negative index would wrap and scribble on the last pool page, which
    may belong to a live request."""
    if isinstance(pages_l, tuple):
        q8, sc = pages_l
        n8, ns = quantize_kv_chunk(new)
        return (
            q8.at[phys_page, offset].set(n8, mode="drop"),
            sc.at[phys_page, offset].set(ns, mode="drop"),
        )
    return pages_l.at[phys_page, offset].set(new, mode="drop")


def paged_forward_one(
    cfg: ModelConfig, params: Params, token, k_pages, v_pages, table, pos,
    attend=_attend_paged,
):
    """One decode step for all slots through the page pool.
    token: (B,) int32; pos: (B,) per-slot position of this token;
    table: (B, max_pages). Returns (logits (B, V), k_pages, v_pages).
    *attend* swaps the page-attention core (the Pallas kernel plugs in
    here). The pools may be dense arrays or int8 (values, scales) pairs —
    the write/gather helpers branch, the layer scan carries either."""
    vals = k_pages[0] if isinstance(k_pages, tuple) else k_pages
    ps = vals.shape[2]
    n_pool = vals.shape[1]
    phys = jnp.take_along_axis(table, (pos // ps)[:, None], axis=1)[:, 0]
    phys = jnp.where(phys >= 0, phys, n_pool)  # unmapped -> dropped write
    offset = pos % ps
    x = params["embed"][token][:, None]                       # (B, 1, D)

    def layer_body(carry, inputs):
        x = carry
        layer, k_l, v_l = inputs
        layer = maybe_dequantize(layer)   # per-layer int8 dequant (see quant.py)
        h = model_lib.rms_norm(x, layer["ln1"])
        q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"])
        k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"])
        v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"])
        positions = pos[:, None]
        q = model_lib.rope(q, positions, cfg.rope_theta, cfg.rope_llama3_scaling)
        k = model_lib.rope(k, positions, cfg.rope_theta, cfg.rope_llama3_scaling)
        k_l = _write_token_kv(k_l, k[:, 0], phys, offset)
        v_l = _write_token_kv(v_l, v[:, 0], phys, offset)
        attn = attend(q[:, 0], k_l, v_l, table, pos)
        x = x + jnp.einsum("bhk,hkd->bd", attn, layer["wo"])[:, None]
        h2 = model_lib.rms_norm(x, layer["ln2"])
        delta, _aux = model_lib._mlp(cfg, h2, layer)
        return x + delta, (k_l, v_l)

    x, (k_pages, v_pages) = jax.lax.scan(
        layer_body, x, (params["blocks"], k_pages, v_pages)
    )
    x = model_lib.rms_norm(x, params["ln_f"])
    head = maybe_dequantize(params["head"])
    logits = jnp.einsum("bsd,dv->bsv", x, head).astype(jnp.float32)
    return logits[:, 0], k_pages, v_pages


def paged_prefill(
    cfg: ModelConfig, params: Params, prompt, k_pages, v_pages,
    slot_row, prompt_len,
):
    """Prefill one slot's prompt into its pages with a single batched
    forward. prompt: (S_bucket,) int32 (bucket-padded); slot_row: the
    slot's page-table row (max_pages,); writes ceil(S_bucket/ps) pages.
    A bucket can exceed the slot's RESERVED pages (power-of-two padding);
    the excess holds pad positions only (real tokens always fit in the
    worst-case reservation), and their writes are DROPPED — clamping
    instead would scribble on pool page 0, which may belong to another
    slot. Returns (first_token_logits (V,), k_pages, v_pages)."""
    from kubetpu.jobs.decode import (
        _int8_cache_io,
        forward_chunk,
        forward_chunk_io,
        init_kv_cache,
        init_kv_cache_int8,
    )

    int8 = isinstance(k_pages, tuple)
    vals = k_pages[0] if int8 else k_pages
    ps = vals.shape[2]
    n_pool = vals.shape[1]
    s_bucket = prompt.shape[0]
    n_write = (s_bucket + ps - 1) // ps
    row = slot_row[:n_write]
    phys = jnp.where(row >= 0, row, n_pool)   # out-of-bounds -> dropped

    def reshape_pages(x):
        # (L, 1, S, H, last) scratch -> (L, n_write, ps, H, last)
        return x[:, 0].reshape(cfg.n_layers, n_write, ps, *x.shape[3:])

    if int8:
        # chunk forward through a TRANSIENT int8 scratch — the SAME
        # quantize-then-attend strategy the int8 DENSE server prefills
        # with (_int8_cache_io), so the pool receives bit-identical
        # quantized entries and paged int8 decode is STRUCTURALLY
        # token-exact against DecodeServer(kv_int8=True) (review r5: an
        # exact-bf16-scratch prefill only agreed by argmax margin)
        scratch = init_kv_cache_int8(cfg, 1, n_write * ps)
        logits, ((kq, ksc), (vq, vsc)) = forward_chunk_io(
            cfg, params, prompt[None], scratch, 0, _int8_cache_io(cfg.window)
        )
        k_pages = (
            k_pages[0].at[:, phys].set(reshape_pages(kq), mode="drop"),
            k_pages[1].at[:, phys].set(reshape_pages(ksc), mode="drop"),
        )
        v_pages = (
            v_pages[0].at[:, phys].set(reshape_pages(vq), mode="drop"),
            v_pages[1].at[:, phys].set(reshape_pages(vsc), mode="drop"),
        )
    else:
        # the very code path the dense server prefills with, so paged
        # greedy decode is token-exact against it; the scratch (one
        # bucket) is re-shaped into page writes and freed by XLA
        k_scratch, v_scratch = init_kv_cache(cfg, 1, n_write * ps)
        logits, k_scratch, v_scratch = forward_chunk(
            cfg, params, prompt[None], k_scratch, v_scratch, 0
        )
        k_pages = k_pages.at[:, phys].set(
            reshape_pages(k_scratch).astype(k_pages.dtype), mode="drop")
        v_pages = v_pages.at[:, phys].set(
            reshape_pages(v_scratch).astype(v_pages.dtype), mode="drop")
    first = jnp.take(logits[0], prompt_len - 1, axis=0)       # (V,)
    return first, k_pages, v_pages


class PagedDecodeServer(SlotServerBase):
    """Continuous batching over a paged KV cache — same public surface as
    ``serving.DecodeServer`` (the request lifecycle IS serving's
    ``SlotServerBase``; only the device legs differ), cache memory
    proportional to live tokens.

    ``n_pages`` provisions the shared pool; a request is admitted only
    when the pool can cover its worst case (prompt + max_new_tokens), so a
    decoding sequence never starves mid-flight — and a request whose worst
    case exceeds the WHOLE pool is rejected up front by ``_check_prompt``
    (otherwise it would park the queue head forever). ``pages_in_use()``
    and ``pool_pages`` expose the accounting the memory test pins.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params: Params,
        n_slots: int = 8,
        max_seq: int = 512,
        max_new_tokens: int = 64,
        page_size: int = 16,
        n_pages: Optional[int] = None,
        eos_id: Optional[int] = None,
        use_kernel: bool = False,
        interpret: bool = False,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        seed: int = 0,
        mesh=None,
        kv_int8: bool = False,
    ) -> None:
        if cfg.window > 0 and use_kernel:
            raise NotImplementedError(
                "the Pallas paged-attention kernel does not implement the "
                "banded mask yet; windowed paged serving uses the gather "
                "core (use_kernel=False)"
            )
        if kv_int8 and use_kernel:
            raise NotImplementedError(
                "the Pallas paged-attention kernel reads dense-dtype pages; "
                "int8 pools use the gather core (use_kernel=False)"
            )
        super().__init__(cfg, params, n_slots, max_seq, max_new_tokens,
                         eos_id, temperature=temperature, top_k=top_k,
                         top_p=top_p, seed=seed)
        self.page_size = page_size
        self._min_bucket = page_size  # bucket >= one page keeps shapes few
        self.max_pages_per_slot = (max_seq + page_size - 1) // page_size
        # Windowed (banded) serving: a slot's LOGICAL pages map onto a
        # small physical RING of ceil(window/ps) + 1 pages (table entry
        # lp -> ring[lp % ring]). Soundness: ring * ps >= window + ps, so
        # the token overwritten at position p sits at p - ring*ps <=
        # p - window - 1 — already outside every future band — and any
        # aliased stale read is outside the band too, killed by the
        # windowed mask in _attend_paged. Cache memory per slot becomes
        # O(window) however long the sequence runs — the paged pool and
        # the O(window) cache COMPOUND (VERDICT r4 #4/#5).
        self._ring_pages = (
            self._pages_needed(cfg.window) + 1 if cfg.window > 0 else 0
        )
        # default pool: HALF the dense equivalent — the win is configurable,
        # callers size it to expected live tokens
        self.pool_pages = n_pages or (n_slots * self.max_pages_per_slot + 1) // 2
        self.kv_int8 = kv_int8
        self.k_pages, self.v_pages = init_page_pool(
            cfg, self.pool_pages, page_size, kv_int8=kv_int8
        )
        if mesh is not None:
            # Multi-chip paged serving: params tensor-parallel (training's
            # specs), pool pages sharded on kv heads over tp. The PAGE axis
            # stays unsharded — the host allocator hands pages to any slot,
            # so a page split would turn every table gather cross-device;
            # the kv-head split keeps gathers local (pairs with the dense
            # server's layout, serving.DecodeServer).
            from jax.sharding import NamedSharding, PartitionSpec as P

            from kubetpu.jobs.train import _filter_spec, _shardings, param_specs

            self.params = jax.device_put(
                params, _shardings(mesh, param_specs(cfg)))
            psh = NamedSharding(
                mesh, _filter_spec(mesh, P(None, None, None, "tp", None)))
            # int8 pools are (values, scales) pairs; the scale leaves'
            # head axis is axis 3 too, so one spec serves every leaf
            self.k_pages = jax.tree.map(
                lambda x: jax.device_put(x, psh), self.k_pages)
            self.v_pages = jax.tree.map(
                lambda x: jax.device_put(x, psh), self.v_pages)
        self._free: List[int] = list(range(self.pool_pages))
        self._table = np.full((n_slots, self.max_pages_per_slot), -1, np.int32)
        self._host_len = [0] * n_slots          # tokens stored per slot

        attend = partial(_attend_paged, window=cfg.window)
        if use_kernel:
            from kubetpu.ops.paged_attention import paged_attention

            attend = partial(paged_attention, interpret=interpret)

        cfg_ = cfg
        sampler = self._sampler

        @partial(jax.jit, donate_argnums=(1, 2))
        def step_all(params, k_pages, v_pages, table, last, pos, active, rng,
                     temp, tk, tp):
            logits, k_pages, v_pages = paged_forward_one(
                cfg_, params, last, k_pages, v_pages, table, pos, attend=attend
            )
            nxt = sampler(logits, rng, temp, tk, tp)
            nxt = jnp.where(active, nxt, last)
            lp = chosen_logprob(logits, nxt)
            pos = pos + active.astype(jnp.int32)
            return k_pages, v_pages, nxt, pos, lp

        @partial(jax.jit, donate_argnums=(1, 2))
        def prefill_slot(params, k_pages, v_pages, prompt, slot_row,
                         prompt_len, rng, temp, tk, tp):
            first, k_pages, v_pages = paged_prefill(
                cfg_, params, prompt, k_pages, v_pages, slot_row, prompt_len
            )
            tok = sampler(first, rng, temp, tk, tp)
            return k_pages, v_pages, tok, chosen_logprob(first, tok)

        self._step_all = step_all
        self._prefill_slot = prefill_slot

    # -- page accounting -----------------------------------------------------

    def pages_in_use(self) -> int:
        return self.pool_pages - len(self._free)

    def _pages_needed(self, n_tokens: int) -> int:
        return (n_tokens + self.page_size - 1) // self.page_size

    def _worst_case_tokens(self, prompt_len: int) -> int:
        return prompt_len + self.max_new_tokens + 1

    def _alloc_pages(self, slot: int, upto_tokens: int) -> bool:
        """Map pages so slot can hold *upto_tokens* tokens; False if the
        pool is exhausted (caller must not admit). Windowed configs map a
        physical ring and alias every logical page onto it (see
        ``_ring_pages``) — the pool cost per slot is the ring, not the
        sequence length."""
        need = self._pages_needed(upto_tokens)
        if self._ring_pages:
            phys_need = min(need, self._ring_pages)
            if phys_need > len(self._free):
                return False
            ring = [self._free.pop() for _ in range(phys_need)]
            for lp in range(need):
                self._table[slot, lp] = ring[lp % phys_need]
            return True
        have = int((self._table[slot] >= 0).sum())
        if need - have > len(self._free):
            return False
        for lp in range(have, need):
            self._table[slot, lp] = self._free.pop()
        return True

    def _release_pages(self, slot: int) -> None:
        freed = set()  # ring tables alias: free each physical page once
        for lp in range(self.max_pages_per_slot):
            phys = int(self._table[slot, lp])
            if phys >= 0 and phys not in freed:
                self._free.append(phys)
                freed.add(phys)
            self._table[slot, lp] = -1

    # -- lifecycle hooks -----------------------------------------------------

    def _check_prompt(self, prompt: List[int]) -> None:
        super()._check_prompt(prompt)
        need = self._pages_needed(self._worst_case_tokens(len(prompt)))
        if self._ring_pages:
            need = min(need, self._ring_pages)
        if need > self.pool_pages:
            # accepted-but-never-admittable would park the queue head
            # forever and starve everything behind it
            raise ValueError(
                f"request needs {need} pages worst-case but the pool has "
                f"only {self.pool_pages} — raise n_pages or lower "
                f"max_new_tokens"
            )

    def _note_admitted(self, slot: int, prompt: List[int]) -> None:
        self._host_len[slot] = len(prompt) + 1

    def _note_emitted(self, slot: int) -> None:
        self._host_len[slot] += 1

    def _on_retire(self, slot: int) -> None:
        self._host_len[slot] = 0
        self._release_pages(slot)          # pages back to the pool NOW

    # -- device legs ---------------------------------------------------------

    def _admit_device(self, prompt: List[int], slot: int):
        """Reserve worst-case pages, dispatch the prefill. None when the
        pool cannot cover the request (nothing mutated); otherwise the
        first token as a DEVICE scalar (no host sync — the defer path
        depends on it)."""
        if not self._alloc_pages(slot, self._worst_case_tokens(len(prompt))):
            return None
        bucket = self._bucket(len(prompt))
        padded = prompt + [0] * (bucket - len(prompt))
        prefill_row = self._table[slot]
        if self._ring_pages:
            # Prefill scatters every bucket page in ONE .at[].set; logical
            # pages aliased onto the same ring page would be duplicate
            # scatter indices (undefined winner). Keep exactly the last
            # ring-many REAL prompt pages: earlier prompt pages are
            # superseded (outside every future band), and pad-only bucket
            # pages must NOT win an aliased write over live prompt data
            # (review r5: bucket padding displaced real pages) — their
            # positions are masked until decode overwrites them token by
            # token, so dropping their writes is free.
            prompt_pages = self._pages_needed(len(prompt))
            phys_live = len({int(p) for p in self._table[slot] if p >= 0})
            keep_lo = max(0, prompt_pages - phys_live)
            if keep_lo > 0 or self._pages_needed(bucket) > prompt_pages:
                prefill_row = self._table[slot].copy()
                prefill_row[:keep_lo] = -1
                prefill_row[prompt_pages:] = -1
        self.k_pages, self.v_pages, first, first_lp = self._prefill_slot(
            self.params, self.k_pages, self.v_pages,
            jnp.asarray(padded, jnp.int32),
            jnp.asarray(prefill_row),
            jnp.int32(len(prompt)), self._next_rng(),
            jnp.float32(self._slot_temp[slot]),
            jnp.int32(self._slot_topk[slot]),
            jnp.float32(self._slot_topp[slot]),
        )
        return first, first_lp

    def _device_step(self) -> "tuple[np.ndarray, np.ndarray]":
        # worst-case pages were reserved at admission, so boundary
        # crossings never fail; the REAL table (with -1 sentinels) flows
        # to the device — the attention core masks unmapped pages
        self.k_pages, self.v_pages, nxt, self.pos, lp = self._step_all(
            self.params, self.k_pages, self.v_pages,
            jnp.asarray(self._table),
            self.last, self.pos, jnp.asarray(self.active), self._next_rng(),
            jnp.asarray(self._slot_temp), jnp.asarray(self._slot_topk),
            jnp.asarray(self._slot_topp),
        )
        self.last = nxt
        return np.asarray(nxt), np.asarray(lp)

    def warmup(self) -> None:
        """Pre-compile every prompt bucket + the step (serving.warmup's
        rationale). Only valid while NO request is active: the dummy
        prefill scribbles on pool pages a live sequence may have mapped."""
        d_temp, d_tk, d_tp = self._default_sampling
        row = np.full((self.max_pages_per_slot,), -1, np.int32)
        row[: self._pages_needed(self.max_seq)] = np.arange(
            self._pages_needed(self.max_seq)
        ) % self.pool_pages

        def prefill_dummy(padded):
            self.k_pages, self.v_pages, _f, _lp = self._prefill_slot(
                self.params, self.k_pages, self.v_pages,
                jnp.asarray(padded, jnp.int32), jnp.asarray(row), jnp.int32(1),
                self._next_rng(), jnp.float32(d_temp), jnp.int32(d_tk),
                jnp.float32(d_tp),
            )

        self._warmup_buckets(prefill_dummy)
        self.k_pages, self.v_pages, _n, _p, _lps = self._step_all(
            self.params, self.k_pages, self.v_pages,
            jnp.asarray(self._table), self.last, self.pos,
            jnp.asarray(np.zeros((self.n_slots,), bool)), self._next_rng(),
            jnp.asarray(self._slot_temp), jnp.asarray(self._slot_topk),
            jnp.asarray(self._slot_topp),
        )
        # drain the dispatch queue so the first live admission doesn't pay
        # (and record) the queued warmup executions as admission stall —
        # same rationale as serving.DecodeServer.warmup
        jax.block_until_ready((self.k_pages, self.v_pages))
