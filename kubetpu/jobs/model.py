"""Flagship model: a decoder-only transformer, TPU-first.

This is the demonstration workload of the framework: the thing kubetpu's
scheduler *arranges hardware for* (the reference's analog is the NCCL jobs
whose bandwidth its NVLink scoring proxies, SURVEY.md §2 "parallelism"
note). Design choices are XLA/TPU-native, not ported from anywhere:

- llama-style block: RMSNorm, rotary embeddings, SwiGLU MLP;
- layer parameters are *stacked* on a leading axis and the forward pass is
  one ``lax.scan`` over layers — a single traced block body, fast compiles,
  and clean ``jax.checkpoint`` rematerialisation;
- matmuls stay large and fused (einsum), bfloat16-friendly;
- the attention core is pluggable so the sequence-parallel ring attention
  (``kubetpu.jobs.ring_attention``) drops in under ``shard_map`` without
  touching the model.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

Params = Dict[str, Any]
# attention core signature: (q, k, v) with shapes (B, S, H, D) -> (B, S, H, D)
AttnFn = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab: int = 256
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    d_ff: int = 512
    max_seq: int = 1024
    rope_theta: float = 10000.0
    dtype: Any = jnp.float32  # bfloat16 on TPU
    remat: bool = False      # jax.checkpoint the scanned block
    # rematerialisation policy when ``remat`` is set. "full" recomputes the
    # whole block forward during backward (max memory savings, ~1 extra fwd
    # of HARDWARE flops — an MFU ceiling of 3/4); "dots" saves every matmul
    # output and recomputes only the cheap elementwise/norm ops (XLA's
    # dots_with_no_batch_dims_saveable policy — near-zero recompute FLOPs,
    # activation memory between full-remat and none). Measured on the v5e:
    # the flagship 0.75B fits batch 4 x seq 2048 under "dots", trading the
    # recompute pass for MFU (see BENCH_MODEL.json train rows).
    remat_policy: str = "full"
    n_experts: int = 0       # 0 = dense SwiGLU; >0 = top-1 MoE in every block
    # 0.0 = dense one-hot dispatch (demo path: E-times activations, zero
    # collectives); > 0 = capacity-based dispatch (production path: each
    # expert processes at most capacity_factor*N/E tokens, XLA inserts the
    # all_to_all over ep; overflowing tokens fall through on the residual)
    moe_capacity_factor: float = 0.0
    # switch-transformer load-balance auxiliary loss coefficient: adds
    # coeff * E * sum_e(frac_tokens_e * mean_prob_e) to next_token_loss,
    # keeping the router from collapsing onto few experts (0 = off)
    moe_aux_coeff: float = 0.0
    # experts per token on the capacity path (1 = switch routing, the
    # default; 2 = GShard/Mixtral-style top-2). Gate weights are the RAW
    # router probabilities for every k (no renormalization), so k=1
    # reproduces switch exactly and the router always gets gradient
    # through the gate. Primary choices claim capacity slots before
    # secondary ones; size capacity_factor for k tokens-per-expert-slots.
    moe_top_k: int = 1
    # 0 = compute the full (B, S, V) logits at the loss (small models);
    # > 0 = stream the LM-head matmul + cross-entropy over sequence chunks
    # of this size (must divide S; under sp, keep S/chunk a multiple of
    # sp). Cuts peak loss-tail HBM from O(S*V) to O(chunk*V) — for the
    # 32k-vocab flagship that is ~2 GB of f32 logits+softmax freed, which
    # is what lets the larger batch fit (see chunked_token_cross_entropy).
    # Honored by every training tail: next_token_loss, the pipelined step,
    # seq2seq_loss, and masked_lm_loss.
    loss_chunk: int = 0
    # uniform label smoothing mass (0 = off): per-position loss becomes
    # (1-e)*nll - e*mean(logp). Applied in BOTH loss-tail memory modes
    # (lm_loss_tail / _position_losses), every LM family.
    label_smoothing: float = 0.0
    # PaLM-style z-loss coefficient (0 = off): + z * logsumexp(logits)^2
    # per position — pins the softmax normalizer near 1 so bf16 logits
    # don't drift over long runs. Same scope as label_smoothing.
    z_loss: float = 0.0
    # sliding-window (local) attention: each position attends to the
    # previous `window` positions including itself (0 = full causal).
    # Honored by the default dense core, the flash kernel (which then
    # skips out-of-window key blocks in BOTH directions — O(window) work
    # per position), and the decode cache read. Ring attention does not
    # compose with a window (validated at step build).
    window: int = 0
    # Llama-3.1 RoPE context-extension frequency warp: (factor,
    # low_freq_factor, high_freq_factor, original_max_position_embeddings)
    # or None (plain rope). A hashable tuple (not the HF dict) so the
    # frozen config stays usable as a static value; hf_import fills it
    # from checkpoint rope_scaling. Applied at every rope site (training,
    # decode, paged).
    rope_llama3_scaling: Optional[tuple] = None
    # grouped-query attention: number of K/V heads (0 = n_heads, plain MHA;
    # 1 = MQA). Must divide n_heads; the decode KV cache stores only these,
    # cutting its HBM footprint by n_heads/n_kv_heads. With tensor
    # parallelism, tp must divide n_kv_heads (the kv-head axis is the one
    # sharded over tp).
    n_kv_heads: int = 0

    def __post_init__(self):
        if self.moe_top_k < 1 or (self.n_experts and self.moe_top_k > self.n_experts):
            raise ValueError(
                f"moe_top_k ({self.moe_top_k}) must be in [1, n_experts]"
            )
        if self.moe_top_k > 1 and self.n_experts > 0 and self.moe_capacity_factor <= 0:
            raise ValueError(
                "moe_top_k > 1 requires the capacity dispatch path "
                "(set moe_capacity_factor > 0)"
            )
        if self.loss_chunk < 0:
            raise ValueError(f"loss_chunk must be >= 0, got {self.loss_chunk}")
        if not 0.0 <= self.label_smoothing < 1.0:
            raise ValueError(
                f"label_smoothing must be in [0, 1), got {self.label_smoothing}"
            )
        if self.z_loss < 0:
            raise ValueError(f"z_loss must be >= 0, got {self.z_loss}")
        if self.window < 0:
            raise ValueError(f"window must be >= 0, got {self.window}")
        if self.rope_llama3_scaling is not None:
            s = self.rope_llama3_scaling
            if (not isinstance(s, tuple) or len(s) != 4
                    or not all(isinstance(x, (int, float)) for x in s)):
                raise ValueError(
                    "rope_llama3_scaling must be a (factor, low_freq_factor, "
                    "high_freq_factor, original_max_position_embeddings) "
                    f"tuple (not the HF dict), got {s!r}"
                )
            if s[1] == s[2]:
                raise ValueError(
                    "rope_llama3_scaling low_freq_factor == high_freq_factor "
                    "divides by zero in the smoothing band"
                )
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(
                f"remat_policy must be 'full' or 'dots', got {self.remat_policy!r}"
            )
        if self.n_kv_heads and self.n_heads % self.n_kv_heads:
            raise ValueError(
                f"n_kv_heads ({self.n_kv_heads}) must divide "
                f"n_heads ({self.n_heads})"
            )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads


def init_params(rng: jax.Array, cfg: ModelConfig) -> Params:
    """Parameter pytree; per-layer tensors stacked on a leading L axis."""
    k_embed, k_layers, k_out = jax.random.split(rng, 3)
    d, h, hd, f, L = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff, cfg.n_layers

    def norm(key, *shape):
        return jax.random.normal(key, shape, cfg.dtype)

    ks = jax.random.split(k_layers, 7)
    scale = d ** -0.5
    kv = cfg.kv_heads  # == h for MHA: init stays bit-identical per seed
    blocks: Params = {
        "ln1": jnp.ones((L, d), cfg.dtype),
        "ln2": jnp.ones((L, d), cfg.dtype),
        "wq": norm(ks[0], L, d, h, hd) * scale,
        "wk": norm(ks[1], L, d, kv, hd) * scale,
        "wv": norm(ks[2], L, d, kv, hd) * scale,
        "wo": norm(ks[3], L, h, hd, d) * (h * hd) ** -0.5,
    }
    if cfg.n_experts > 0:
        E = cfg.n_experts
        # fold_in rather than widening the split: dense-model init stays
        # bit-identical for a given seed whether or not MoE exists
        k_router = jax.random.fold_in(k_layers, 7)
        blocks.update(
            {
                "moe_router": norm(k_router, L, d, E) * scale,
                "w_gate": norm(ks[4], L, E, d, f) * scale,
                "w_up": norm(ks[5], L, E, d, f) * scale,
                "w_down": norm(ks[6], L, E, f, d) * f ** -0.5,
            }
        )
    else:
        blocks.update(
            {
                "w_gate": norm(ks[4], L, d, f) * scale,
                "w_up": norm(ks[5], L, d, f) * scale,
                "w_down": norm(ks[6], L, f, d) * f ** -0.5,
            }
        )
    params: Params = {
        "embed": norm(k_embed, cfg.vocab, d) * scale,
        "blocks": blocks,
        "ln_f": jnp.ones((d,), cfg.dtype),
        "head": norm(k_out, d, cfg.vocab) * scale,
    }
    return params


def remat_xla_policy(cfg: ModelConfig):
    """The ``jax.checkpoint`` policy for *cfg* (None = save nothing)."""
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    return None


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps).astype(x.dtype)) * scale


def rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float,
         llama3_scaling=None) -> jnp.ndarray:
    """Rotary position embedding. x: (B, S, H, D), positions: (S,) or (B, S).

    ``llama3_scaling`` = (factor, low_freq_factor, high_freq_factor,
    original_max_position_embeddings): the Llama-3.1 context-extension
    frequency warp (``cfg.rope_llama3_scaling``) — long wavelengths divide
    by *factor*, short ones pass through, the band between interpolates
    smoothly. Matches the HF reference formula exactly (pinned by the
    hf_import cross-framework tests)."""
    d_half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(0, d_half, dtype=jnp.float32) / d_half)
    if llama3_scaling is not None:
        factor, lo, hi, old_len = llama3_scaling
        wavelen = 2.0 * jnp.pi / freqs
        scaled = jnp.where(wavelen > old_len / lo, freqs / factor, freqs)
        smooth = (old_len / wavelen - lo) / (hi - lo)
        smoothed = (1.0 - smooth) * scaled / factor + smooth * scaled
        medium = (wavelen >= old_len / hi) & (wavelen <= old_len / lo)
        freqs = jnp.where(medium, smoothed, scaled)
    angles = positions.astype(jnp.float32)[..., None] * freqs  # (..., S, d_half)
    if angles.ndim == 2:  # (S, d_half) -> broadcast over batch
        angles = angles[None]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :d_half], x[..., d_half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """Expand grouped K/V heads to the full query-head count
    (..., H_kv, D) -> (..., H_kv * n_rep, D). Identity for MHA."""
    if n_rep == 1:
        return x
    return jnp.repeat(x, n_rep, axis=2)


def dense_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = True,
    window: int = 0,
) -> jnp.ndarray:
    """Reference attention core: full softmax, causal or bidirectional —
    ONE body so numerics fixes serve both (mirrors the flash kernel's
    causal kwarg). ``window > 0`` (causal only): sliding-window band —
    each position sees the previous ``window`` positions including
    itself. (B, S, H, D) in/out."""
    if window > 0 and not causal:
        raise ValueError("window > 0 requires causal attention")
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        if window > 0:
            pos = jnp.arange(s)
            mask &= pos[:, None] - pos[None, :] < window
        scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def dense_causal_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Causal spelling of ``dense_attention`` (the decoder default)."""
    return dense_attention(q, k, v, causal=True)


def default_attn_fn(cfg: ModelConfig) -> AttnFn:
    """THE default attention core for a config — every path that lets
    ``attn_fn`` default (training forward, prefill, seq2seq decoder,
    train-step dense branch) resolves through here, so a window (or any
    future default-attention knob) can never be honored in one path and
    silently dropped in another."""
    if cfg.window > 0:
        return partial(dense_attention, causal=True, window=cfg.window)
    return dense_causal_attention


def _moe_aux_from_probs(probs: jnp.ndarray, top_k: int = 1) -> jnp.ndarray:
    """Switch-transformer load-balance term from router probs (B, S, E) or
    (N, E): E * sum_e(f_e * P_e), minimized (= 1) when routing is uniform.
    f_e = fraction of token-assignments routed to e (non-differentiable),
    P_e = mean router probability (carries the gradient). With top_k > 1,
    f_e counts ALL k assignments per token (mean of the k one-hots), so
    balance pressure sees secondary-expert load too — argmax-only would
    understate real expert load under top-2 routing."""
    probs = probs.reshape(-1, probs.shape[-1])
    e = probs.shape[-1]
    _, topk_idx = jax.lax.top_k(probs, top_k)          # (N, k)
    frac = jnp.mean(jax.nn.one_hot(topk_idx, e, dtype=jnp.float32), axis=(0, 1))
    mean_prob = jnp.mean(probs, axis=0)
    return e * jnp.sum(jax.lax.stop_gradient(frac) * mean_prob)


def _mlp(cfg: ModelConfig, h: jnp.ndarray, layer: Params):
    """The block's MLP branch (dense SwiGLU / dense-dispatch MoE /
    capacity-dispatch MoE) -> (residual delta, aux loss term). One
    implementation shared by training forward, pipeline stages, and the
    decode path so they can never diverge."""
    aux = jnp.zeros((), jnp.float32)
    if cfg.n_experts > 0 and cfg.moe_capacity_factor > 0:
        out, probs = _moe_mlp_capacity(h, layer, cfg.moe_capacity_factor,
                                       cfg.moe_top_k)
    elif cfg.n_experts > 0:
        out, probs = _moe_mlp(h, layer)
    else:
        gate = jax.nn.silu(jnp.einsum("bsd,df->bsf", h, layer["w_gate"]))
        up = jnp.einsum("bsd,df->bsf", h, layer["w_up"])
        return jnp.einsum("bsf,fd->bsd", gate * up, layer["w_down"]), aux
    if cfg.moe_aux_coeff > 0:
        aux = _moe_aux_from_probs(probs, cfg.moe_top_k)
    return out, aux


def _block(
    cfg: ModelConfig,
    attn_fn: AttnFn,
    positions: jnp.ndarray,
    x: jnp.ndarray,
    layer: Params,
) -> jnp.ndarray:
    """One transformer block (the lax.scan body)."""
    x, _aux, _k, _v = _block_with_aux(cfg, attn_fn, positions, x, layer)
    return x


def _block_with_aux(
    cfg: ModelConfig,
    attn_fn: AttnFn,
    positions: jnp.ndarray,
    x: jnp.ndarray,
    layer: Params,
):
    """One transformer block; also returns the layer's MoE aux-loss term
    (0.0 for dense blocks) and the rotary-embedded (k, v) projections (for
    prefill cache filling)."""
    h = rms_norm(x, layer["ln1"])
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"])
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"])
    q = rope(q, positions, cfg.rope_theta, cfg.rope_llama3_scaling)
    k = rope(k, positions, cfg.rope_theta, cfg.rope_llama3_scaling)
    # GQA: expand grouped K/V to full heads ONLY for the attention core, so
    # every core (dense, flash, ring) sees equal head counts; the returned
    # k/v stay at kv_heads width — that is what the decode cache stores.
    n_rep = cfg.n_heads // cfg.kv_heads
    attn = attn_fn(q, repeat_kv(k, n_rep), repeat_kv(v, n_rep))
    x = x + jnp.einsum("bshk,hkd->bsd", attn, layer["wo"])

    h = rms_norm(x, layer["ln2"])
    delta, aux = _mlp(cfg, h, layer)
    return x + delta, aux, k, v


def _moe_mlp_capacity(
    h: jnp.ndarray, layer: Params, capacity_factor: float, top_k: int = 1
) -> "tuple[jnp.ndarray, jnp.ndarray]":
    """Top-k mixture-of-experts with capacity-based dispatch — the
    production path (k=1: switch routing; k=2: GShard/Mixtral-style).

    Tokens are assigned a slot inside their chosen expert's capacity buffer
    (position = running count of earlier tokens routed to that expert); the
    dispatch einsum gathers at most ``C = capacity_factor * N / E`` tokens
    per expert into an (E, C, D) buffer, experts run on their buffers only
    (total expert FLOPs ~ N*D*F instead of the dense path's E*N*D*F), and
    the combine einsum scatters results back. With experts sharded over
    ``ep`` XLA turns dispatch/combine into the all_to_all pair. Tokens past
    capacity are dropped — they ride the residual connection (standard
    switch-transformer semantics).
    """
    b, s, d = h.shape
    n = b * s
    e = layer["moe_router"].shape[-1]
    k = top_k
    tokens = h.reshape(n, d)

    router = (tokens @ layer["moe_router"]).astype(jnp.float32)   # (N, E)
    probs = jax.nn.softmax(router, axis=-1)
    topw, topi = jax.lax.top_k(probs, k)                          # (N, K)
    # RAW router probabilities as gate weights for every k: k=1 reproduces
    # switch exactly, and the router gets gradient through the gate
    # without an aux-loss dependency (a renormalized k=1 gate would be
    # the constant 1.0 — gradient-dead)
    gate_w = topw                                                 # (N, K) f32
    onehot = jax.nn.one_hot(topi, e, dtype=jnp.float32)           # (N, K, E)

    capacity = max(1, int(capacity_factor * n / e))
    # slot of each (token, rank) within its expert. RANK-MAJOR cumsum:
    # every token's primary choice claims its slot before any secondary
    # choice competes — flatten (K, N, E) so rank-0 rows come first
    flat = onehot.transpose(1, 0, 2).reshape(k * n, e)            # (K*N, E)
    position = jnp.cumsum(flat, axis=0) * flat                    # 1-based
    keep = (position <= capacity).astype(jnp.float32) * flat
    slot_onehot = jax.nn.one_hot(
        (position - 1.0).astype(jnp.int32), capacity, dtype=jnp.float32
    )                                                             # (K*N, E, C)
    dispatch = (
        (keep[..., None] * slot_onehot)
        .reshape(k, n, e, capacity)
        .transpose(1, 0, 2, 3)
        .astype(h.dtype)
    )                                                             # (N, K, E, C)

    expert_in = jnp.einsum("nkec,nd->ecd", dispatch, tokens)      # (E, C, D)
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, layer["w_gate"]))
    up = jnp.einsum("ecd,edf->ecf", expert_in, layer["w_up"])
    out = jnp.einsum("ecf,efd->ecd", gate * up, layer["w_down"])  # (E, C, D)

    combined = jnp.einsum(
        "nkec,ecd,nk->nd", dispatch.astype(jnp.float32),
        out.astype(jnp.float32), gate_w,
    ).astype(h.dtype)
    return combined.reshape(b, s, d), probs


def _moe_mlp(h: jnp.ndarray, layer: Params) -> jnp.ndarray:
    """Top-1 mixture-of-experts SwiGLU with dense dispatch.

    Dense dispatch (one-hot einsum instead of capacity-based all_to_all)
    keeps the routing entirely in large einsums the MXU likes and lets
    GSPMD shard the expert axis over ``ep`` with zero manual collectives;
    the E-times activation cost is the standard demo trade-off — a
    capacity-bucketed all_to_all dispatch is the production upgrade path.
    Gradients reach the router through the top-1 probability weighting.
    """
    router = jnp.einsum("bsd,de->bse", h, layer["moe_router"]).astype(jnp.float32)
    probs = jax.nn.softmax(router, axis=-1)
    top1 = jnp.argmax(probs, axis=-1)                        # (B, S)
    one_hot = jax.nn.one_hot(top1, probs.shape[-1], dtype=h.dtype)  # (B, S, E)
    weight = jnp.sum(probs * one_hot, axis=-1, keepdims=True).astype(h.dtype)

    expert_in = jnp.einsum("bse,bsd->ebsd", one_hot, h)      # zeros off-route
    gate = jax.nn.silu(jnp.einsum("ebsd,edf->ebsf", expert_in, layer["w_gate"]))
    up = jnp.einsum("ebsd,edf->ebsf", expert_in, layer["w_up"])
    out = jnp.einsum("ebsf,efd->ebsd", gate * up, layer["w_down"])
    return jnp.einsum("ebsd,bse->bsd", out, one_hot) * weight, probs


def forward(
    params: Params,
    tokens: jnp.ndarray,
    cfg: ModelConfig,
    attn_fn: Optional[AttnFn] = None,
    positions: Optional[jnp.ndarray] = None,
    return_aux: bool = False,
) -> jnp.ndarray:
    """Logits for next-token prediction. tokens: (B, S) int32 -> (B, S, V);
    with ``return_aux`` also the summed MoE load-balance term.

    ``positions`` defaults to 0..S-1; sequence-parallel callers pass global
    positions for their shard.
    """
    x, aux = forward_hidden(params, tokens, cfg, attn_fn, positions)
    logits = jnp.einsum("bsd,dv->bsv", x, params["head"])
    if return_aux:
        return logits, aux
    return logits


def forward_hidden(
    params: Params,
    tokens: jnp.ndarray,
    cfg: ModelConfig,
    attn_fn: Optional[AttnFn] = None,
    positions: Optional[jnp.ndarray] = None,
):
    """The block stack without the LM head: final-norm hidden states
    (B, S, D) plus the summed MoE aux term. This is what an encoder
    producing memory for cross-attention consumes (``jobs.seq2seq``)."""
    if attn_fn is None:
        attn_fn = default_attn_fn(cfg)
    if positions is None:
        positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)

    x = params["embed"][tokens]  # (B, S, D) gather rides the MXU-free path
    body = partial(_block_with_aux, cfg, attn_fn, positions)

    def scan_body(carry, layer):
        x, aux, _k, _v = body(carry, layer)
        return x, aux

    if cfg.remat:
        scan_body = jax.checkpoint(scan_body, policy=remat_xla_policy(cfg))
    x, auxes = jax.lax.scan(scan_body, x, params["blocks"])
    return rms_norm(x, params["ln_f"]), jnp.sum(auxes)


def forward_with_kv(
    params: Params,
    tokens: jnp.ndarray,
    cfg: ModelConfig,
    attn_fn: Optional[AttnFn] = None,
):
    """Batched forward that also returns every layer's rotary-embedded K/V
    stacks — the prefill path of the decode cache. Uses the exact same
    block implementation as training (including the MoE dispatch mode), so
    prefill can never drift from the trained model. *attn_fn* swaps the
    causal core — e.g. ring attention over an sp mesh for LONG-context
    prefill, where the prompt pass is the compute-heavy phase.

    Returns (last-position logits (B, V) float32, ks (L, B, S, H_kv, D),
    vs (L, B, S, H_kv, D)).
    """
    positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)
    x = params["embed"][tokens]
    body = partial(_block_with_aux, cfg, attn_fn or default_attn_fn(cfg),
                   positions)

    def scan_body(carry, layer):
        x, _aux, k, v = body(carry, layer)
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(scan_body, x, params["blocks"])
    x = rms_norm(x, params["ln_f"])
    logits = jnp.einsum("bsd,dv->bsv", x, params["head"])[:, -1]
    return logits.astype(jnp.float32), ks, vs


def _position_losses(logits, targets, label_smoothing, z_loss):
    """Per-position loss in f32 from raw logits — THE formula both loss
    tails (materialized and chunked) share, so they cannot diverge:

    - cross-entropy, optionally label-smoothed: ``(1-e)*nll - e*mean(logp)``
      (uniform smoothing mass over the vocab);
    - PaLM-style z-loss ``z * logsumexp(logits)^2`` — pulls the softmax
      normalizer toward 1, keeping bf16 logits from drifting large over
      long runs (a stability term, near-zero gradient when healthy).
    """
    f32 = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(f32, axis=-1)
    logp = f32 - lse[..., None]
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if label_smoothing > 0:
        nll = ((1.0 - label_smoothing) * nll
               - label_smoothing * jnp.mean(logp, axis=-1))
    if z_loss > 0:
        nll = nll + z_loss * jnp.square(lse)
    return nll


def token_cross_entropy(
    logits: jnp.ndarray,
    targets: jnp.ndarray,
    weights: Optional[jnp.ndarray] = None,
    label_smoothing: float = 0.0,
    z_loss: float = 0.0,
) -> jnp.ndarray:
    """Token-level cross-entropy in float32 — the shared loss tail of the
    causal, pipelined, and masked-LM training paths. Unweighted mean by
    default; with *weights* (same shape as targets) a weighted mean over
    the nonzero-weight positions (the masked-LM reduction). Optional
    label smoothing and z-loss per ``_position_losses``."""
    nll = _position_losses(logits, targets, label_smoothing, z_loss)
    if weights is None:
        return jnp.mean(nll)
    w = weights.astype(jnp.float32)
    return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)


def chunked_token_cross_entropy(
    x: jnp.ndarray,
    head: jnp.ndarray,
    targets: jnp.ndarray,
    chunk: int,
    weights: Optional[jnp.ndarray] = None,
    label_smoothing: float = 0.0,
    z_loss: float = 0.0,
) -> jnp.ndarray:
    """Cross-entropy from HIDDEN states without ever materializing the full
    (B, S, V) logits: scan over sequence chunks, each computing its
    (B, chunk, V) head matmul + log-softmax and reducing to scalars. The
    chunk body is ``jax.checkpoint``-ed, so backward recomputes one chunk's
    logits at a time too — peak loss-tail memory drops from O(S*V) to
    O(chunk*V) at the cost of one extra head matmul (a few % of step FLOPs
    for the flagship, bought back by the larger batch the freed HBM
    admits; see BENCH_MODEL.json loss_chunk rows).

    ``chunk`` must divide S. Under sequence parallelism pick a chunk count
    that is a multiple of sp so the (B, S, D) -> (nc, B, chunk, D) reshape
    lands on shard boundaries and GSPMD inserts no resharding.
    """
    b, s, d = x.shape
    if s % chunk:
        raise ValueError(f"chunk ({chunk}) must divide sequence length ({s})")
    nc = s // chunk
    xc = x.reshape(b, nc, chunk, d).transpose(1, 0, 2, 3)        # (nc, B, C, D)
    tc = targets.reshape(b, nc, chunk).transpose(1, 0, 2)        # (nc, B, C)
    if weights is None:
        wc = jnp.ones((nc, b, chunk), jnp.float32)
    else:
        wc = weights.reshape(b, nc, chunk).transpose(1, 0, 2).astype(jnp.float32)

    @jax.checkpoint
    def body(carry, ch):
        nll_sum, w_sum = carry
        xi, ti, wi = ch
        logits = jnp.einsum("bcd,dv->bcv", xi, head)
        nll = _position_losses(logits, ti, label_smoothing, z_loss)
        return (nll_sum + jnp.sum(nll * wi), w_sum + jnp.sum(wi)), None

    (nll_sum, w_sum), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (xc, tc, wc),
    )
    return nll_sum / jnp.maximum(w_sum, 1.0)


def lm_loss_tail(
    x: jnp.ndarray,
    head: jnp.ndarray,
    targets: jnp.ndarray,
    cfg: ModelConfig,
    weights: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """THE loss tail: final-norm hidden states -> mean cross-entropy, as
    either one materialized (B, S, V) logits tensor or the chunked stream
    (``cfg.loss_chunk``). Every LM-shaped family (causal, pipelined,
    seq2seq decoder, masked-LM) ends here, so a tail change — z-loss,
    label smoothing — lands everywhere at once and the two memory modes
    can never diverge."""
    if cfg.loss_chunk > 0:
        return chunked_token_cross_entropy(
            x, head, targets, cfg.loss_chunk, weights,
            label_smoothing=cfg.label_smoothing, z_loss=cfg.z_loss)
    logits = jnp.einsum("bsd,dv->bsv", x, head)
    return token_cross_entropy(logits, targets, weights,
                               label_smoothing=cfg.label_smoothing,
                               z_loss=cfg.z_loss)


def next_token_loss(
    params: Params,
    tokens: jnp.ndarray,
    targets: jnp.ndarray,
    cfg: ModelConfig,
    attn_fn: Optional[AttnFn] = None,
    positions: Optional[jnp.ndarray] = None,
    weights: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Mean causal LM cross-entropy.

    ``targets`` is ``tokens`` shifted by one (the data pipeline's job): with
    the sequence axis sharded for sequence parallelism, an in-model
    ``[:, 1:]`` shift would need a cross-shard halo exchange for nothing.

    With ``cfg.loss_chunk > 0`` the loss tail streams over sequence chunks
    (``chunked_token_cross_entropy``) instead of materializing (B, S, V)
    logits — numerically identical (same f32 log-softmax per position, same
    mean), different memory/FLOPs trade.

    ``weights`` (B, S) masks positions out of the mean — the packed-batch
    path (``data.pack_documents(mode="greedy")``) zeroes pad positions.
    """
    x, aux = forward_hidden(params, tokens, cfg, attn_fn, positions)
    loss = lm_loss_tail(x, params["head"], targets, cfg, weights)
    if cfg.n_experts > 0 and cfg.moe_aux_coeff > 0:
        loss = loss + cfg.moe_aux_coeff * aux
    return loss
