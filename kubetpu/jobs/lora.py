"""LoRA fine-tuning: low-rank adapters over the flagship model family.

TPU-first shape of the idea: adapters are STACKED on the leading layer axis
exactly like the base blocks (one lax.scan body, one pair of einsums per
target), and fine-tuning is expressed *functionally* — the base parameters
are an untouched input of the jitted step, the effective weights
``W + (alpha/r) * A @ B`` are materialized inside the traced computation
(XLA fuses the rank-r update into the surrounding graph; no model-code
changes, no module surgery), and ONLY the adapters carry gradients and
optimizer state. Memory cost of training therefore scales with the adapter
count (two rank-r factors per target per layer) instead of the model: for
the 0.75B flagship at rank 8 the trainable fraction is ~0.1%, which is the
entire point — adamw moments for the full model are 2 x 4 bytes/param,
LoRA's are negligible, so fine-tuning fits where pretraining wouldn't.

The reference (microsoft/KubeGPU) has no training stack at all — this
module extends the framework's job layer the same way the other families
do, reusing ``make_update_step`` so every step-level feature (grad
accumulation, non-finite guard) applies to LoRA runs unchanged.

Merging for export is the same function the train step traces
(``merge_lora``): serving/decode consume the merged params with zero
inference-time overhead.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubetpu.jobs import model as model_lib
from kubetpu.jobs.model import ModelConfig, Params
from kubetpu.jobs.train import (
    TrainState,
    _filter_spec,
    _resolve_attention,
    _shardings,
    batch_spec,
    make_optimizer,
    make_update_step,
)

# per-target factor layout: (A einsum, B einsum) contract over rank r with
# the layer axis batched. A carries the IN dims, B the OUT dims of the base
# weight, so delta = A @ B lands in the base's exact shape.
_MERGE_EINSUM = {
    "wq": "ldr,lrhk->ldhk",
    "wk": "ldr,lrhk->ldhk",
    "wv": "ldr,lrhk->ldhk",
    "wo": "lhkr,lrd->lhkd",
    "w_gate": "ldr,lrf->ldf",
    "w_up": "ldr,lrf->ldf",
    "w_down": "lfr,lrd->lfd",
}
_MLP_TARGETS = ("w_gate", "w_up", "w_down")


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    """rank/alpha and which base weights get adapters. Default targets are
    the attention projections (the standard LoRA recipe); MLP targets are
    valid for DENSE models only (MoE expert weights carry an expert axis
    the rank-r factorization here doesn't model)."""

    rank: int = 8
    alpha: float = 16.0
    targets: Tuple[str, ...] = ("wq", "wk", "wv", "wo")

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError(f"rank must be >= 1, got {self.rank}")
        unknown = [t for t in self.targets if t not in _MERGE_EINSUM]
        if unknown:
            raise ValueError(
                f"unknown LoRA target(s) {unknown}; choose from "
                f"{sorted(_MERGE_EINSUM)}"
            )
        if not self.targets:
            raise ValueError("LoRA needs at least one target")

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def _check_targets(cfg: ModelConfig, lcfg: LoraConfig) -> None:
    if cfg.n_experts > 0 and any(t in _MLP_TARGETS for t in lcfg.targets):
        raise ValueError(
            "MLP LoRA targets are unsupported for MoE configs (expert-axis "
            "weights); restrict targets to the attention projections"
        )


def _factor_shapes(cfg: ModelConfig, target: str, r: int):
    """(A shape, B shape) for one target, mirroring init_params layouts."""
    L, d, h, hd, f = (cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.head_dim,
                      cfg.d_ff)
    kv = cfg.kv_heads
    if target in ("wq", "wk", "wv"):
        heads = h if target == "wq" else kv
        return (L, d, r), (L, r, heads, hd)
    if target == "wo":
        return (L, h, hd, r), (L, r, d)
    if target in ("w_gate", "w_up"):
        return (L, d, r), (L, r, f)
    return (L, f, r), (L, r, d)  # w_down


def init_lora_params(rng: jax.Array, cfg: ModelConfig,
                     lcfg: LoraConfig) -> Params:
    """A ~ N(0, 1/in_dim), B = 0 — the adapter delta starts at exactly
    zero, so step 0 of fine-tuning reproduces the base model bit-for-bit
    (pinned by tests)."""
    _check_targets(cfg, lcfg)
    blocks: Params = {}
    for i, t in enumerate(lcfg.targets):
        a_shape, b_shape = _factor_shapes(cfg, t, lcfg.rank)
        in_dim = 1
        for s in a_shape[1:-1]:
            in_dim *= s
        k = jax.random.fold_in(rng, i)
        blocks[f"{t}_a"] = (
            jax.random.normal(k, a_shape, cfg.dtype) * in_dim ** -0.5
        )
        blocks[f"{t}_b"] = jnp.zeros(b_shape, cfg.dtype)
    return {"blocks": blocks}


def lora_param_specs(cfg: ModelConfig, lcfg: LoraConfig) -> Params:
    """Shardings consistent with train.param_specs: whichever base axis is
    on tp stays on tp in the factor that carries it; the rank axis is tiny
    and always replicated."""
    specs: Params = {}
    for t in lcfg.targets:
        if t in ("wq", "wk", "wv"):
            a, b = P(None, None, None), P(None, None, "tp", None)
        elif t == "wo":
            a, b = P(None, "tp", None, None), P(None, None, None)
        elif t in ("w_gate", "w_up"):
            a, b = P(None, None, None), P(None, None, "tp")
        else:  # w_down
            a, b = P(None, "tp", None), P(None, None, None)
        specs[f"{t}_a"], specs[f"{t}_b"] = a, b
    return {"blocks": specs}


def merge_lora(base: Params, lora: Params, lcfg: LoraConfig) -> Params:
    """Effective parameters ``W + (alpha/r) * A @ B`` for every target;
    non-target leaves pass through by reference (no copy). This is both
    what the train step traces AND the export path — serving/decode take
    the merged tree with zero inference-time overhead."""
    blocks = dict(base["blocks"])
    for t in lcfg.targets:
        a, b = lora["blocks"][f"{t}_a"], lora["blocks"][f"{t}_b"]
        delta = jnp.einsum(_MERGE_EINSUM[t], a, b) * lcfg.scale
        blocks[t] = blocks[t] + delta.astype(blocks[t].dtype)
    return {**base, "blocks": blocks}


def lora_param_count(lora: Params) -> int:
    return sum(x.size for x in jax.tree.leaves(lora))


def init_lora_state(
    rng: jax.Array,
    cfg: ModelConfig,
    lcfg: LoraConfig,
    mesh: Mesh,
    optimizer=None,
) -> Tuple[TrainState, Any]:
    """TrainState over the ADAPTERS only (the base model is not part of the
    optimized state — pass it to the step)."""
    _check_targets(cfg, lcfg)
    optimizer = optimizer or make_optimizer()
    shardings = _shardings(mesh, lora_param_specs(cfg, lcfg))

    @partial(jax.jit, out_shardings=shardings)
    def _init(rng):
        return init_lora_params(rng, cfg, lcfg)

    lora = _init(rng)
    opt_state = jax.jit(optimizer.init)(lora)
    return (
        TrainState(params=lora, opt_state=opt_state,
                   step=jnp.zeros((), jnp.int32)),
        optimizer,
    )


def make_lora_train_step(
    cfg: ModelConfig,
    lcfg: LoraConfig,
    mesh: Mesh,
    optimizer=None,
    attention: Optional[str] = None,
    accum_steps: int = 1,
    skip_nonfinite: bool = False,
):
    """Jitted ``(state, base_params, tokens, targets) -> (state, loss)``.

    The base is an ordinary (non-donated) argument: it stays live in HBM
    across steps, gradients flow through the merge into A/B only, and the
    optimizer updates only the adapter state (which IS donated). All of
    ``make_update_step``'s features (accumulation, non-finite skip) apply.
    """
    _check_targets(cfg, lcfg)
    optimizer = optimizer or make_optimizer()
    attn_fn = (
        _resolve_attention(mesh, attention, cfg.window) if attention else None
    )

    def loss_fn(lora, base, tokens, targets):
        merged = merge_lora(base, lora, lcfg)
        return model_lib.next_token_loss(merged, tokens, targets, cfg,
                                         attn_fn=attn_fn)

    if accum_steps > 1:
        # make_update_step's accumulation reshapes every batch arg into
        # microbatches — base_params rides in the batch position and must
        # not be; LoRA's activation memory equals the base model's anyway,
        # so shrink the batch instead.
        raise NotImplementedError(
            "accum_steps > 1 with LoRA: use a smaller batch — the adapter "
            "state is tiny, activation memory matches the base model's"
        )
    # make_update_step's contract is (params, *batch): base_params rides as
    # the first batch element (constant wrt grad, never donated/reshaped)
    inner = make_update_step(loss_fn, optimizer, skip_nonfinite=skip_nonfinite)

    bspec = NamedSharding(mesh, _filter_spec(mesh, batch_spec(mesh)))
    return jax.jit(
        inner,
        in_shardings=(None, None, bspec, bspec),
        donate_argnums=(0,),
    )
