"""HuggingFace llama-family checkpoint import.

The switching on-ramp: load a pretrained ``LlamaForCausalLM`` (or its
state_dict) into kubetpu's parameter layout and every downstream path —
sharded training, LoRA fine-tuning, decode/serving/beam/speculative —
consumes it unchanged. The conversion is pure layout: kubetpu's block
math (half-split RoPE with ``theta^(-i/(d/2))`` frequencies, f32 RMSNorm
at eps 1e-6, SiLU gate MLP, pre-norm residuals, hd^-0.5 attention scale)
is the llama recipe, so converted logits match the torch reference to
float tolerance — pinned by a cross-framework parity test.

Layout mapping (torch Linear stores (out, in); kubetpu stacks layers on a
leading L axis and keeps head structure explicit):

    embed_tokens.weight   (V, D)      -> embed            (V, D)
    q_proj.weight         (H*hd, D)   -> wq[l] = W.T reshaped (D, H, hd)
    k/v_proj.weight       (KV*hd, D)  -> wk/wv[l]          (D, KV, hd)
    o_proj.weight         (D, H*hd)   -> wo[l] = W.T reshaped (H, hd, D)
    gate/up_proj.weight   (F, D)      -> w_gate/w_up[l]    (D, F)
    down_proj.weight      (D, F)      -> w_down[l]         (F, D)
    input_layernorm       (D,)        -> ln1[l]
    post_attention_layernorm (D,)     -> ln2[l]
    model.norm.weight     (D,)        -> ln_f
    lm_head.weight        (V, D)      -> head = W.T        (D, V)
                                         (embed.T when weights are tied)
"""

from __future__ import annotations

import warnings
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubetpu.jobs.model import ModelConfig, Params


def config_from_hf(hf_config, **overrides) -> ModelConfig:
    """``ModelConfig`` from a ``transformers`` llama config.

    Checkpoint features kubetpu's block math does not reproduce are
    REFUSED, not silently dropped — a conversion that succeeds is one
    whose logits match the torch reference. Llama-3.1-style
    ``rope_scaling`` (type 'llama3') IS reproduced (translated to
    ``ModelConfig.rope_llama3_scaling``); other scaling types and
    attention/MLP biases refuse. RMSNorm eps is fixed at 1e-6 in
    kubetpu; a checkpoint trained at another eps converts with a
    warning (the delta is ~eps-level, acceptable for most uses)."""
    if getattr(hf_config, "model_type", "llama") != "llama":
        raise ValueError(
            f"unsupported model_type {hf_config.model_type!r}; the importer "
            f"maps the llama family"
        )
    scaling = getattr(hf_config, "rope_scaling", None)
    llama3 = None
    if scaling:
        rope_type = scaling.get("rope_type", scaling.get("type"))
        if rope_type == "llama3":
            # translated to the hashable ModelConfig tuple; rope() applies
            # the identical frequency warp (parity-tested against torch)
            llama3 = (
                float(scaling["factor"]),
                float(scaling["low_freq_factor"]),
                float(scaling["high_freq_factor"]),
                int(scaling["original_max_position_embeddings"]),
            )
        elif rope_type != "default":
            raise ValueError(
                f"rope_scaling type {rope_type!r} is not supported (only "
                f"'llama3' and 'default'): converting would produce "
                f"silently wrong logits"
            )
    if getattr(hf_config, "attention_bias", False) or getattr(
            hf_config, "mlp_bias", False):
        raise ValueError(
            "attention_bias/mlp_bias checkpoints are not supported: "
            "kubetpu's projections are bias-free, so the bias terms would "
            "be silently dropped"
        )
    eps = float(getattr(hf_config, "rms_norm_eps", 1e-6))
    if abs(eps - 1e-6) > 0:
        warnings.warn(
            f"checkpoint rms_norm_eps={eps:g} != kubetpu's fixed 1e-6; "
            f"converted logits will differ at the ~{eps:g} level",
            stacklevel=2,
        )
    explicit_hd = getattr(hf_config, "head_dim", None)
    derived_hd = hf_config.hidden_size // hf_config.num_attention_heads
    if explicit_hd is not None and int(explicit_hd) != derived_hd:
        # same refusal contract as rope_scaling/bias above: kubetpu derives
        # head_dim as hidden/heads, so a checkpoint with a decoupled
        # head_dim would hit a confusing reshape error deep in the mapping
        raise ValueError(
            f"head_dim={explicit_hd} != hidden_size/num_attention_heads="
            f"{derived_hd}: kubetpu's blocks derive head_dim, so this "
            f"checkpoint cannot be mapped faithfully"
        )
    kw = dict(
        vocab=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        d_ff=hf_config.intermediate_size,
        max_seq=hf_config.max_position_embeddings,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
    )
    if llama3 is not None:
        kw["rope_llama3_scaling"] = llama3
    n_kv = getattr(hf_config, "num_key_value_heads", kw["n_heads"])
    if n_kv != kw["n_heads"]:
        kw["n_kv_heads"] = n_kv
    kw.update(overrides)
    return ModelConfig(**kw)


def _np(t) -> np.ndarray:
    """torch tensor / numpy array -> float32 numpy (layout work happens in
    f32; the final cast to cfg.dtype is one place, below)."""
    if hasattr(t, "detach"):
        t = t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


def params_from_hf(
    model_or_state_dict,
    cfg: Optional[ModelConfig] = None,
    dtype: Any = None,
) -> Tuple[Params, ModelConfig]:
    """Convert a ``LlamaForCausalLM`` (or its ``state_dict()``) into
    (params, cfg). ``dtype`` overrides the parameter dtype (e.g.
    ``jnp.bfloat16`` for TPU serving); defaults to ``cfg.dtype``."""
    if hasattr(model_or_state_dict, "state_dict"):
        if cfg is None:
            cfg = config_from_hf(model_or_state_dict.config)
        sd = model_or_state_dict.state_dict()
    else:
        sd = dict(model_or_state_dict)
        if cfg is None:
            raise ValueError("pass cfg when converting a bare state_dict")
    if cfg.n_experts > 0:
        raise ValueError("the importer maps dense llama; MoE configs don't")
    dtype = dtype or cfg.dtype
    d, h, hd, kv, f = (cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.kv_heads,
                       cfg.d_ff)

    consumed = set()

    def get(name):
        key = f"model.{name}" if f"model.{name}" in sd else name
        if key not in sd:
            raise KeyError(f"checkpoint is missing {name!r}")
        consumed.add(key)
        return _np(sd[key])

    def layer(i, name):
        return get(f"layers.{i}.{name}")

    L = cfg.n_layers
    blocks: Dict[str, np.ndarray] = {
        "ln1": np.stack([layer(i, "input_layernorm.weight")
                         for i in range(L)]),
        "ln2": np.stack([layer(i, "post_attention_layernorm.weight")
                         for i in range(L)]),
        "wq": np.stack([
            layer(i, "self_attn.q_proj.weight").T.reshape(d, h, hd)
            for i in range(L)
        ]),
        "wk": np.stack([
            layer(i, "self_attn.k_proj.weight").T.reshape(d, kv, hd)
            for i in range(L)
        ]),
        "wv": np.stack([
            layer(i, "self_attn.v_proj.weight").T.reshape(d, kv, hd)
            for i in range(L)
        ]),
        "wo": np.stack([
            layer(i, "self_attn.o_proj.weight").T.reshape(h, hd, d)
            for i in range(L)
        ]),
        "w_gate": np.stack([
            layer(i, "mlp.gate_proj.weight").T for i in range(L)
        ]),
        "w_up": np.stack([
            layer(i, "mlp.up_proj.weight").T for i in range(L)
        ]),
        "w_down": np.stack([
            layer(i, "mlp.down_proj.weight").T for i in range(L)
        ]),
    }
    embed = get("embed_tokens.weight")
    if "lm_head.weight" in sd:
        head = _np(sd["lm_head.weight"]).T
    else:  # tied embeddings
        head = embed.T
    params = {
        "embed": embed,
        "blocks": blocks,
        "ln_f": get("norm.weight"),
        "head": head,
    }
    expect = {
        "embed": (cfg.vocab, d), "ln_f": (d,), "head": (d, cfg.vocab),
    }
    for k, shape in expect.items():
        if params[k].shape != shape:
            raise ValueError(
                f"{k}: checkpoint shape {params[k].shape} != config {shape} "
                f"— config/checkpoint mismatch"
            )
    consumed.add("lm_head.weight")
    # Anything left unmapped means the converted model would NOT reproduce
    # the reference (dropped bias terms, extra adapters, ...). Rotary
    # inv_freq buffers are the one benign legacy leftover.
    leftover = sorted(
        k for k in sd
        if k not in consumed and "rotary_emb.inv_freq" not in k
    )
    if leftover:
        raise ValueError(
            f"checkpoint has {len(leftover)} unmapped tensor(s) the "
            f"conversion would silently drop: {leftover[:6]}"
            f"{'...' if len(leftover) > 6 else ''}"
        )
    return jax.tree.map(lambda a: jnp.asarray(a, dtype), params), cfg
