"""Sharded training step: dp x sp x tp over a jax.sharding.Mesh.

The GSPMD path: parameters and activations carry ``NamedSharding``
annotations and XLA inserts the collectives (psum for tensor-parallel
matmuls, all-reduce for data-parallel grads) over ICI; the one manual-SPMD
region is the ring-attention core (``shard_map`` + ``ppermute``). This is
the "pick a mesh, annotate shardings, let XLA do the rest" recipe — not a
port of any NCCL pipeline.

Axes:
- ``dp``: batch (pure data parallelism, gradient all-reduce)
- ``sp``: sequence (ring attention; long-context)
- ``tp``: attention heads + MLP hidden + vocab (tensor parallelism)
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubetpu.jobs import model as model_lib
from kubetpu.jobs.model import ModelConfig, Params
from kubetpu.jobs.ring_attention import make_ring_attention


def param_specs(cfg: ModelConfig, pp: bool = False) -> Params:
    """PartitionSpec pytree matching init_params: heads/ff/vocab on tp,
    experts on ep, and (when *pp*) the stacked layer axis on pp."""
    L = "pp" if pp else None
    blocks = {
        "ln1": P(L, None),                  # (L, D)
        "ln2": P(L, None),
        "wq": P(L, None, "tp", None),       # (L, D, H, hd): heads on tp
        "wk": P(L, None, "tp", None),
        "wv": P(L, None, "tp", None),
        "wo": P(L, "tp", None, None),       # (L, H, hd, D)
    }
    if cfg.n_experts > 0:
        blocks.update(
            {
                "moe_router": P(L, None, None),      # (L, D, E)
                "w_gate": P(L, "ep", None, "tp"),    # (L, E, D, F)
                "w_up": P(L, "ep", None, "tp"),
                "w_down": P(L, "ep", "tp", None),    # (L, E, F, D)
            }
        )
    else:
        blocks.update(
            {
                "w_gate": P(L, None, "tp"),          # (L, D, F): ff on tp
                "w_up": P(L, None, "tp"),
                "w_down": P(L, "tp", None),          # (L, F, D)
            }
        )
    return {
        "embed": P(None, None),             # (V, D) replicated (small)
        "blocks": blocks,
        "ln_f": P(None),
        "head": P(None, "tp"),              # (D, V): vocab on tp
    }


def batch_spec() -> P:
    """(B, S) tokens: batch on dp, sequence on sp."""
    return P("dp", "sp")


class TrainState(NamedTuple):
    params: Params
    opt_state: Any
    step: jnp.ndarray


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.01):
    return optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=weight_decay)


def _filter_spec(mesh: Mesh, spec: P) -> P:
    """Drop axis names the mesh doesn't have (a dp x sp x tp mesh simply
    replicates the ep/pp dimensions), so one spec table serves any mesh."""
    names = set(mesh.axis_names)
    return P(*((a if a in names else None) for a in spec))


def _shardings(mesh: Mesh, tree):
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, _filter_spec(mesh, spec)),
        tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def init_state(
    rng: jax.Array, cfg: ModelConfig, mesh: Mesh, optimizer=None, pp: bool = False
) -> Tuple[TrainState, Any]:
    """Initialize params/opt state directly into their shardings (jit with
    out_shardings: no host-side full copy, params materialize sharded).
    ``pp=True`` additionally shards the stacked layer axis over the pp mesh
    axis (the pipeline path)."""
    optimizer = optimizer or make_optimizer()
    p_shardings = _shardings(mesh, param_specs(cfg, pp=pp))

    @partial(jax.jit, out_shardings=p_shardings)
    def _init(rng):
        return model_lib.init_params(rng, cfg)

    params = _init(rng)
    opt_state = jax.jit(optimizer.init)(params)
    return TrainState(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32)), optimizer


def make_update_step(loss_fn, optimizer):
    """The one train-step body (value_and_grad -> optimizer -> new state)
    shared by the causal, pipelined, masked-LM, and ViT step builders —
    a future change (grad clipping, loss scaling) lands everywhere at once.
    ``loss_fn(params, *batch) -> scalar``; returns an un-jitted step."""

    def train_step(state: TrainState, *batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, *batch)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return TrainState(new_params, new_opt, state.step + 1), loss

    return train_step


def _resolve_attention(mesh: Mesh, attention: str):
    """Pick the attention core: 'ring' (sequence-parallel over sp),
    'ring_flash' (ring with the Pallas flash kernels inside every step —
    VMEM-tiled scores, fused ring backward; append '_interpret' for the CPU
    Pallas interpreter in tests), 'flash' (the Pallas kernel —
    single-sequence-shard paths), or 'dense'."""
    if attention == "ring":
        return make_ring_attention(mesh)
    if attention in ("ring_flash", "ring_flash_interpret"):
        return make_ring_attention(
            mesh, impl="flash", interpret=attention.endswith("_interpret")
        )
    if attention == "flash":
        from kubetpu.ops import flash_attention

        return partial(flash_attention, block_q=128, block_k=128)
    if attention == "dense":
        return None
    raise ValueError(f"unknown attention {attention!r}")


def make_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer=None,
    use_ring: bool = True,
    attention: Optional[str] = None,
    jit: bool = True,
):
    """Build the jitted full training step: loss -> grads -> adamw update.

    Pass the optimizer returned by ``init_state`` — the opt_state was built
    by it, and a mismatched default here would silently apply the wrong
    hyperparameters. Donates the state buffers (in-place update on device).
    ``attention``: 'ring' (default; sequence-parallel over sp), 'flash'
    (Pallas kernel, for sp=1 meshes), or 'dense'; ``use_ring=False`` is the
    legacy spelling of 'dense'. ``jit=False`` returns the raw traced-once
    body instead, for callers that embed the step in a larger jitted
    computation (the bench harness loops it inside one ``fori_loop``).
    """
    optimizer = optimizer or make_optimizer()
    if attention is None:
        attention = "ring" if use_ring else "dense"
    attn_fn = _resolve_attention(mesh, attention)

    def loss_fn(params, tokens, targets):
        return model_lib.next_token_loss(params, tokens, targets, cfg, attn_fn)

    step = make_update_step(loss_fn, optimizer)
    if not jit:
        return step
    bspec = NamedSharding(mesh, _filter_spec(mesh, batch_spec()))
    return jax.jit(
        step,
        in_shardings=(None, bspec, bspec),  # state keeps its own shardings
        donate_argnums=(0,),
    )


def make_eval_step(cfg: ModelConfig, mesh: Mesh, use_ring: bool = True):
    attn_fn = make_ring_attention(mesh) if use_ring else None
    bspec = NamedSharding(mesh, _filter_spec(mesh, batch_spec()))

    def eval_step(params, tokens, targets):
        return model_lib.next_token_loss(params, tokens, targets, cfg, attn_fn)

    return jax.jit(eval_step, in_shardings=(None, bspec, bspec))
