"""Sharded training step: dp x sp x tp over a jax.sharding.Mesh.

The GSPMD path: parameters and activations carry ``NamedSharding``
annotations and XLA inserts the collectives (psum for tensor-parallel
matmuls, all-reduce for data-parallel grads) over ICI; the one manual-SPMD
region is the ring-attention core (``shard_map`` + ``ppermute``). This is
the "pick a mesh, annotate shardings, let XLA do the rest" recipe — not a
port of any NCCL pipeline.

Axes:
- ``dp``: batch (pure data parallelism, gradient all-reduce)
- ``sp``: sequence (ring attention; long-context)
- ``tp``: attention heads + MLP hidden + vocab (tensor parallelism)
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubetpu.jobs import model as model_lib
from kubetpu.jobs.model import ModelConfig, Params
from kubetpu.jobs.ring_attention import make_ring_attention


def param_specs(cfg: ModelConfig, pp: bool = False) -> Params:
    """PartitionSpec pytree matching init_params: heads/ff/vocab on tp,
    experts on ep, and (when *pp*) the stacked layer axis on pp."""
    L = "pp" if pp else None
    blocks = {
        "ln1": P(L, None),                  # (L, D)
        "ln2": P(L, None),
        "wq": P(L, None, "tp", None),       # (L, D, H, hd): heads on tp
        "wk": P(L, None, "tp", None),
        "wv": P(L, None, "tp", None),
        "wo": P(L, "tp", None, None),       # (L, H, hd, D)
    }
    if cfg.n_experts > 0:
        blocks.update(
            {
                "moe_router": P(L, None, None),      # (L, D, E)
                "w_gate": P(L, "ep", None, "tp"),    # (L, E, D, F)
                "w_up": P(L, "ep", None, "tp"),
                "w_down": P(L, "ep", "tp", None),    # (L, E, F, D)
            }
        )
    else:
        blocks.update(
            {
                "w_gate": P(L, None, "tp"),          # (L, D, F): ff on tp
                "w_up": P(L, None, "tp"),
                "w_down": P(L, "tp", None),          # (L, F, D)
            }
        )
    return {
        "embed": P(None, None),             # (V, D) replicated (small)
        "blocks": blocks,
        "ln_f": P(None),
        "head": P(None, "tp"),              # (D, V): vocab on tp
    }


def batch_spec(mesh: Optional[Mesh] = None) -> P:
    """(B, S) tokens: batch on the data axes, sequence on sp. On a
    multislice mesh (a ``dcn`` axis — slices joined over the data-center
    network) the batch shards over BOTH dcn and dp: params carry no dcn
    axis (replicated per slice), so the only DCN traffic XLA emits is the
    gradient all-reduce — data parallelism between slices, ICI parallelism
    within, the standard multislice recipe."""
    if mesh is not None and "dcn" in mesh.axis_names:
        return P(("dcn", "dp"), "sp")
    return P("dp", "sp")


class TrainState(NamedTuple):
    params: Params
    opt_state: Any
    step: jnp.ndarray


def make_optimizer(
    lr: float = 3e-4,
    weight_decay: float = 0.01,
    warmup_steps: int = 0,
    decay_steps: Optional[int] = None,
    min_lr_ratio: float = 0.1,
    clip_norm: Optional[float] = None,
    b1: float = 0.9,
    b2: float = 0.95,
):
    """AdamW with the standard LLM pretraining trimmings, all optional so
    the bare default stays what every existing test/checkpoint expects:
    linear warmup -> cosine decay to ``min_lr_ratio * lr`` (when
    ``decay_steps`` is given; warmup alone holds peak lr after warmup),
    and global-norm gradient clipping BEFORE the adamw update (the chain
    order that actually bounds the step)."""
    if decay_steps is not None:
        schedule = optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=lr, warmup_steps=warmup_steps,
            decay_steps=decay_steps, end_value=lr * min_lr_ratio,
        )
    elif warmup_steps:
        schedule = optax.linear_schedule(0.0, lr, warmup_steps)
    else:
        schedule = lr
    tx = optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay)
    if clip_norm is not None:
        tx = optax.chain(optax.clip_by_global_norm(clip_norm), tx)
    return tx


def _filter_spec(mesh: Mesh, spec: P) -> P:
    """Drop axis names the mesh doesn't have (a dp x sp x tp mesh simply
    replicates the ep/pp dimensions), so one spec table serves any mesh.
    Tuple entries (one array dim sharded over several mesh axes, e.g. the
    multislice batch ``("dcn", "dp")``) filter element-wise."""
    names = set(mesh.axis_names)

    def keep(a):
        if isinstance(a, (tuple, list)):
            kept = tuple(x for x in a if x in names)
            return kept if kept else None
        return a if a in names else None

    return P(*(keep(a) for a in spec))


def _shardings(mesh: Mesh, tree):
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, _filter_spec(mesh, spec)),
        tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def init_state(
    rng: jax.Array, cfg: ModelConfig, mesh: Mesh, optimizer=None, pp: bool = False
) -> Tuple[TrainState, Any]:
    """Initialize params/opt state directly into their shardings (jit with
    out_shardings: no host-side full copy, params materialize sharded).
    ``pp=True`` additionally shards the stacked layer axis over the pp mesh
    axis (the pipeline path)."""
    optimizer = optimizer or make_optimizer()
    p_shardings = _shardings(mesh, param_specs(cfg, pp=pp))

    @partial(jax.jit, out_shardings=p_shardings)
    def _init(rng):
        return model_lib.init_params(rng, cfg)

    params = _init(rng)
    opt_state = jax.jit(optimizer.init)(params)
    return TrainState(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32)), optimizer


def make_update_step(loss_fn, optimizer, accum_steps: int = 1,
                     chunk_constraint=None, skip_nonfinite: bool = False):
    """The one train-step body (value_and_grad -> optimizer -> new state)
    shared by the causal, pipelined, masked-LM, and ViT step builders —
    a future change (loss scaling, new regularizers) lands everywhere at
    once. ``loss_fn(params, *batch) -> scalar``; returns an un-jitted step.

    ``accum_steps > 1`` enables gradient accumulation: the global batch is
    split into that many equal microbatches along axis 0 and scanned, so
    activation memory scales with the MICRObatch while the update sees the
    full-batch mean gradient — numerically the same update as one big
    batch (equal-size chunks, mean of means), bought with recompute-free
    sequential passes. That identity requires the loss to be an UNWEIGHTED
    mean over examples: for a weighted mean (the masked-LM path, where
    each chunk normalizes by its own mask count) chunk-equal averaging
    biases toward sparse-mask microbatches — keep accum_steps == 1 there
    unless the batch is mask-balanced. The reshape alone does NOT keep the microbatch
    batch axis dp-sharded (GSPMD moves the sharding to the new leading
    accum axis, or drops it when indivisible — replicating microbatches
    would defeat the memory saving); ``chunk_constraint``, a callable
    applied to each reshaped batch leaf, pins it back
    (make_train_step supplies the mesh-aware constraint).

    ``skip_nonfinite`` guards multi-day runs against loss spikes and
    hardware glitches: when the loss or ANY gradient leaf is non-finite,
    params and optimizer state are left untouched (the step counter still
    advances, so checkpoints/schedules stay monotonic) and the non-finite
    loss is returned so the caller can count skips. The guard is one
    fused select per leaf — no host round-trip, no recompile."""

    def train_step(state: TrainState, *batch):
        if accum_steps <= 1:
            loss, grads = jax.value_and_grad(loss_fn)(state.params, *batch)
        else:
            b = batch[0].shape[0]
            if b % accum_steps:
                raise ValueError(
                    f"batch size {b} not divisible by accum_steps {accum_steps}"
                )
            chunks = tuple(
                x.reshape(accum_steps, b // accum_steps, *x.shape[1:])
                for x in batch
            )
            if chunk_constraint is not None:
                chunks = tuple(chunk_constraint(x) for x in chunks)
            zero_grads = jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=jnp.float32), state.params
            )

            def micro(acc, chunk):
                acc_loss, acc_grads = acc
                loss, grads = jax.value_and_grad(loss_fn)(state.params, *chunk)
                acc_grads = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32), acc_grads, grads
                )
                return (acc_loss + loss, acc_grads), None

            (loss_sum, grad_sum), _ = jax.lax.scan(
                micro, (jnp.zeros((), jnp.float32), zero_grads), chunks
            )
            loss = loss_sum / accum_steps
            grads = jax.tree_util.tree_map(
                lambda p, g: (g / accum_steps).astype(p.dtype),
                state.params, grad_sum,
            )
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        if skip_nonfinite:
            ok = jnp.isfinite(loss)
            for g in jax.tree_util.tree_leaves(grads):
                ok = ok & jnp.isfinite(g).all()
            pick = lambda new, old: jax.tree_util.tree_map(
                lambda n, o: jnp.where(ok, n, o), new, old)
            new_params = pick(new_params, state.params)
            new_opt = pick(new_opt, state.opt_state)
        return TrainState(new_params, new_opt, state.step + 1), loss

    return train_step


def _resolve_attention(mesh: Mesh, attention: str, window: int = 0,
                       block_q: int = 128, block_k: int = 128):
    """Pick the attention core: 'ring' (sequence-parallel over sp),
    'ring_flash' (ring with the Pallas flash kernels inside every step —
    VMEM-tiled scores, fused ring backward; append '_interpret' for the CPU
    Pallas interpreter in tests), 'flash' (the Pallas kernel —
    single-sequence-shard paths), or 'dense'. ``window`` (cfg.window) makes
    every core sliding-window; under the rings it selects the BANDED ring
    (window <= S/sp: one boundary ppermute replaces the full rotation —
    sequence parallelism and O(window) attention compose)."""
    if attention in ("ring", "ring_flash", "ring_flash_interpret"):
        if window > 0:
            # both ring impls share the banded core — the band is too
            # narrow for per-step flash kernels to pay for themselves
            return make_ring_attention(mesh, window=window)
        if attention == "ring":
            return make_ring_attention(mesh)
        return make_ring_attention(
            mesh, impl="flash", block_q=block_q, block_k=block_k,
            interpret=attention.endswith("_interpret")
        )
    if attention in ("flash", "flash_interpret"):
        from kubetpu.ops import flash_attention

        return partial(flash_attention, block_q=block_q, block_k=block_k,
                       interpret=attention.endswith("_interpret"),
                       window=window)
    if attention == "dense":
        if window > 0:
            # None would fall to the model default, which already honors
            # the window via default_attn_fn — being explicit here keeps
            # the resolver self-contained
            return partial(model_lib.dense_attention, causal=True,
                           window=window)
        return None
    raise ValueError(f"unknown attention {attention!r}")


def make_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer=None,
    use_ring: bool = True,
    attention: Optional[str] = None,
    jit: bool = True,
    accum_steps: int = 1,
    skip_nonfinite: bool = False,
    weighted: bool = False,
    block_q: int = 128,
    block_k: int = 128,
):
    """Build the jitted full training step: loss -> grads -> adamw update.

    Pass the optimizer returned by ``init_state`` — the opt_state was built
    by it, and a mismatched default here would silently apply the wrong
    hyperparameters. Donates the state buffers (in-place update on device).
    ``attention``: 'ring' (default; sequence-parallel over sp), 'flash'
    (Pallas kernel, for sp=1 meshes), or 'dense'; ``use_ring=False`` is the
    legacy spelling of 'dense'. ``jit=False`` returns the raw traced-once
    body instead, for callers that embed the step in a larger jitted
    computation (the bench harness loops it inside one ``fori_loop``).
    ``weighted=True`` makes the step ``(state, tokens, targets, weights)``
    with per-position loss weights — the packed-batch path (pad masking;
    note the gradient-accumulation caveat on weighted means in
    ``make_update_step``). ``block_q``/``block_k`` tune the flash kernels'
    VMEM tiles (the 'flash'/'ring_flash' cores; bench_model's flashtune
    section sweeps them on-chip).
    """
    optimizer = optimizer or make_optimizer()
    if attention is None:
        # use_ring + window composes now: the banded ring (one boundary
        # ppermute) honors both — no fallback, no warning (round 5)
        attention = "ring" if use_ring else "dense"
    attn_fn = _resolve_attention(mesh, attention, cfg.window,
                                 block_q=block_q, block_k=block_k)

    if weighted:
        def loss_fn(params, tokens, targets, weights):
            return model_lib.next_token_loss(params, tokens, targets, cfg,
                                             attn_fn, weights=weights)
    else:
        def loss_fn(params, tokens, targets):
            return model_lib.next_token_loss(params, tokens, targets, cfg,
                                             attn_fn)

    chunk_constraint = None
    if accum_steps > 1:
        batch_axes = batch_spec(mesh)[0]  # "dp" or ("dcn", "dp")

        def chunk_constraint(x):
            # (accum, micro-B, S): batch on the data axes, seq on sp
            spec = P(*([None, batch_axes, "sp"][: x.ndim]))
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, _filter_spec(mesh, spec))
            )

    step = make_update_step(loss_fn, optimizer, accum_steps=accum_steps,
                            chunk_constraint=chunk_constraint,
                            skip_nonfinite=skip_nonfinite)
    if not jit:
        return step
    bspec = NamedSharding(mesh, _filter_spec(mesh, batch_spec(mesh)))
    n_batch = 3 if weighted else 2
    return jax.jit(
        step,
        in_shardings=(None,) + (bspec,) * n_batch,  # state keeps its shardings
        donate_argnums=(0,),
    )


def make_eval_step(cfg: ModelConfig, mesh: Mesh, use_ring: bool = True):
    # same resolution as make_train_step so eval measures the TRAINING
    # objective — in particular a windowed config evaluates through the
    # banded ring, not full causal attention (review r5)
    attn_fn = _resolve_attention(mesh, "ring" if use_ring else "dense",
                                 cfg.window)
    bspec = NamedSharding(mesh, _filter_spec(mesh, batch_spec(mesh)))

    def eval_step(params, tokens, targets):
        return model_lib.next_token_loss(params, tokens, targets, cfg, attn_fn)

    return jax.jit(eval_step, in_shardings=(None, bspec, bspec))
