"""Allocation -> jax.sharding.Mesh: the bridge from kubetpu's scheduler to
a running JAX job.

The scheduler places a gang on an ICI-contiguous block of chips and the
device manager exports the libtpu env (``TPU_VISIBLE_DEVICES``, bounds,
worker id). Inside the job, this module turns that allocation into a device
mesh whose axis order respects the physical torus: the tensor-parallel axis
rides the innermost (fastest-varying, physically adjacent) chips, sequence
parallelism the next ring, data parallelism the outermost — so the
highest-bandwidth collectives map to nearest-neighbor ICI hops.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

from kubetpu.plugintypes.mesh import Coord

DEFAULT_AXES = ("dp", "sp", "tp")


def factor_axes(n_devices: int, axes: Sequence[str] = DEFAULT_AXES) -> Dict[str, int]:
    """Split n devices over the mesh axes, balanced: prime factors assigned
    round-robin starting at the innermost axis (tp gets the first factor so
    its collectives ride adjacent chips). n=8 -> dp=2, sp=2, tp=2."""
    sizes = {a: 1 for a in axes}
    factors: List[int] = []
    rest, d = n_devices, 2
    while rest > 1:
        while rest % d == 0:
            factors.append(d)
            rest //= d
        d += 1
    cycle = list(reversed(list(axes)))  # innermost first
    for i, f in enumerate(factors):
        sizes[cycle[i % len(cycle)]] *= f
    return sizes


def make_mesh(
    axis_sizes: Dict[str, int], devices: Optional[Sequence] = None
) -> Mesh:
    """Mesh over the first prod(sizes) local devices, row-major."""
    names = tuple(axis_sizes)
    shape = tuple(axis_sizes[a] for a in names)
    n = int(np.prod(shape))
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < n:
        raise ValueError(f"need {n} devices for mesh {axis_sizes}, have {len(devs)}")
    return Mesh(np.array(devs[:n]).reshape(shape), names)


def slice_groups(devices: Sequence) -> List[List]:
    """Group devices by the physical slice they belong to: by the runtime's
    ``device.slice_index`` when exposed (real TPU multislice), else one
    group (single slice / CPU). Groups are ordered by slice index, devices
    by id within each — the deterministic frame both the scheduler's
    slice-id stamps and ``make_multislice_mesh`` rely on."""
    by_slice: Dict[int, List] = {}
    for d in devices:
        by_slice.setdefault(getattr(d, "slice_index", 0) or 0, []).append(d)
    return [
        sorted(by_slice[s], key=lambda d: getattr(d, "id", 0))
        for s in sorted(by_slice)
    ]


def make_multislice_mesh(
    axis_sizes: Dict[str, int], devices: Optional[Sequence] = None
) -> Mesh:
    """Mesh whose OUTERMOST axis (``dcn``) spans physical slices: only that
    axis's collectives cross the data-center network; every inner (ICI)
    axis stays within one slice. ``axis_sizes`` must contain ``"dcn"`` —
    its size is the number of slices — plus the usual ICI axes; the mesh
    axis order is forced to dcn-first regardless of dict order, which is
    what makes the placement claim true (jax lays devices out row-major,
    so the leading axis strides across the per-slice groups).

    Devices are grouped by ``slice_index`` when the runtime exposes it
    (real multislice); a flat device list (CPU validation meshes, the
    driver's virtual-device dryrun) is split into ``dcn`` equal contiguous
    chunks — the same worker-id-major order the scheduler's sub-gangs
    export.

    An EXPLICIT device list must fit the mesh exactly: oversupply (more
    slice groups than ``dcn``, or a group larger than the inner axes)
    raises like undersupply does — a silently truncated allocation would
    leave scheduled chips idle and hide a placement bug. The default
    (process-wide ``jax.devices()``) keeps the permissive take-what-fits
    behavior."""
    if "dcn" not in axis_sizes:
        raise ValueError("make_multislice_mesh needs a 'dcn' axis (n_slices)")
    n_slices = axis_sizes["dcn"]
    inner = {a: s for a, s in axis_sizes.items() if a != "dcn"}
    per_slice = int(np.prod(list(inner.values()))) if inner else 1
    explicit = devices is not None
    devs = list(devices) if explicit else jax.devices()
    groups = slice_groups(devs)
    if len(groups) == 1 and n_slices > 1:
        # flat list: split into contiguous chunks of per_slice devices
        flat = groups[0]
        if len(flat) < n_slices * per_slice:
            raise ValueError(
                f"need {n_slices * per_slice} devices for mesh {axis_sizes}, "
                f"have {len(flat)}"
            )
        if explicit and len(flat) > n_slices * per_slice:
            raise ValueError(
                f"mesh {axis_sizes} uses {n_slices * per_slice} devices but "
                f"{len(flat)} were supplied — truncating would leave "
                f"allocated chips idle"
            )
        groups = [
            flat[i * per_slice : (i + 1) * per_slice] for i in range(n_slices)
        ]
    if len(groups) < n_slices:
        raise ValueError(
            f"mesh wants dcn={n_slices} slices but devices span only "
            f"{len(groups)}"
        )
    if explicit and len(groups) > n_slices:
        raise ValueError(
            f"mesh wants dcn={n_slices} slices but the supplied devices "
            f"span {len(groups)} — truncating would drop whole slices"
        )
    for g in groups[:n_slices]:
        if len(g) < per_slice:
            raise ValueError(
                f"slice group has {len(g)} devices, inner axes need {per_slice}"
            )
        if explicit and len(g) > per_slice:
            raise ValueError(
                f"slice group has {len(g)} devices but the inner axes use "
                f"{per_slice} — truncating would leave allocated chips idle"
            )
    arr = np.array([g[:per_slice] for g in groups[:n_slices]])
    names = ("dcn",) + tuple(inner)
    shape = (n_slices,) + tuple(inner.values())
    return Mesh(arr.reshape(shape), names)


def mesh_from_allocation(
    coords: Sequence[Coord],
    axis_sizes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh for a scheduled allocation (the coords the gang landed
    on, e.g. from ``Cluster.allocate`` + meshstate), ordering devices so that
    mesh-adjacent ranks are torus-adjacent chips: devices are laid out in
    row-major order of their sorted coordinate block, and the innermost mesh
    axis walks the innermost torus dimension."""
    n = len(coords)
    if axis_sizes is None:
        axis_sizes = factor_axes(n)
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < n:
        raise ValueError(f"allocation has {n} chips but only {len(devs)} devices visible")
    ordered = [devs[i] for i in np.lexsort(np.array([list(c) for c in coords]).T[::-1])]
    names = tuple(axis_sizes)
    shape = tuple(axis_sizes[a] for a in names)
    return Mesh(np.array(ordered).reshape(shape), names)
