"""Allocation -> jax.sharding.Mesh: the bridge from kubetpu's scheduler to
a running JAX job.

The scheduler places a gang on an ICI-contiguous block of chips and the
device manager exports the libtpu env (``TPU_VISIBLE_DEVICES``, bounds,
worker id). Inside the job, this module turns that allocation into a device
mesh whose axis order respects the physical torus: the tensor-parallel axis
rides the innermost (fastest-varying, physically adjacent) chips, sequence
parallelism the next ring, data parallelism the outermost — so the
highest-bandwidth collectives map to nearest-neighbor ICI hops.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

from kubetpu.plugintypes.mesh import Coord

DEFAULT_AXES = ("dp", "sp", "tp")


def factor_axes(n_devices: int, axes: Sequence[str] = DEFAULT_AXES) -> Dict[str, int]:
    """Split n devices over the mesh axes, balanced: prime factors assigned
    round-robin starting at the innermost axis (tp gets the first factor so
    its collectives ride adjacent chips). n=8 -> dp=2, sp=2, tp=2."""
    sizes = {a: 1 for a in axes}
    factors: List[int] = []
    rest, d = n_devices, 2
    while rest > 1:
        while rest % d == 0:
            factors.append(d)
            rest //= d
        d += 1
    cycle = list(reversed(list(axes)))  # innermost first
    for i, f in enumerate(factors):
        sizes[cycle[i % len(cycle)]] *= f
    return sizes


def make_mesh(
    axis_sizes: Dict[str, int], devices: Optional[Sequence] = None
) -> Mesh:
    """Mesh over the first prod(sizes) local devices, row-major."""
    names = tuple(axis_sizes)
    shape = tuple(axis_sizes[a] for a in names)
    n = int(np.prod(shape))
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < n:
        raise ValueError(f"need {n} devices for mesh {axis_sizes}, have {len(devs)}")
    return Mesh(np.array(devs[:n]).reshape(shape), names)


def mesh_from_allocation(
    coords: Sequence[Coord],
    axis_sizes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh for a scheduled allocation (the coords the gang landed
    on, e.g. from ``Cluster.allocate`` + meshstate), ordering devices so that
    mesh-adjacent ranks are torus-adjacent chips: devices are laid out in
    row-major order of their sorted coordinate block, and the innermost mesh
    axis walks the innermost torus dimension."""
    n = len(coords)
    if axis_sizes is None:
        axis_sizes = factor_axes(n)
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < n:
        raise ValueError(f"allocation has {n} chips but only {len(devs)} devices visible")
    ordered = [devs[i] for i in np.lexsort(np.array([list(c) for c in coords]).T[::-1])]
    names = tuple(axis_sizes)
    shape = tuple(axis_sizes[a] for a in names)
    return Mesh(np.array(ordered).reshape(shape), names)
