"""Tracing / profiling for the jobs layer (SURVEY.md §5.1: the reference
has no tracing at all; kubetpu's scheduler side has latency histograms —
this is the compute side).

- ``trace(log_dir)``: context manager around the JAX profiler — captures a
  TensorBoard/XProf-loadable device trace of whatever runs inside (train
  steps, decode rounds), the tool for finding HBM-bound ops and collective
  stalls on real TPU.
- ``StepTimer``: wall-clock step statistics (p50/p99 + tokens/sec) over the
  same ``LatencyRecorder`` the scheduler uses, for quick in-loop numbers
  without a trace viewer.
- ``marginal_ms``: the honest microbenchmark primitive for remote/tunneled
  TPU backends, where ``jax.block_until_ready`` may return before the
  device finishes (observed on the experimental ``axon`` platform: a dense
  4k attention "measured" 22x over the chip's peak FLOP rate) and every
  dispatch carries a multi-ms round trip. Runs the op N1 and N2 times
  *inside one jitted computation* with a live data dependency, forces a
  scalar host fetch (which cannot lie), and reports the marginal
  ``(t2 - t1) / (N2 - N1)`` — fixed dispatch/RTT/fetch costs cancel.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

import jax

from kubetpu.core.metrics import LatencyRecorder


def fetch_scalar(x) -> float:
    """Force a device->host transfer of a scalar — the only timing fence
    that works on backends whose block_until_ready is advisory."""
    import numpy as np

    return float(np.asarray(x))


def marginal_ms(make_run, n1: int, n2: int, reps: int = 3) -> float:
    """Marginal per-iteration milliseconds of an op, immune to dispatch
    overhead and async/non-blocking backends.

    ``make_run(n)`` must return a zero-arg callable whose call executes the
    op *n* times inside ONE jitted computation (with a data dependency
    between iterations so XLA cannot CSE or dead-code them) and returns a
    device scalar. Each variant is compiled+warmed once, then timed
    ``reps`` times around a forced scalar fetch; the best (least-noise)
    wall time per variant enters the two-point slope.
    """
    def measure(reps_now: int) -> float:
        best = {}
        for n in (n1, n2):
            run = make_run(n)
            fetch_scalar(run())  # compile + warm
            times = []
            for _ in range(reps_now):
                t0 = time.perf_counter()
                fetch_scalar(run())
                times.append(time.perf_counter() - t0)
            best[n] = min(times)
        return (best[n2] - best[n1]) / (n2 - n1) * 1e3

    ms = measure(reps)
    if ms <= 0:
        # RTT jitter swamped the slope (sub-ms op, multi-ms tunnel noise):
        # one retry with doubled reps, then clamp — a checked-in artifact
        # must never carry a negative/infinite throughput
        ms = measure(reps * 2)
        if ms <= 0:
            import sys

            print(
                f"marginal_ms: non-positive slope ({ms:.4f} ms) even at "
                f"reps={reps * 2}; clamping to 1e-3 ms — treat this "
                "measurement as noise-dominated",
                file=sys.stderr,
            )
            ms = 1e-3
    return ms


@contextmanager
def trace(log_dir: str):
    """Capture a JAX profiler trace into *log_dir* (view with TensorBoard's
    profile plugin / xprof). Wrap a handful of already-compiled steps —
    tracing compilations swamps the timeline."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Time training/decode steps and report tokens/sec.

    >>> timer = StepTimer(tokens_per_step=batch * seq)
    >>> for ... :
    ...     with timer.step():
    ...         state, loss = train_step(state, tokens, targets)
    >>> timer.summary()   # {"p50_ms": ..., "p99_ms": ..., "tokens_per_s": ...}

    The timed block must block on the result (jit is async — call
    ``jax.block_until_ready`` or read the loss) or the numbers are
    dispatch times, not step times.
    """

    def __init__(self, tokens_per_step: int = 0):
        self.tokens_per_step = tokens_per_step
        self._rec = LatencyRecorder()

    @contextmanager
    def step(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._rec.record("step", time.perf_counter() - t0)

    def summary(self) -> dict:
        stats = self._rec.summary().get("step")
        if not stats:
            return {}
        out = dict(stats)
        if self.tokens_per_step and stats.get("p50_ms"):
            out["tokens_per_s"] = round(
                self.tokens_per_step / (stats["p50_ms"] / 1e3), 1
            )
        return out
