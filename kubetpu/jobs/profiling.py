"""Tracing / profiling for the jobs layer (SURVEY.md §5.1: the reference
has no tracing at all; kubetpu's scheduler side has latency histograms —
this is the compute side).

- ``trace(log_dir)``: context manager around the JAX profiler — captures a
  TensorBoard/XProf-loadable device trace of whatever runs inside (train
  steps, decode rounds), the tool for finding HBM-bound ops and collective
  stalls on real TPU.
- ``StepTimer``: wall-clock step statistics (p50/p99 + tokens/sec) over the
  same ``LatencyRecorder`` the scheduler uses, for quick in-loop numbers
  without a trace viewer.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

import jax

from kubetpu.core.metrics import LatencyRecorder


@contextmanager
def trace(log_dir: str):
    """Capture a JAX profiler trace into *log_dir* (view with TensorBoard's
    profile plugin / xprof). Wrap a handful of already-compiled steps —
    tracing compilations swamps the timeline."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Time training/decode steps and report tokens/sec.

    >>> timer = StepTimer(tokens_per_step=batch * seq)
    >>> for ... :
    ...     with timer.step():
    ...         state, loss = train_step(state, tokens, targets)
    >>> timer.summary()   # {"p50_ms": ..., "p99_ms": ..., "tokens_per_s": ...}

    The timed block must block on the result (jit is async — call
    ``jax.block_until_ready`` or read the loss) or the numbers are
    dispatch times, not step times.
    """

    def __init__(self, tokens_per_step: int = 0):
        self.tokens_per_step = tokens_per_step
        self._rec = LatencyRecorder()

    @contextmanager
    def step(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._rec.record("step", time.perf_counter() - t0)

    def summary(self) -> dict:
        stats = self._rec.summary().get("step")
        if not stats:
            return {}
        out = dict(stats)
        if self.tokens_per_step and stats.get("p50_ms"):
            out["tokens_per_s"] = round(
                self.tokens_per_step / (stats["p50_ms"] / 1e3), 1
            )
        return out
