"""Beam search over the KV-cached decode path.

TPU-shaped beam search: beams fold into the batch axis (the cache is
(L, B*K, S_max, H_kv, D) — every matmul stays as large and batched as
plain decoding with batch B*K), the whole search is ONE ``lax.scan``
inside a single jit, and each step is two fused stages: a flattened
top-k over (K*V) continuations per example, then a parent-beam gather
that reorders the cache along the beam axis (``take_along_axis`` on a
(L, B, K, S, H, D) view — the standard seq2seq-framework cache shuffle,
static shapes throughout).

EOS semantics: a finished beam is pinned — its only continuation is EOS
at log-probability 0, so its cumulative score freezes while the search
keeps shapes static. Final ranking applies the GNMT length penalty
``((5 + len) / 6) ** alpha`` when ``length_penalty > 0`` (neutral at 0).

Reuses ``decode.prefill`` / ``decode._forward_one`` — the same chunk
forward as greedy decoding and speculative verification, so the three
paths cannot diverge.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubetpu.jobs.decode import (
    _forward_one,
    init_kv_cache,
    kv_cache_specs,
    prefill,
)
from kubetpu.jobs.model import ModelConfig, Params

NEG_INF = -1e30


def _gnmt_penalty(length: jnp.ndarray, alpha: float) -> jnp.ndarray:
    return ((5.0 + length.astype(jnp.float32)) / 6.0) ** alpha


def make_beam_search(
    cfg: ModelConfig,
    beam_size: int,
    mesh: Optional[Mesh] = None,
    length_penalty: float = 0.0,
    eos_id: Optional[int] = None,
):
    """Jitted ``beam_search(params, prompt (B, S_p), num_steps) ->
    (tokens (B, K, S_p + num_steps), scores (B, K))``, beams sorted
    best-first. ``scores`` are summed token log-probabilities
    (length-penalized iff ``length_penalty > 0``); finished beams pad
    with EOS at frozen score."""
    if beam_size < 1:
        raise ValueError(f"beam_size must be >= 1, got {beam_size}")
    k = beam_size

    def beam_search(params, prompt, num_steps: int):
        b, s_p = prompt.shape
        max_seq = s_p + num_steps
        k_cache, v_cache = init_kv_cache(cfg, b, max_seq)
        logits, k_cache, v_cache = prefill(cfg, params, prompt,
                                           k_cache, v_cache)
        # tile prompt cache/logits across beams: beam axis rides INSIDE
        # the batch axis (L, B*K, ...)
        k_cache = jnp.repeat(k_cache, k, axis=1)
        v_cache = jnp.repeat(v_cache, k, axis=1)
        logits = jnp.repeat(logits, k, axis=0)          # (B*K, V)
        if mesh is not None:
            from kubetpu.jobs.train import _filter_spec

            cspec = NamedSharding(mesh, _filter_spec(mesh, kv_cache_specs()))
            k_cache = jax.lax.with_sharding_constraint(k_cache, cspec)
            v_cache = jax.lax.with_sharding_constraint(v_cache, cspec)
        # beam 0 starts at score 0, the rest at -inf: the first flattened
        # top-k then draws K DISTINCT tokens from beam 0 (the uniform-loop
        # trick — no special first step)
        scores = jnp.tile(
            jnp.array([0.0] + [NEG_INF] * (k - 1), jnp.float32), (b, 1)
        )
        finished = jnp.zeros((b, k), bool)
        gen_len = jnp.zeros((b, k), jnp.int32)

        def step(carry, i):
            k_cache, v_cache, prev_logits, scores, finished, gen_len = carry
            logp = jax.nn.log_softmax(
                prev_logits.astype(jnp.float32), axis=-1
            ).reshape(b, k, -1)
            v = logp.shape[-1]
            if eos_id is not None:
                # pin finished beams: only continuation is EOS at logp 0
                pin = jnp.full((v,), NEG_INF).at[eos_id].set(0.0)
                logp = jnp.where(finished[:, :, None], pin[None, None], logp)
            flat = (scores[:, :, None] + logp).reshape(b, k * v)
            new_scores, idx = jax.lax.top_k(flat, k)     # (B, K)
            parent = idx // v
            token = (idx % v).astype(prompt.dtype)
            was_finished = jnp.take_along_axis(finished, parent, axis=1)
            gen_len = jnp.take_along_axis(gen_len, parent, axis=1)
            gen_len = jnp.where(was_finished, gen_len, gen_len + 1)
            if eos_id is not None:
                finished = was_finished | (token == eos_id)
            else:
                finished = was_finished
            # reorder the cache to each new beam's parent
            def reorder(cache):
                l, bk, s, h, d = cache.shape
                view = cache.reshape(l, b, k, s, h, d)
                pidx = parent[None, :, :, None, None, None]
                return jnp.take_along_axis(view, pidx, axis=2).reshape(
                    l, bk, s, h, d
                )

            k_cache, v_cache = reorder(k_cache), reorder(v_cache)
            logits, k_cache, v_cache = _forward_one(
                cfg, params, token.reshape(b * k), k_cache, v_cache, s_p + i
            )
            return (k_cache, v_cache, logits, new_scores, finished,
                    gen_len), (token, parent)

        carry = (k_cache, v_cache, logits, scores, finished, gen_len)
        (_, _, _, scores, finished, gen_len), (tokens, parents) = jax.lax.scan(
            step, carry, jnp.arange(num_steps)
        )
        # backtrack: tokens[t] were selected for the beams of step t, but
        # later steps reorder ancestry — walk parents from the last step
        def back(carry, tp):
            beam_idx = carry
            token_t, parent_t = tp
            tok = jnp.take_along_axis(token_t, beam_idx, axis=1)
            beam_idx = jnp.take_along_axis(parent_t, beam_idx, axis=1)
            return beam_idx, tok

        last_idx = jnp.tile(jnp.arange(k)[None], (b, 1))
        _, rev = jax.lax.scan(back, last_idx, (tokens, parents), reverse=True)
        seq = jnp.moveaxis(rev, 0, -1)                   # (B, K, num_steps)

        final = scores
        if length_penalty > 0:
            final = scores / _gnmt_penalty(gen_len, length_penalty)
        order = jnp.argsort(-final, axis=1)
        seq = jnp.take_along_axis(seq, order[:, :, None], axis=1)
        final = jnp.take_along_axis(final, order, axis=1)
        prompt_k = jnp.repeat(prompt[:, None], k, axis=1)
        return jnp.concatenate([prompt_k, seq], axis=-1), final

    in_shardings = None
    if mesh is not None:
        bspec = NamedSharding(
            mesh, P("dp", None) if "dp" in mesh.axis_names else P()
        )
        in_shardings = (None, bspec)
    return jax.jit(beam_search, static_argnums=(2,),
                   in_shardings=in_shardings)
