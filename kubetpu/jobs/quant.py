"""Weight-only int8 quantization for the inference path.

Decode is HBM-bound: every step streams the full parameter set through
the MXU for one token. Storing the matmul weights as int8 with a
per-output-channel float scale halves the resident weight bytes (bf16 ->
int8 + a thin scale vector), which is the difference between a model
fitting one chip or not. Crucially, dequantization happens PER LAYER
inside the scan body (and per use for the head), never as a whole-tree
copy before the loop — a whole-tree dequant would materialize a full
bf16 parameter set as loop inputs and cost MORE memory and bandwidth
than not quantizing. Inside the layer body the ``convert(int8->bf16) *
scale`` chain is a producer XLA fuses into the dot's operand read.

TPU-shaped choices:

- symmetric per-OUTPUT-CHANNEL scales (one f32 per column of each matmul
  weight): zero-points would break the MXU-friendly multiply-then-scale
  form, and per-channel granularity keeps worst-case rounding error
  ~1/127 of each channel's max — accurate enough that greedy decode on
  the test model is token-identical;
- norms, embeddings, and every 1-D tensor stay in the original dtype
  (they are bandwidth-trivial and precision-critical);
- ``QTensor`` is a registered pytree node, so quantized params flow
  through ``jax.jit``/``lax.scan`` exactly like raw arrays — the decode
  and serving code calls ``maybe_dequantize`` at the top of its jitted
  body and is otherwise unchanged.

Reference: none (the reference has no inference stack, SURVEY.md §2);
the scheme is the public weight-only-int8 recipe used across JAX LLM
serving stacks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp


@dataclass
class QTensor:
    """int8 values + broadcastable f32 scales (keepdims reduction shape)."""

    q: jnp.ndarray       # int8, same shape as the original weight
    scale: jnp.ndarray   # f32, broadcastable against q
    dtype: Any           # original dtype, restored on dequantize

    def dequantize(self) -> jnp.ndarray:
        return (self.q.astype(jnp.float32) * self.scale).astype(self.dtype)

    @property
    def nbytes(self) -> int:
        return self.q.size + self.scale.size * 4


jax.tree_util.register_pytree_node(
    QTensor,
    lambda t: ((t.q, t.scale), t.dtype),
    lambda dtype, children: QTensor(children[0], children[1], dtype),
)


def quantize_tensor(w: jnp.ndarray) -> QTensor:
    """Symmetric int8 quantization. Granularity: per output channel for
    2-D (in, out) weights; for stacked >= 3-D weights (leading layer —
    or layer+expert — axes) the scale keeps the LEADING axis and the
    LAST axis and reduces the middle, i.e. per-layer per-last-channel.
    Any broadcastable scale dequantizes exactly — granularity only sets
    the rounding error, and this uniform rule needs no per-tensor
    contraction map while keeping each layer's dynamic range separate.
    Zero channels stay exactly zero. scale = max|w| / 127."""
    axes = (0,) if w.ndim == 2 else tuple(range(1, w.ndim - 1))
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return QTensor(q.astype(jnp.int8), scale.astype(jnp.float32), w.dtype)


def quantize_params(params) -> Any:
    """Quantize every matmul-shaped (ndim >= 2) leaf EXCEPT the embedding
    table; 1-D tensors (norm gains, biases) keep their dtype. The
    embedding stays raw: it is consumed by a gather (dequantizing it
    would materialize the full table in bf16 per step) and a single
    per-column scale across the whole vocabulary is the worst possible
    granularity for it. The stacked-blocks layout quantizes fine: q and
    scale both keep the leading layer axis, so a ``lax.scan`` over the
    blocks slices QTensors per layer and the dequant happens INSIDE the
    loop body (a per-layer bf16 temporary, never a whole-tree copy)."""
    def one(path, w):
        if not hasattr(w, "ndim") or w.ndim < 2:
            return w
        keys = {getattr(k, "key", None) for k in path}
        if "embed" in keys:
            return w
        if "blocks" in keys and w.ndim < 3:
            # a 2-D leaf under the stacked blocks is a per-layer 1-D gain
            # (ln1/ln2, (L, D)) — precision-critical, and a QTensor's
            # keepdims scale would lose the leading layer axis the block
            # scan slices on
            return w
        return quantize_tensor(w)

    return jax.tree_util.tree_map_with_path(one, params)


def maybe_dequantize(params) -> Any:
    """Restore full-precision leaves inside a jitted body — a no-op for
    raw params, so decode/serving code handles both transparently. The
    dequant chain fuses into each consuming matmul's operand read."""
    return jax.tree_util.tree_map(
        lambda w: w.dequantize() if isinstance(w, QTensor) else w,
        params,
        is_leaf=lambda w: isinstance(w, QTensor),
    )


def quantize_kv_chunk(x: jnp.ndarray) -> tuple:
    """Dynamic per-token per-head int8 quantization for KV-cache entries:
    x (..., H_kv, D) -> (int8 values, f32 scale (..., H_kv, 1)). Unlike
    weights (static, per-output-channel), cache entries arrive one
    token/chunk at a time with unknown range — the max|.|/127 scale is
    computed per head per position at WRITE time, so a loud head cannot
    crush a quiet one's resolution. Zero vectors stay exactly zero."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def param_bytes(params) -> int:
    """Resident bytes of a (possibly quantized) param tree."""
    return sum(
        leaf.nbytes
        for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=lambda w: isinstance(w, QTensor)
        )
    )
