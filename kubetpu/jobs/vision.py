"""Vision Transformer — the third model family, MXU-shaped.

Patchify -> linear projection -> learned position embeddings -> the SAME
transformer blocks as the decoder/encoder (``model._block`` under a
bidirectional core) -> mean-pool -> classification head. Two TPU-first
choices:

- patchify is a reshape + one big matmul (no convolution: an (N, P*P*C) x
  (P*P*C, D) einsum feeds the MXU directly);
- rotary embeddings are neutralized by feeding position 0 everywhere
  (rope at angle 0 is the identity), so the shared block body needs no
  flag — image order comes from the learned position table, as in ViT.

Reference: the reference has no models (SURVEY.md §2) — family breadth is
a kubetpu extension.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubetpu.jobs import model as model_lib
from kubetpu.jobs.encoder import dense_bidirectional_attention
from kubetpu.jobs.model import ModelConfig, Params
from kubetpu.jobs.train import (
    TrainState,
    _filter_spec,
    make_optimizer,
    make_update_step,
    param_specs,
)


@dataclasses.dataclass(frozen=True)
class VitConfig:
    image_size: int = 32
    patch_size: int = 4
    channels: int = 3
    n_classes: int = 10
    model: ModelConfig = dataclasses.field(
        default_factory=lambda: ModelConfig(d_model=128, n_layers=4, n_heads=4,
                                            d_ff=256)
    )

    def __post_init__(self):
        if self.image_size % self.patch_size:
            raise ValueError(
                f"patch_size ({self.patch_size}) must divide "
                f"image_size ({self.image_size})"
            )

    @property
    def n_patches(self) -> int:
        side = self.image_size // self.patch_size
        return side * side

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels


def init_vit_params(rng: jax.Array, cfg: VitConfig) -> Params:
    """Blocks come from the shared init (bit-identical machinery); the
    vocab embed/head are replaced by patch projection, learned position
    table, and the classification head."""
    k_model, k_patch, k_pos, k_head = jax.random.split(rng, 4)
    base = model_lib.init_params(k_model, cfg.model)
    d = cfg.model.d_model
    dt = cfg.model.dtype
    return {
        "patch_proj": jax.random.normal(k_patch, (cfg.patch_dim, d), dt)
        * cfg.patch_dim ** -0.5,
        "pos_embed": jax.random.normal(k_pos, (cfg.n_patches, d), dt) * 0.02,
        "blocks": base["blocks"],
        "ln_f": base["ln_f"],
        "head_cls": jax.random.normal(k_head, (d, cfg.n_classes), dt) * d ** -0.5,
    }


def patchify(images: jnp.ndarray, cfg: VitConfig) -> jnp.ndarray:
    """(B, H, W, C) -> (B, N, P*P*C) by pure reshape/transpose."""
    b = images.shape[0]
    p, side = cfg.patch_size, cfg.image_size // cfg.patch_size
    x = images.reshape(b, side, p, side, p, cfg.channels)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # (B, side, side, p, p, C)
    return x.reshape(b, cfg.n_patches, cfg.patch_dim)


def vit_forward(
    params: Params,
    images: jnp.ndarray,
    cfg: VitConfig,
    attn_fn=None,
    return_aux: bool = False,
):
    """Class logits. images: (B, H, W, C) float -> (B, n_classes); with
    ``return_aux`` also the summed MoE load-balance term (mirrors
    model.forward, including remat of the scanned block)."""
    attn = attn_fn or dense_bidirectional_attention
    x = patchify(images.astype(cfg.model.dtype), cfg) @ params["patch_proj"]
    x = x + params["pos_embed"][None]
    # position 0 everywhere -> rope is the identity inside the shared block
    positions = jnp.zeros((cfg.n_patches,), jnp.int32)

    def scan_body(carry, layer):
        out, aux, _k, _v = model_lib._block_with_aux(
            cfg.model, attn, positions, carry, layer
        )
        return out, aux

    if cfg.model.remat:
        scan_body = jax.checkpoint(scan_body, policy=model_lib.remat_xla_policy(cfg.model))
    x, auxes = jax.lax.scan(scan_body, x, params["blocks"])
    x = model_lib.rms_norm(jnp.mean(x, axis=1), params["ln_f"])  # mean-pool
    logits = jnp.einsum("bd,dc->bc", x, params["head_cls"]).astype(jnp.float32)
    if return_aux:
        return logits, jnp.sum(auxes)
    return logits


def vit_loss(params, images, labels, cfg: VitConfig, attn_fn=None) -> jnp.ndarray:
    """Classification cross-entropy; MoE configs get the same load-balance
    auxiliary term as every other family."""
    mcfg = cfg.model
    if mcfg.n_experts > 0 and mcfg.moe_aux_coeff > 0:
        logits, aux = vit_forward(params, images, cfg, attn_fn=attn_fn,
                                  return_aux=True)
        extra = mcfg.moe_aux_coeff * aux
    else:
        logits = vit_forward(params, images, cfg, attn_fn=attn_fn)
        extra = 0.0
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1)) + extra


def vit_param_specs(cfg: VitConfig) -> Params:
    """Sharding: blocks reuse the shared spec tree (heads/ff on tp);
    the small ViT-specific tensors stay replicated."""
    blocks = param_specs(cfg.model)["blocks"]
    return {
        "patch_proj": P(None, None),
        "pos_embed": P(None, None),
        "blocks": blocks,
        "ln_f": P(None),
        "head_cls": P(None, None),
    }


def init_vit_state(
    rng: jax.Array, cfg: VitConfig, mesh: Mesh, optimizer=None
):
    """Sharded params + opt state (mirrors train.init_state)."""
    optimizer = optimizer or make_optimizer()
    specs = vit_param_specs(cfg)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, _filter_spec(mesh, s)), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    params = jax.jit(init_vit_params, static_argnums=(1,),
                     out_shardings=shardings)(rng, cfg)
    opt_state = jax.jit(optimizer.init)(params)
    return TrainState(params=params, opt_state=opt_state,
                      step=jnp.zeros((), jnp.int32)), optimizer


def make_vit_train_step(
    cfg: VitConfig,
    mesh: Mesh,
    optimizer=None,
    attention: str = "dense",
    interpret: bool = False,
):
    """Jitted classification train step (batch over dp; blocks tp-sharded).
    ``attention``: 'dense' or 'flash' (the Pallas kernel, causal=False)."""
    optimizer = optimizer or make_optimizer()
    if attention == "flash":
        from functools import partial

        from kubetpu.ops import flash_attention

        attn_fn = partial(flash_attention, block_q=64, block_k=64,
                          interpret=interpret, causal=False)
    elif attention == "dense":
        attn_fn = dense_bidirectional_attention
    else:
        raise ValueError(f"unknown vit attention {attention!r}")

    bspec = NamedSharding(mesh, _filter_spec(mesh, P("dp", None, None, None)))
    lspec = NamedSharding(mesh, _filter_spec(mesh, P("dp")))

    def loss_fn(params, images, labels):
        return vit_loss(params, images, labels, cfg, attn_fn=attn_fn)

    return jax.jit(
        make_update_step(loss_fn, optimizer),
        in_shardings=(None, bspec, lspec),
        donate_argnums=(0,),
    )
