"""HF ``tokenizer.json`` loader: byte-level BPE, pure Python.

Completes the HF on-ramp above the reference's end point (the reference
story stops at Allocate env injection; kubetpu's "import, train, serve"
claim needs text in / text out for imported checkpoints): a
``params_from_hf`` checkpoint plus this loader serves prompt strings
end-to-end with no Rust/tokenizers dependency at runtime.

Covers the llama-3 family layout and the GPT-2 byte-level layout:

- model ``type: "BPE"`` — vocab (token string -> id) + ranked merges;
  ``ignore_merges: true`` (llama-3 / tiktoken convention: a pretoken that
  is itself a vocab entry short-circuits the merge loop).
- byte-level alphabet: text is UTF-8 bytes mapped through the standard
  GPT-2 printable-unicode table, so every input is encodable and decode
  is exact byte reconstruction.
- pretokenizer: ``Split`` with a regex pattern (llama-3's tiktoken-style
  pattern, applied via the ``regex`` module for ``\\p{L}``-class support),
  ``ByteLevel`` (with the GPT-2 pattern when ``use_regex``), or a
  ``Sequence`` of those.
- added/special tokens: matched greedily before pretokenization, emitted
  as single ids, skippable on decode.

The encoder is exact BPE (lowest-rank merge first), memoized per
pretoken. Parity with the Rust ``tokenizers`` package is pinned by
fixture vectors and a live cross-check in ``tests/test_tokenizer.py``.
"""

from __future__ import annotations

import json
from functools import lru_cache
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

try:  # \p{L}/\p{N} classes need the `regex` module (stdlib `re` lacks them)
    import regex as _re
except ImportError:  # pragma: no cover - regex ships with transformers
    import re as _re  # type: ignore[no-redef]

# GPT-2 byte-level pretokenizer pattern (ByteLevel use_regex=true)
GPT2_PATTERN = (
    r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"
)


@lru_cache(maxsize=1)
def bytes_to_unicode() -> Dict[int, str]:
    """The GPT-2 byte <-> printable-unicode bijection: the 188 'nice'
    bytes map to themselves, the rest to 256+offset — so every byte
    sequence is a string of printable vocab characters."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


def _parse_pretokenizer(node) -> List[Tuple[str, str]]:
    """tokenizer.json pre_tokenizer -> ordered (regex, behavior) splits.
    Behaviors: ``Isolated`` (matches AND the spans between them become
    pieces) and ``Removed`` (matches are dropped, the spans between them
    become pieces). Unknown pretokenizer types or behaviors refuse
    loudly: silently skipping one would produce a tokenizer that encodes
    differently from the checkpoint's."""
    if node is None:
        return []
    t = node.get("type")
    if t == "Sequence":
        out: List[Tuple[str, str]] = []
        for sub in node["pretokenizers"]:
            out.extend(_parse_pretokenizer(sub))
        return out
    if t == "Split":
        if node.get("invert"):
            raise ValueError("Split with invert=true is not supported")
        behavior = node.get("behavior", "Isolated")
        if behavior not in ("Isolated", "Removed"):
            raise ValueError(
                f"Split behavior {behavior!r} is not supported "
                f"(Isolated, Removed)"
            )
        pat = node["pattern"]
        if "Regex" in pat:
            return [(pat["Regex"], behavior)]
        return [(_re.escape(pat["String"]), behavior)]
    if t == "ByteLevel":
        # the byte mapping itself is applied unconditionally downstream;
        # here only its optional GPT-2 regex contributes a split
        return [(GPT2_PATTERN, "Isolated")] if node.get("use_regex", True) else []
    raise ValueError(
        f"unsupported pre_tokenizer type {t!r}: loading would silently "
        f"mis-tokenize (supported: Sequence, Split, ByteLevel)"
    )


def _split_piece(piece: str, pat, behavior: str) -> Iterable[str]:
    """Apply one split to one piece, PRESERVING non-matching spans (the
    gap between matches is a piece too — dropping it would silently eat
    input text; review r5)."""
    pos = 0
    for m in pat.finditer(piece):
        if m.start() > pos:
            yield piece[pos : m.start()]
        if behavior == "Isolated" and m.group(0):
            yield m.group(0)
        pos = m.end()
    if pos < len(piece):
        yield piece[pos:]


class BpeTokenizer:
    """Byte-level BPE tokenizer loaded from an HF ``tokenizer.json``.

    ``encode(text)`` -> ids (optionally with BOS/EOS), ``decode(ids)`` ->
    text. Special tokens round-trip as literal text unless skipped.
    """

    def __init__(
        self,
        vocab: Dict[str, int],
        merges: Sequence[Tuple[str, str]],
        special_tokens: Optional[Dict[str, int]] = None,
        added_tokens: Optional[Dict[str, int]] = None,
        split_patterns: Optional[Sequence] = None,
        ignore_merges: bool = False,
        bos_token: Optional[str] = None,
        eos_token: Optional[str] = None,
    ) -> None:
        """``added_tokens`` covers EVERY added token (matched before
        pretokenization, like the Rust added-tokens trie);
        ``special_tokens`` is the subset ``decode(skip_special=True)``
        strips. ``split_patterns=None`` means "use the GPT-2 byte-level
        pattern" (constructor convenience); an EMPTY list means a real
        no-pretokenizer config — BPE over whole chunks."""
        self.vocab = dict(vocab)
        self.id_to_token = {i: t for t, i in self.vocab.items()}
        self.ranks = {tuple(m): r for r, m in enumerate(merges)}
        self.special_tokens = dict(special_tokens or {})
        self.added_tokens = dict(added_tokens or {})
        for t, i in self.special_tokens.items():
            self.added_tokens.setdefault(t, i)
        self.id_to_added = {i: t for t, i in self.added_tokens.items()}
        self._special_ids = set(self.special_tokens.values())
        self.ignore_merges = ignore_merges
        if split_patterns is None:
            split_patterns = [(GPT2_PATTERN, "Isolated")]
        self._splits = [
            (_re.compile(p), b)
            for p, b in (
                (s, "Isolated") if isinstance(s, str) else s
                for s in split_patterns
            )
        ]
        if self.added_tokens:
            # longest-first so overlapping tokens (<|eot|> vs <|eot_id|>)
            # match maximally, like the Rust added-tokens trie
            alt = "|".join(
                _re.escape(t)
                for t in sorted(self.added_tokens, key=len, reverse=True)
            )
            self._added_re = _re.compile(f"({alt})")
        else:
            self._added_re = None
        self._byte_enc = bytes_to_unicode()
        self._byte_dec = {c: b for b, c in self._byte_enc.items()}
        self._cache: Dict[str, List[int]] = {}
        self.bos_token = bos_token
        self.eos_token = eos_token
        self.bos_id = self.special_tokens.get(bos_token) if bos_token else None
        self.eos_id = self.special_tokens.get(eos_token) if eos_token else None

    # -- loading -------------------------------------------------------------

    @classmethod
    def from_file(cls, path: str) -> "BpeTokenizer":
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
        return cls.from_json(obj)

    @classmethod
    def from_json(cls, obj: dict) -> "BpeTokenizer":
        model = obj.get("model", {})
        if model.get("type") != "BPE":
            raise ValueError(
                f"unsupported model type {model.get('type')!r} (only BPE)"
            )
        if model.get("byte_fallback"):
            raise ValueError(
                "byte_fallback BPE (sentencepiece-style, llama-2) is not "
                "supported; use a byte-level checkpoint (llama-3, gpt2)"
            )
        # This loader implements BYTE-LEVEL BPE: text is byte-mapped through
        # the GPT-2 table before BPE (and inverted on decode). A layout with
        # no ByteLevel component anywhere does its BPE over raw characters —
        # loading it here would silently byte-map anyway and diverge.
        def _has_bytelevel(node) -> bool:
            if not isinstance(node, dict):
                return False
            if node.get("type") == "ByteLevel":
                return True
            return any(
                _has_bytelevel(sub) for sub in node.get("pretokenizers", [])
            )

        if not (_has_bytelevel(obj.get("pre_tokenizer"))
                or _has_bytelevel(obj.get("decoder"))):
            raise ValueError(
                "tokenizer.json has no ByteLevel pretokenizer/decoder: only "
                "byte-level BPE layouts (llama-3, gpt2) are supported"
            )
        vocab = dict(model["vocab"])
        merges = [
            tuple(m.split(" ", 1)) if isinstance(m, str) else tuple(m)
            for m in model.get("merges", [])
        ]
        added = {
            t["content"]: int(t["id"]) for t in obj.get("added_tokens", [])
        }
        specials = {
            t["content"]: int(t["id"])
            for t in obj.get("added_tokens", [])
            if t.get("special", True)
        }
        vocab.update(added)  # added tokens are addressable ids too
        bos = eos = None
        # best-effort identity from conventional names; the TemplateProcessing
        # post-processor is not interpreted (chat templates live above this
        # layer), only single BOS/EOS framing
        for name in ("<|begin_of_text|>", "<s>", "<bos>"):
            if name in specials:
                bos = name
                break
        for name in ("<|end_of_text|>", "</s>", "<eos>", "<|endoftext|>"):
            if name in specials:
                eos = name
                break
        return cls(
            vocab,
            merges,
            special_tokens=specials,
            added_tokens=added,
            split_patterns=_parse_pretokenizer(obj.get("pre_tokenizer")),
            ignore_merges=bool(model.get("ignore_merges", False)),
            bos_token=bos,
            eos_token=eos,
        )

    # -- encoding ------------------------------------------------------------

    @property
    def vocab_size(self) -> int:
        return max(max(self.vocab.values()), *([-1] + list(self.id_to_added))) + 1

    def _bpe(self, piece: str) -> List[int]:
        """Exact BPE over one byte-mapped pretoken: repeatedly merge the
        lowest-rank adjacent pair (the training order), then map symbols
        to ids."""
        hit = self._cache.get(piece)
        if hit is not None:
            return hit
        if self.ignore_merges and piece in self.vocab:
            out = [self.vocab[piece]]
            self._cache[piece] = out
            return out
        word = list(piece)
        while len(word) > 1:
            best_rank = None
            best_i = -1
            for i in range(len(word) - 1):
                r = self.ranks.get((word[i], word[i + 1]))
                if r is not None and (best_rank is None or r < best_rank):
                    best_rank, best_i = r, i
            if best_rank is None:
                break
            word[best_i : best_i + 2] = [word[best_i] + word[best_i + 1]]
        try:
            out = [self.vocab[w] for w in word]
        except KeyError as e:  # un-merged symbol outside the vocab
            raise ValueError(
                f"symbol {e.args[0]!r} is not in the vocabulary — the "
                f"checkpoint's alphabet does not cover this input"
            ) from None
        if len(self._cache) > 65536:  # bound the memo on adversarial input
            self._cache.clear()
        self._cache[piece] = out
        return out

    def _encode_chunk(self, text: str) -> List[int]:
        """Pretokenize (split patterns in sequence, gap-preserving) +
        byte-map + BPE."""
        pieces = [text]
        for pat, behavior in self._splits:
            nxt: List[str] = []
            for p in pieces:
                nxt.extend(_split_piece(p, pat, behavior))
            pieces = nxt
        out: List[int] = []
        for p in pieces:
            mapped = "".join(self._byte_enc[b] for b in p.encode("utf-8"))
            out.extend(self._bpe(mapped))
        return out

    def encode(
        self, text: str, bos: bool = False, eos: bool = False
    ) -> List[int]:
        """Text -> ids. Added/special tokens appearing literally in *text*
        are emitted as their single ids (the serving convention — prompts
        may carry template markers); ``bos``/``eos`` frame the result when
        the tokenizer knows those ids."""
        out: List[int] = []
        if bos:
            if self.bos_id is None:
                raise ValueError("tokenizer has no BOS token")
            out.append(self.bos_id)
        if self._added_re is not None:
            parts = self._added_re.split(text)
        else:
            parts = [text]
        for part in parts:
            if not part:
                continue
            aid = self.added_tokens.get(part)
            if aid is not None:
                out.append(aid)
            else:
                out.extend(self._encode_chunk(part))
        if eos:
            if self.eos_id is None:
                raise ValueError("tokenizer has no EOS token")
            out.append(self.eos_id)
        return out

    # -- decoding ------------------------------------------------------------

    def decode(self, ids: Iterable[int], skip_special: bool = False) -> str:
        """Ids -> text: byte-table inversion, exact for any encode output
        (byte-level BPE loses nothing). Unknown ids raise — silently
        dropping them would hide a vocab-size mismatch with the model."""
        buf: List[str] = []  # decoded segments
        pending: List[int] = []  # byte values awaiting utf-8 flush
        for i in ids:
            i = int(i)
            added = self.id_to_added.get(i)
            if added is not None:
                if pending:
                    buf.append(bytes(pending).decode("utf-8", errors="replace"))
                    pending = []
                # non-special added tokens always render; skip_special
                # strips only the special subset (BOS/EOS/markers)
                if not (skip_special and i in self._special_ids):
                    buf.append(added)
                continue
            tok = self.id_to_token.get(i)
            if tok is None:
                raise ValueError(f"id {i} is not in the vocabulary")
            pending.extend(self._byte_dec[c] for c in tok)
        if pending:
            buf.append(bytes(pending).decode("utf-8", errors="replace"))
        return "".join(buf)

    # -- corpus bridge -------------------------------------------------------

    @property
    def token_dtype_bytes(self) -> int:
        """Bytes per token id in ``encode_file`` output: 2 (uint16) when
        every id fits, else 4 — pass this to ``TokenFile(path,
        dtype_bytes=...)``; the reader's default of 2 would silently
        scramble a wide-vocab corpus (llama-3's 128k vocab needs 4)."""
        return 2 if self.vocab_size <= 65536 else 4

    def encode_file(
        self, text_path: str, out_path: str, doc_sep: str = "\n\n"
    ) -> int:
        """Tokenize a text file into the flat binary corpus format
        (``native_data.TokenFile``), BOS...EOS framing per document —
        the subword counterpart of ``ByteTokenizer.encode_file``. Open
        the result with ``TokenFile(out_path,
        dtype_bytes=tok.token_dtype_bytes)``."""
        import numpy as np

        from kubetpu.jobs.native_data import write_token_file

        with open(text_path, encoding="utf-8") as f:
            text = f.read()
        ids: List[int] = []
        for doc in filter(None, text.split(doc_sep)):
            ids.extend(
                self.encode(doc, bos=self.bos_id is not None,
                            eos=self.eos_id is not None)
            )
        arr = np.asarray(ids, np.int32)
        dtype = np.uint16 if self.token_dtype_bytes == 2 else np.uint32
        write_token_file(out_path, arr, dtype=dtype)
        return int(arr.size)


def load_hf_tokenizer(path_or_dir: str) -> BpeTokenizer:
    """Load ``tokenizer.json`` from a file path or a checkpoint directory
    (the layout ``params_from_hf`` converts from)."""
    import os

    path = path_or_dir
    if os.path.isdir(path):
        path = os.path.join(path, "tokenizer.json")
    return BpeTokenizer.from_file(path)
