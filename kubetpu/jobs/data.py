"""Training data pipeline: deterministic synthetic LM corpus + sharded
device prefetch.

The input pipeline produces host-side numpy batches (tokens, targets) and
``prefetch_to_mesh`` stages them onto the mesh with the training batch
sharding ((dp, sp)) one step ahead of consumption, so host tokenization and
device compute overlap — the host->HBM transfer rides the same async
dispatch XLA uses for the step itself.
"""

from __future__ import annotations

import collections
import itertools
from typing import Iterable, Iterator, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding

from kubetpu.jobs.train import _filter_spec, batch_spec

Batch = Tuple[np.ndarray, np.ndarray]  # (tokens, targets), both (B, S) int32


class SyntheticCorpus:
    """Deterministic pseudo-text: a Markov-ish integer stream with enough
    structure for a model to measurably learn (each next token depends on
    the previous one), reproducible from (vocab, seed)."""

    def __init__(self, vocab: int, seed: int = 0,
                 skew: Optional[Sequence[float]] = None):
        """``skew``: probability over the 4 successors (default uniform).
        A skewed chain (e.g. ``[0.85, 0.05, 0.05, 0.05]``) has a clearly
        learnable argmax — natural text is like this, and it is what makes
        a distilled draft's greedy agreement (speculative decoding's
        acceptance rate) meaningfully measurable on synthetic data."""
        self.vocab = vocab
        rng = np.random.RandomState(seed)
        # sparse row-stochastic transition structure: each token prefers a
        # handful of successors
        self._next = rng.randint(0, vocab, size=(vocab, 4))
        self._skew = None if skew is None else np.asarray(skew, np.float64)
        if self._skew is not None and (
            self._skew.shape != (4,) or abs(self._skew.sum() - 1.0) > 1e-9
        ):
            raise ValueError("skew must be 4 probabilities summing to 1")

    def batches(self, batch: int, seq: int, seed: int = 0) -> Iterator[Batch]:
        rng = np.random.RandomState(seed)
        while True:
            tokens = np.empty((batch, seq + 1), np.int32)
            tokens[:, 0] = rng.randint(0, self.vocab, size=batch)
            for t in range(seq):
                if self._skew is None:
                    choice = rng.randint(0, 4, size=batch)
                else:
                    choice = rng.choice(4, size=batch, p=self._skew)
                tokens[:, t + 1] = self._next[tokens[:, t], choice]
            yield tokens[:, :-1].copy(), tokens[:, 1:].copy()


def pack_documents(
    docs: Iterable,
    batch: int,
    seq: int,
    eos_id: int,
    mode: str = "stream",
    pad_id: int = 0,
    isolate_documents: bool = False,
) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Pack variable-length token documents into fixed (B, S) training
    batches — yields (tokens, targets, weights), all (B, S), weights f32.

    Real corpora are mostly SHORT documents; without packing, a seq-4096
    batch of 300-token documents wastes >90% of every MXU matmul on pad.
    Two modes, both streaming (documents are consumed lazily):

    - ``"stream"`` (GPT-style): documents are concatenated with one
      ``eos_id`` after each and the stream is chopped into (seq+1) windows
      — zero pad (weights all 1), documents may straddle window
      boundaries. Maximum efficiency; the model sees cross-document
      attention, which the EOS token delimits (the standard pretraining
      trade).
    - ``"greedy"`` (first-fit): documents never split across rows; each
      row takes documents while they fit, the tail is padded with
      ``pad_id`` and weights 0 (train with
      ``make_train_step(weighted=True)``). Documents longer than seq+1
      are split anyway (they cannot fit whole by definition).

    Isolation caveat (both packing modes): a row holding several documents
    gives the model CROSS-DOCUMENT attention (no block-diagonal mask — the
    EOS delimiter is the only separation signal, the standard pretraining
    trade), and by default the EOS -> next-document-first-token transition
    trains at weight 1. ``isolate_documents=True`` zeros the weight on
    those cross-document transitions in greedy mode, so no position's loss
    asks the model to predict an unrelated document's opening token;
    attention still crosses documents within the row.

    ``weights.mean()`` IS the packing efficiency — worth logging.
    """
    if mode not in ("stream", "greedy"):
        raise ValueError(f"mode must be 'stream' or 'greedy', got {mode!r}")
    if isolate_documents and mode != "greedy":
        # stream mode chops a continuous token stream — document boundaries
        # deliberately vanish into it, so "isolation" cannot be honored;
        # refusing beats silently ignoring the caller's request
        raise ValueError("isolate_documents requires mode='greedy'")
    window = seq + 1

    def flush(rows, bounds=None):
        tokens = np.full((batch, seq), pad_id, np.int32)
        targets = np.full((batch, seq), pad_id, np.int32)
        weights = np.zeros((batch, seq), np.float32)
        for i, row in enumerate(rows):
            m = len(row)
            if m < 2:
                continue
            arr = np.asarray(row, np.int32)
            tokens[i, : m - 1] = arr[:-1]
            targets[i, : m - 1] = arr[1:]
            weights[i, : m - 1] = 1.0
            if bounds is not None:
                # zero the cross-document transitions: position cum-1
                # trains "last token of piece k -> first token of piece
                # k+1", an unlearnable target (isolate_documents)
                cum = 0
                for plen in bounds[i][:-1]:
                    cum += plen
                    if cum - 1 < seq:
                        weights[i, cum - 1] = 0.0
        return tokens, targets, weights

    if mode == "stream":
        buf: list = []
        rows: list = []
        for doc in docs:
            buf.extend(int(t) for t in doc)
            buf.append(eos_id)
            while len(buf) >= window:
                rows.append(buf[:window])
                # stride window-1: consecutive windows share one token, so
                # every stream position is a TARGET exactly once (stride
                # window would leave each boundary token never predicted —
                # the same off-by-one the greedy oversized split guards)
                buf = buf[window - 1:]
                if len(rows) == batch:
                    yield flush(rows)
                    rows = []
        return  # tail (partial window / partial batch) is dropped

    rows = [[] for _ in range(batch)]
    bounds = [[] for _ in range(batch)]  # per-row piece lengths
    iso = bounds if isolate_documents else None
    for doc in docs:
        pieces = [list(map(int, doc)) + [eos_id]]
        if len(pieces[0]) > window:  # cannot fit whole anywhere
            flat = pieces[0]
            # stride window-1: consecutive pieces overlap by one token, so
            # every boundary token still appears as an INPUT in the next
            # piece (a stride of window would silently drop its input role
            # — each row only trains on its first m-1 positions)
            pieces = [
                flat[i: i + window]
                for i in range(0, len(flat) - 1, window - 1)
            ]
        for piece in pieces:
            placed = False
            for row, b in zip(rows, bounds):
                if len(row) + len(piece) <= window:
                    row.extend(piece)
                    b.append(len(piece))
                    placed = True
                    break
            if not placed:
                yield flush(rows, iso)
                rows = [[] for _ in range(batch)]
                bounds = [[] for _ in range(batch)]
                iso = bounds if isolate_documents else None
                rows[0].extend(piece)
                bounds[0].append(len(piece))
    if any(rows):
        yield flush(rows, iso)


def prefetch_to_mesh(
    it: Iterable, mesh: Mesh, depth: int = 2
) -> Iterator[tuple]:
    """Stage batches onto the mesh with the training sharding, *depth*
    steps ahead (double buffering by default). Batches are tuples of any
    arity with the (B, S) layout — (tokens, targets) from the plain
    corpus, (tokens, targets, weights) from ``pack_documents``."""
    sharding = NamedSharding(mesh, _filter_spec(mesh, batch_spec(mesh)))
    queue: collections.deque = collections.deque()

    def put(batch):
        queue.append(tuple(jax.device_put(x, sharding) for x in batch))

    it = iter(it)
    for batch in itertools.islice(it, depth):
        put(batch)
    for batch in it:
        ready = queue.popleft()
        put(batch)
        yield ready
    while queue:
        yield queue.popleft()


def mlm_batches(
    corpus: "SyntheticCorpus", batch: int, seq: int,
    mask_rate: float = 0.15, seed: int = 0,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """(tokens, mask_positions) batches for the encoder's masked-LM
    objective (``jobs.encoder.make_mlm_train_step``): original tokens plus
    a bool mask of the positions to corrupt/predict. Deterministic per
    seed; every row has at least one masked position (an all-unmasked row
    contributes nothing)."""
    rng = np.random.RandomState(seed + 1)
    for tokens, _targets in corpus.batches(batch, seq, seed=seed):
        mask = rng.rand(batch, seq) < mask_rate
        none = ~mask.any(axis=1)
        mask[none, rng.randint(0, seq, size=int(none.sum()))] = True
        yield tokens, mask


class ByteTokenizer:
    """Byte-level tokenizer: UTF-8 bytes are token ids 0..255, with BOS=256
    and EOS=257 (vocab 258). Zero vocabulary files, fully reversible, and
    every possible input is in-distribution — the TPU-friendly baseline
    tokenizer (fixed small vocab keeps the embedding/head matmuls modest;
    models that need subwords plug their own encode/decode in, the train
    loop only sees int32 arrays). For imported HF checkpoints use the
    real subword tokenizer: ``kubetpu.jobs.tokenizer.load_hf_tokenizer``
    (byte-level BPE from ``tokenizer.json``, same encode/decode/
    encode_file surface)."""

    BOS = 256
    EOS = 257
    vocab = 258

    def encode(self, text: str, bos: bool = True, eos: bool = True):
        ids = list(text.encode("utf-8"))
        return ([self.BOS] if bos else []) + ids + ([self.EOS] if eos else [])

    def decode(self, ids) -> str:
        data = bytes(i for i in ids if 0 <= int(i) < 256)
        return data.decode("utf-8", errors="replace")

    def encode_file(self, text_path: str, out_path: str,
                    doc_sep: str = "\n\n") -> int:
        """Tokenize a text file into a flat binary corpus for the native
        loader (``native_data.TokenFile``): documents split on *doc_sep*
        each get BOS...EOS framing. Returns the token count. The output is
        the same uint16 format ``write_token_file`` produces, so
        ``TokenFile(out_path).batches(...)`` feeds the train loop
        directly."""
        from kubetpu.jobs.native_data import write_token_file

        with open(text_path, encoding="utf-8") as f:
            text = f.read()
        ids: list = []
        for doc in filter(None, text.split(doc_sep)):
            ids.extend(self.encode(doc))
        tokens = np.asarray(ids, np.int32)
        write_token_file(out_path, tokens, dtype=np.uint16)
        return int(tokens.size)


def evaluate(eval_step, params, batches: Iterable[Batch], n_batches: int):
    """Mean validation loss + perplexity over *n_batches* from *batches*.

    *eval_step* is ``train.make_eval_step``'s jitted (params, tokens,
    targets) -> scalar loss; batches come from any corpus source
    (synthetic, TokenFile, or ``prefetch_to_mesh`` staging). Losses stay
    on device until one final fetch so evaluation pipelines like
    training does."""
    losses = []
    n_tokens = 0
    for tokens, targets in itertools.islice(iter(batches), n_batches):
        losses.append(eval_step(params, tokens, targets))
        n_tokens += int(np.prod(tokens.shape))  # shape only: no device fetch
    if not losses:
        raise ValueError("evaluate: no batches")
    mean = float(np.mean([float(l) for l in losses]))
    return {
        "loss": mean,
        "perplexity": float(np.exp(min(mean, 80.0))),
        "n_batches": len(losses),
        "n_tokens": n_tokens,
    }


class SyntheticImages:
    """Deterministic labeled images for the ViT family: each class is a
    distinct low-frequency pattern plus noise — separable enough that a
    small ViT measurably learns, reproducible from (n_classes, seed)."""

    def __init__(self, image_size: int = 16, channels: int = 3,
                 n_classes: int = 10, seed: int = 0):
        self.image_size = image_size
        self.channels = channels
        self.n_classes = n_classes
        rng = np.random.RandomState(seed)
        self._prototypes = rng.randn(
            n_classes, image_size, image_size, channels
        ).astype(np.float32)

    def batches(
        self, batch: int, seed: int = 0, noise: float = 0.3
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Endless (images (B,H,W,C) float32, labels (B,) int32)."""
        rng = np.random.RandomState(seed)
        while True:
            labels = rng.randint(0, self.n_classes, size=batch)
            images = self._prototypes[labels] + noise * rng.randn(
                batch, self.image_size, self.image_size, self.channels
            ).astype(np.float32)
            yield images, labels.astype(np.int32)
