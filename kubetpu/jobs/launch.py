"""Multi-host job launch wiring: from the scheduler's allocation to a
``jax.distributed`` process group.

The device manager injects the libtpu env contract per container
(``TPU_VISIBLE_DEVICES``, ``TPU_WORKER_ID``, bounds — SURVEY.md §5.8); this
module is the *inside-the-container* counterpart that turns a gang's
allocations into the JAX runtime configuration for a multi-host slice:
process index = worker id = host index, process count = gang size, chips
per process from the bounds, coordinator = gang rank 0. Collectives between
these processes ride ICI because the gang scheduler placed the hosts on a
contiguous host-block of one slice.

On single-host (or in tests) ``launch_config`` still produces a coherent
config; ``initialize_distributed`` is a no-op when the gang is one process.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Sequence


@dataclass(frozen=True)
class LaunchConfig:
    """Everything jax.distributed.initialize needs for one gang worker."""

    coordinator_address: str
    num_processes: int
    process_id: int
    local_device_ids: List[int]

    def initialize_kwargs(self) -> Dict[str, object]:
        return {
            "coordinator_address": self.coordinator_address,
            "num_processes": self.num_processes,
            "process_id": self.process_id,
            "local_device_ids": self.local_device_ids,
        }


def launch_config(
    env: Mapping[str, str],
    gang_hosts: Sequence[str],
    rank: Optional[int] = None,
    coordinator_port: int = 8476,
) -> LaunchConfig:
    """Build a worker's LaunchConfig from its injected container env and the
    gang's host list (ordered by gang rank — the order schedule_gang placed
    them).

    ``rank`` is the worker's position within the gang and is what
    jax.distributed requires (process_id must lie in [0, num_processes)).
    It defaults to the env's TPU_WORKER_ID, which equals the gang rank only
    when the gang spans a full slice in host order — a partial-slice gang
    (e.g. hosts {0, 2}) MUST pass the explicit rank.
    """
    if not gang_hosts:
        raise ValueError("gang_hosts must name at least the coordinator host")
    process_id = int(env.get("TPU_WORKER_ID", "0")) if rank is None else rank
    if not 0 <= process_id < len(gang_hosts):
        raise ValueError(
            f"process_id {process_id} outside [0, {len(gang_hosts)}); pass the "
            "gang rank explicitly for partial-slice gangs"
        )
    visible = env.get("TPU_VISIBLE_DEVICES", "")
    local_device_ids = [int(x) for x in visible.split(",") if x != ""]
    return LaunchConfig(
        coordinator_address=f"{gang_hosts[0]}:{coordinator_port}",
        num_processes=len(gang_hosts),
        process_id=process_id,
        local_device_ids=local_device_ids,
    )


def select_device_env(envs: Sequence[Mapping[str, str]]) -> Dict[str, str]:
    """Pick the device-bearing container env out of a pod's per-container
    allocation results — the ONE place that encodes "the container whose
    env names visible devices wins; sidecars/init containers may have
    empty allocations". Raises when no container carries a device env:
    a gang worker launched without its allocation env would silently run
    on default devices, masking the very contract breakage the launcher
    exists to certify."""
    for cand in envs:
        if cand.get("TPU_VISIBLE_DEVICES") or cand.get("NVIDIA_VISIBLE_DEVICES"):
            return dict(cand)
    raise ValueError(
        "no container env carries TPU_VISIBLE_DEVICES/NVIDIA_VISIBLE_DEVICES "
        "— the pod's allocation env is missing or the injection regressed"
    )


def gang_launch_configs(
    cluster, placed_pods, coordinator_port: int = 8476
) -> List[LaunchConfig]:
    """One LaunchConfig per gang worker, from a ``schedule_gang`` result:
    runs each pod's container allocation and assembles the process group.
    Gang rank = position in the placed list (NOT the host's worker id —
    a partial-slice gang's host indices are not contiguous)."""
    hosts = [p.node_name for p in placed_pods]
    configs: List[LaunchConfig] = []
    for rank, pod in enumerate(placed_pods):
        results = cluster.allocate(pod.name)
        env = select_device_env([cand for _, _, cand in results.values()])
        configs.append(launch_config(env, hosts, rank=rank, coordinator_port=coordinator_port))
    return configs


def initialize_distributed(config: Optional[LaunchConfig]) -> None:
    """Call jax.distributed.initialize for a multi-process gang; no-op for
    single-process jobs (the local backend already owns all chips)."""
    if config is None or config.num_processes <= 1:
        return
    import jax

    jax.distributed.initialize(**config.initialize_kwargs())


def run_gang_worker(
    config: Optional[LaunchConfig],
    platform: Optional[str] = None,
    seed: int = 0,
) -> Dict[str, object]:
    """The inside-the-container body of one gang worker: join the process
    group, then run ONE data-parallel train step over the global mesh —
    the gradient all-reduce crosses process boundaries, so a finite,
    identical loss on every worker proves the whole env contract
    (coordinator reachability, worker-id ordering, device visibility)
    end to end. Returns {"process_index", "process_count",
    "global_devices", "loss"}.

    ``platform="cpu"`` pins the CPU backend + gloo cross-process
    collectives — the CI/laptop path (a sitecustomize may have pinned a
    hardware platform at import time, so the env var alone is not enough).
    On real multi-host TPU leave it None: jax picks libtpu and the ICI
    fabric.
    """
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
        if platform == "cpu":
            # cross-process CPU collectives ride gloo over TCP; without it
            # the processes connect but psum cannot cross them
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
            if config is not None and config.local_device_ids:
                # one CPU device per allocated chip: the worker sees the
                # same local device count a real TPU worker would
                n = len(config.local_device_ids)
                try:
                    jax.config.update("jax_num_cpu_devices", n)
                except AttributeError:
                    # older jax spells this knob as an XLA flag; a gang
                    # worker is a fresh process whose backend is not
                    # initialized yet, so the env var still takes effect
                    import os

                    flags = os.environ.get("XLA_FLAGS", "")
                    if "xla_force_host_platform_device_count" not in flags:
                        os.environ["XLA_FLAGS"] = (
                            flags + " --xla_force_host_platform_device_"
                            f"count={n}").strip()
    initialize_distributed(config)

    import jax.numpy as jnp

    from kubetpu.jobs import ModelConfig, init_state, make_mesh, make_train_step
    from jax.sharding import NamedSharding, PartitionSpec as P

    world = jax.device_count()
    mesh = make_mesh({"dp": world}, devices=jax.devices())
    cfg = ModelConfig(vocab=128, d_model=64, n_layers=2, n_heads=4, d_ff=128,
                      max_seq=64)
    state, opt = init_state(jax.random.PRNGKey(seed), cfg, mesh)
    step = make_train_step(cfg, mesh, optimizer=opt, use_ring=False)

    # Each process contributes ITS OWN batch shard (seeded by rank) to the
    # global data-parallel batch — the loss below is the global mean, so
    # identical losses across workers certify the cross-process psum.
    per_proc = max(1, world // jax.process_count())
    local = jax.random.randint(
        jax.random.PRNGKey(seed + 1 + jax.process_index()),
        (per_proc, 32), 0, cfg.vocab, jnp.int32,
    )
    bspec = NamedSharding(mesh, P("dp"))  # batch on dp; mesh has no sp axis
    global_shape = (per_proc * jax.process_count(), 32)
    tokens = jax.make_array_from_process_local_data(bspec, local, global_shape)
    targets = jax.make_array_from_process_local_data(
        bspec, jnp.roll(local, -1, axis=1), global_shape
    )
    state, loss = step(state, tokens, targets)
    out = {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": world,
        "loss": float(loss),
    }
    if not jnp.isfinite(loss):  # not assert: python -O must not skip this
        raise RuntimeError(f"non-finite gang loss {loss}")
    return out
