"""Encoder-decoder (seq2seq) — the fourth model family.

Built the same way the encoder and ViT families were: the shared decoder
blocks do all the heavy lifting (``model._block_with_aux`` is the self-
attention + MLP body for BOTH stacks), and the only new math is the
cross-attention branch that lets every decoder position read the encoder's
memory. TPU-first choices:

- the encoder is ``model.forward_hidden`` under a bidirectional core (the
  Pallas ``flash_attention(causal=False)`` kernel on hardware);
- the source is encoded ONCE per generate and its per-layer cross K/V
  precomputed; the default greedy loop decodes through a self-attention
  KV cache (one T=1 block pass per layer per step), pinned exactly
  against a full-recompute reference path;
- all per-layer weights (including the cross branch) are stacked on a
  leading L axis and scanned, so compiles stay flat and remat applies
  uniformly;
- sharding reuses training's specs: cross projections shard heads on tp
  exactly like self-attention, memory shards as activations ((dp, sp)).

Reference: the reference has no models at all (SURVEY.md §2) — family
breadth is a kubetpu extension.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubetpu.jobs import model as model_lib
from kubetpu.jobs.encoder import dense_bidirectional_attention
from kubetpu.jobs.model import ModelConfig, Params
from kubetpu.jobs.train import _filter_spec, _shardings, make_optimizer, make_update_step


def init_seq2seq_params(rng: jax.Array, cfg: ModelConfig) -> Params:
    """{"encoder": blocks+embed+ln_f, "decoder": blocks(+cross)+embed+
    ln_f+head}. The encoder reuses the decoder-family init minus the LM
    head; decoder blocks gain the cross-attention branch (ln_x, wq_x,
    wk_x, wv_x, wo_x) with the same shapes/scaling as self-attention."""
    k_enc, k_dec, k_cross = jax.random.split(rng, 3)
    enc = model_lib.init_params(k_enc, cfg)
    del enc["head"]  # memory, not logits
    dec = model_lib.init_params(k_dec, cfg)

    d, h, hd, L = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.n_layers
    kv = cfg.kv_heads
    ks = jax.random.split(k_cross, 4)
    scale = d ** -0.5
    dec["blocks"].update(
        {
            "ln_x": jnp.ones((L, d), cfg.dtype),
            "wq_x": jax.random.normal(ks[0], (L, d, h, hd), cfg.dtype) * scale,
            "wk_x": jax.random.normal(ks[1], (L, d, kv, hd), cfg.dtype) * scale,
            "wv_x": jax.random.normal(ks[2], (L, d, kv, hd), cfg.dtype) * scale,
            "wo_x": jax.random.normal(ks[3], (L, h, hd, d), cfg.dtype)
            * (h * hd) ** -0.5,
        }
    )
    return {"encoder": enc, "decoder": dec}


def seq2seq_param_specs(cfg: ModelConfig) -> Params:
    """PartitionSpecs matching init_seq2seq_params — training's specs for
    both stacks, cross projections sharded like self-attention."""
    from kubetpu.jobs.train import param_specs

    enc = param_specs(cfg)
    del enc["head"]
    dec = param_specs(cfg)
    dec["blocks"] = dict(dec["blocks"])
    dec["blocks"].update(
        {
            "ln_x": P(None, None),
            "wq_x": P(None, None, "tp", None),
            "wk_x": P(None, None, "tp", None),
            "wv_x": P(None, None, "tp", None),
            "wo_x": P(None, "tp", None, None),
        }
    )
    return {"encoder": enc, "decoder": dec}


def _cross_attend(cfg: ModelConfig, h: jnp.ndarray, layer: Params,
                  mem_k: jnp.ndarray, mem_v: jnp.ndarray) -> jnp.ndarray:
    """Full-visibility attention of decoder states (B, T, D) over
    precomputed memory projections mem_k/mem_v (B, S, Hkv, hd). No rope:
    source and target positions live in different sequences (the encoder
    already position-encoded its side)."""
    q = jnp.einsum("btd,dhk->bthk", h, layer["wq_x"])
    n_rep = cfg.n_heads // cfg.kv_heads
    attn = dense_bidirectional_attention(
        q, model_lib.repeat_kv(mem_k, n_rep), model_lib.repeat_kv(mem_v, n_rep)
    )
    return jnp.einsum("bthk,hkd->btd", attn, layer["wo_x"])


def memory_projections(cfg: ModelConfig, dec_blocks: Params,
                       memory: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-layer cross K/V from the encoder memory: (L, B, S, Hkv, hd)
    pair. Computed once per (encode, generate) — the decode loop's cross
    branch is then a pure read."""
    k = jnp.einsum("bsd,ldhk->lbshk", memory, dec_blocks["wk_x"])
    v = jnp.einsum("bsd,ldhk->lbshk", memory, dec_blocks["wv_x"])
    return k, v


def encode(params: Params, src: jnp.ndarray, cfg: ModelConfig,
           attn_fn=None, return_aux: bool = False):
    """Source tokens (B, S) -> memory (B, S, D) (bidirectional stack);
    with ``return_aux`` also the encoder's summed MoE load-balance term."""
    mem, aux = model_lib.forward_hidden(
        params["encoder"], src, cfg,
        attn_fn=attn_fn or dense_bidirectional_attention,
    )
    return (mem, aux) if return_aux else mem


def decoder_forward(
    params: Params,
    tgt_in: jnp.ndarray,
    memory: jnp.ndarray,
    cfg: ModelConfig,
    attn_fn=None,
    positions: Optional[jnp.ndarray] = None,
    return_aux: bool = False,
    return_hidden: bool = False,
):
    """Teacher-forced decoder logits (B, T, V): causal self-attention over
    *tgt_in* plus cross-attention into *memory* in every block. With
    ``return_aux`` also the decoder's summed MoE load-balance term; with
    ``return_hidden`` the final-norm hidden states (B, T, D) + aux instead
    of logits (the chunked-CE tail consumes these, cfg.loss_chunk)."""
    dec = params["decoder"]
    if attn_fn is None:
        # honors cfg.window — the cached generate path bands its cache
        # read with the same window, and the two must agree
        attn_fn = model_lib.default_attn_fn(cfg)
    if positions is None:
        positions = jnp.arange(tgt_in.shape[1], dtype=jnp.int32)
    mem_k, mem_v = memory_projections(cfg, dec["blocks"], memory)

    x = dec["embed"][tgt_in]
    body = partial(model_lib._block_with_aux, cfg, attn_fn, positions)

    def scan_body(carry, layer_and_mem):
        layer, mk, mv = layer_and_mem
        # block order: self-attention -> MLP (the shared block body,
        # unchanged so all families stay on one implementation), then the
        # cross branch as its own pre-normed residual read of the memory.
        # Equivalent capacity to the classic self -> cross -> MLP order;
        # chosen so _block_with_aux is reused verbatim.
        x, aux, _k, _v = body(carry, layer)
        h = model_lib.rms_norm(x, layer["ln_x"])
        x = x + _cross_attend(cfg, h, layer, mk, mv)
        return x, aux

    if cfg.remat:
        scan_body = jax.checkpoint(
            scan_body, policy=model_lib.remat_xla_policy(cfg))
    x, auxes = jax.lax.scan(scan_body, x, (dec["blocks"], mem_k, mem_v))
    x = model_lib.rms_norm(x, dec["ln_f"])
    if return_hidden:
        return x, jnp.sum(auxes)
    logits = jnp.einsum("btd,dv->btv", x, dec["head"])
    if return_aux:
        return logits, jnp.sum(auxes)
    return logits


def seq2seq_loss(params: Params, src: jnp.ndarray, tgt_in: jnp.ndarray,
                 tgt_out: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """Mean next-token cross-entropy of tgt_out given (src, tgt_in); MoE
    configs add the load-balance aux from BOTH stacks (the same
    ``moe_aux_coeff`` contract as every other family)."""
    memory, aux_enc = encode(params, src, cfg, return_aux=True)
    x, aux_dec = decoder_forward(params, tgt_in, memory, cfg,
                                 return_hidden=True)
    loss = model_lib.lm_loss_tail(x, params["decoder"]["head"], tgt_out, cfg)
    if cfg.moe_aux_coeff > 0:
        loss = loss + cfg.moe_aux_coeff * (aux_enc + aux_dec)
    return loss


def make_seq2seq_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer=None,
    accum_steps: int = 1,
):
    """Jitted (state, src, tgt_in, tgt_out) -> (state, loss) with training's
    sharding discipline (batch on dp, sequence on sp, params per
    seq2seq_param_specs)."""
    optimizer = optimizer or make_optimizer()
    bspec = NamedSharding(mesh, _filter_spec(mesh, P("dp", "sp")))

    step = make_update_step(
        lambda p, s, ti, to: seq2seq_loss(p, s, ti, to, cfg),
        optimizer, accum_steps=accum_steps,
    )
    return jax.jit(step, donate_argnums=(0,),
                   in_shardings=(None, bspec, bspec, bspec))


def init_seq2seq_state(rng: jax.Array, cfg: ModelConfig, mesh: Mesh,
                       optimizer=None):
    """(TrainState, optimizer) with params born sharded on *mesh*."""
    from kubetpu.jobs.train import TrainState

    optimizer = optimizer or make_optimizer()
    p_shardings = _shardings(mesh, seq2seq_param_specs(cfg))

    @partial(jax.jit, out_shardings=p_shardings)
    def _init(rng):
        return init_seq2seq_params(rng, cfg)

    params = _init(rng)
    opt_state = jax.jit(optimizer.init)(params)  # inherits param shardings
    return TrainState(params=params, opt_state=opt_state,
                      step=jnp.zeros((), jnp.int32)), optimizer


def decoder_forward_chunk(cfg: ModelConfig, params: Params, tokens,
                          mem_k, mem_v, k_cache, v_cache, pos):
    """T-token chunk through the decoder's self-attention KV cache plus a
    cross-attention read of the precomputed memory projections — the
    seq2seq analog of ``decode.forward_chunk`` (same block body via
    ``decode._decode_block``, cross branch appended exactly as in
    ``decoder_forward``). tokens: (B, T); caches: (L, B, S_max, Hkv, D);
    mem_k/mem_v from ``memory_projections``."""
    from kubetpu.jobs import decode as decode_lib

    dec = params["decoder"]
    x = dec["embed"][tokens]

    def layer_body(carry, inputs):
        x = carry
        layer, mk, mv, k_l, v_l = inputs
        x, k_l, v_l = decode_lib._decode_block(cfg, layer, x, k_l, v_l, pos)
        h = model_lib.rms_norm(x, layer["ln_x"])
        x = x + _cross_attend(cfg, h, layer, mk, mv)
        return x, (k_l, v_l)

    x, (k_cache, v_cache) = jax.lax.scan(
        layer_body, x, (dec["blocks"], mem_k, mem_v, k_cache, v_cache)
    )
    x = model_lib.rms_norm(x, dec["ln_f"])
    logits = jnp.einsum("btd,dv->btv", x, dec["head"]).astype(jnp.float32)
    return logits, k_cache, v_cache


def make_seq2seq_generate(cfg: ModelConfig, bos_id: int = 1,
                          eos_id: Optional[int] = None,
                          cached: bool = True):
    """Greedy generate(params, src (B, S), num_steps) -> (B, num_steps)
    target tokens. The SOURCE is encoded once. ``cached=True`` (default)
    decodes through the self-attention KV cache with the cross K/V
    precomputed once — each step pays one T=1 block pass per layer.
    ``cached=False`` re-runs the decoder on the full prefix each step
    (O(num_steps) full passes — the correctness reference the cached path
    is pinned against in tests). With *eos_id*, sequences that emit it
    keep emitting eos_id for their remaining steps (the fixed-shape
    analog of stopping)."""
    if cached:
        return _make_cached_generate(cfg, bos_id, eos_id)

    def generate(params, src, num_steps: int):
        memory = encode(params, src, cfg)
        b = src.shape[0]
        out = jnp.full((b, num_steps + 1), bos_id, jnp.int32)
        done0 = jnp.zeros((b,), bool)

        def step(i, carry):
            out, done = carry
            logits = decoder_forward(params, out[:, : num_steps + 1], memory, cfg)
            nxt = jnp.argmax(logits, axis=-1)  # (B, T)
            pick = jnp.take_along_axis(nxt, i[None, None].astype(jnp.int32),
                                       axis=1)[:, 0]
            if eos_id is not None:
                pick = jnp.where(done, eos_id, pick)
                done = done | (pick == eos_id)
            out = jax.lax.dynamic_update_slice(
                out, pick[:, None].astype(jnp.int32), (0, i + 1))
            return out, done

        out, _ = jax.lax.fori_loop(0, num_steps, step, (out, done0))
        return out[:, 1:]

    return jax.jit(generate, static_argnums=(2,))


def _make_cached_generate(cfg: ModelConfig, bos_id: int,
                          eos_id: Optional[int]):
    from kubetpu.jobs import decode as decode_lib

    def generate(params, src, num_steps: int):
        memory = encode(params, src, cfg)
        mem_k, mem_v = memory_projections(cfg, params["decoder"]["blocks"],
                                          memory)
        b = src.shape[0]
        k_cache, v_cache = decode_lib.init_kv_cache(cfg, b, num_steps + 1)
        last = jnp.full((b,), bos_id, jnp.int32)
        done0 = jnp.zeros((b,), bool)

        def step(carry, i):
            last, k_cache, v_cache, done = carry
            logits, k_cache, v_cache = decoder_forward_chunk(
                cfg, params, last[:, None], mem_k, mem_v, k_cache, v_cache, i
            )
            nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            if eos_id is not None:
                nxt = jnp.where(done, eos_id, nxt)
                done = done | (nxt == eos_id)
            return (nxt, k_cache, v_cache, done), nxt

        _, outs = jax.lax.scan(
            step, (last, k_cache, v_cache, done0),
            jnp.arange(num_steps, dtype=jnp.int32),
        )
        return outs.T  # (num_steps, B) -> (B, num_steps)

    return jax.jit(generate, static_argnums=(2,))
