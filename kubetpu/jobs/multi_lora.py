"""Multi-LoRA serving: N adapters over ONE base model in ONE batch.

The S-LoRA pattern, TPU-shaped: the base matmuls stay batched across every
slot (one weight stream from HBM per step regardless of tenant mix) while
each slot's rank-r delta is a pair of skinny per-example einsums against a
STACKED adapter tree (leaves (N_adapters, L, ...)) gathered by a per-slot
adapter-id array — retargeting a slot swaps an integer, never weights, so
one compiled step serves every tenant mix. Adapter weights live in HBM
once; for the 0.75B flagship at rank 8 an adapter is ~0.1% of the base, so
hundreds fit where a second model replica would not.

Prefill runs through the same chunk path (``decode.forward_chunk`` with
the stack), so the prompt pass applies the adapter too: the greedy output
of every slot EXACTLY equals single-request decoding of
``lora.merge_lora(base, adapter_i)`` — pinned by test. The device legs are
``DecodeServer``'s own (its jitted prefill/step already thread the
(lora, adapter) pair); this class only supplies them.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubetpu.jobs.lora import _MLP_TARGETS, LoraConfig
from kubetpu.jobs.model import ModelConfig, Params
from kubetpu.jobs.serving import DecodeServer

# the targets _decode_block can apply per-example
_DECODE_TARGETS = ("wq", "wk", "wv", "wo")


def stack_adapters(lcfg: LoraConfig, adapters: Sequence[Params]) -> Params:
    """Stack per-adapter LoRA trees (``lora.init_lora_params`` layout) into
    one tree with a leading adapter axis: leaves (N, L, ...). Validation
    runs over the adapters' ACTUAL block keys (not ``lcfg.targets``): a
    stacked target the decode path cannot apply would silently break the
    merged-parity contract. Decode-path multi-LoRA supports the attention
    targets only (the MLP branch lives in the shared ``model._mlp``, which
    has no per-example plumbing)."""
    if not adapters:
        raise ValueError("need at least one adapter")
    keys = sorted(adapters[0]["blocks"])
    targets = {k.rsplit("_", 1)[0] for k in keys}
    bad = sorted(targets - set(_DECODE_TARGETS))
    if bad:
        hint = (
            "cannot be applied per-example in the decode path"
            if set(bad) & set(_MLP_TARGETS)
            else "is not a LoRA attention target"
        )
        raise ValueError(
            f"multi-LoRA serving supports attention targets only; {bad} {hint}"
        )
    for a in adapters[1:]:
        if sorted(a["blocks"]) != keys:
            raise ValueError("adapters disagree on targets")
    return {
        "blocks": {
            k: jnp.stack([a["blocks"][k] for a in adapters]) for k in keys
        }
    }


class MultiLoraDecodeServer(DecodeServer):
    """``DecodeServer`` where every request picks an adapter from a shared
    stack: ``submit(prompt, adapter=i)`` / ``enqueue(prompt, adapter=i)``
    (default adapter 0). The per-slot adapter ids are a traced array of
    the compiled step — admission writes an integer, never a recompile."""

    def __init__(self, cfg: ModelConfig, params: Params, lcfg: LoraConfig,
                 lora_stack: Params, **kw) -> None:
        self.n_adapters = next(iter(lora_stack["blocks"].values())).shape[0]
        self._lora_scale = lcfg.scale  # read by the base legs at build time
        self.lora_stack = lora_stack
        self._rid_adapter: dict = {}
        self._submit_adapter: Optional[int] = None
        # before super().__init__: the _admit_lora/_step_lora hooks it may
        # exercise during construction read this array (ADVICE r4). n_slots
        # rides kw (this signature has no positional for it).
        from kubetpu.jobs.serving import DEFAULT_N_SLOTS

        self._slot_adapter = np.zeros(
            (kw.get("n_slots", DEFAULT_N_SLOTS),), np.int32
        )
        super().__init__(cfg, params, **kw)
        assert self._slot_adapter.shape == (self.n_slots,)

    # -- request surface ------------------------------------------------------

    def _check_adapter(self, adapter: int) -> int:
        if not 0 <= adapter < self.n_adapters:
            raise ValueError(
                f"adapter {adapter} out of range [0, {self.n_adapters})"
            )
        return int(adapter)

    def submit(self, prompt: List[int], sampling: Optional[dict] = None,
               adapter: int = 0) -> Optional[int]:
        self._submit_adapter = self._check_adapter(adapter)
        try:
            return super().submit(prompt, sampling)
        finally:
            self._submit_adapter = None

    def enqueue(self, prompt: List[int], sampling: Optional[dict] = None,
                adapter: int = 0) -> int:
        aid = self._check_adapter(adapter)  # validate BEFORE any bookkeeping
        rid = super().enqueue(prompt, sampling)
        self._rid_adapter[rid] = aid
        return rid

    def _bind_slot(self, rid: int, slot: int) -> None:
        # the shared binding hook runs on BOTH admission paths (monolithic
        # _try_admit and the chunked-prefill _begin_prefill), so a chunked
        # multi-LoRA prefill applies the right adapter from chunk one
        if rid not in self._rid_adapter:  # submit path: rid is brand new
            self._rid_adapter[rid] = (
                0 if self._submit_adapter is None else self._submit_adapter
            )
        self._slot_adapter[slot] = self._rid_adapter[rid]
        self._invalidate_dev("adapter")
        super()._bind_slot(rid, slot)

    def cancel(self, rid: int) -> bool:
        out = super().cancel(rid)
        if out:
            self._rid_adapter.pop(rid, None)
        return out

    def pop_result(self, rid: int):
        out = super().pop_result(rid)  # raises for unfinished rids FIRST
        self._rid_adapter.pop(rid, None)
        return out

    # -- the lora hooks the base legs consume ---------------------------------

    def _admit_lora(self, slot: int):
        return self.lora_stack, jnp.int32(self._slot_adapter[slot])

    def _step_lora(self):
        return self.lora_stack, self._dev(
            "adapter", lambda: self._slot_adapter)
