"""Multi-LoRA serving: N adapters over ONE base model in ONE batch.

The S-LoRA pattern, TPU-shaped: the base matmuls stay batched across every
slot (one weight stream from HBM per step regardless of tenant mix) while
each slot's rank-r delta is a pair of skinny per-example einsums against a
STACKED adapter tree (leaves (N_adapters, L, ...)) gathered by a per-slot
adapter-id array — retargeting a slot swaps an integer, never weights, so
one compiled step serves every tenant mix. Adapter weights live in HBM
once; for the 0.75B flagship at rank 8 an adapter is ~0.1% of the base, so
hundreds fit where a second model replica would not.

Prefill runs through the same chunk path (``decode.forward_chunk`` with
the stack), so the prompt pass applies the adapter too: the greedy output
of every slot EXACTLY equals single-request decoding of
``lora.merge_lora(base, adapter_i)`` — pinned by test.

Round-22 extends the pattern to the PRODUCTION paged stack:
``PagedMultiLoraDecodeServer`` threads the per-slot adapter ids through
the page-pool legs (``paged.paged_forward_one/_chunk`` — the deltas wrap
AROUND the attention core, so the fused Pallas kernel is untouched) and
``SpecMultiLoraDecodeServer`` through the speculative verify chunk, so
chunked prefill, kv_int8 pools, prefix-cache hits and draft+verify rounds
all serve every tenant mix greedy-token-exact vs the merged single-tenant
decode. Two multi-tenant-specific rules ride along:

- PREFIX ISOLATION: the radix tree's keys are SALTED with the slot's
  adapter id AND that index's eviction generation (``_prefix_tokens``:
  token -> (gen * capacity + aid + 1) << 32 | token, length-preserving
  so all page math is unchanged) at every tree touchpoint — match,
  publish, host-tier fill. Adapter A's cached KV can never warm-start
  adapter B (their K/V differ under different wk/wv deltas), and a
  tenant hot-loaded into a RECYCLED index can never warm-start from
  the evicted occupant's pages (the generation bumps on evict); the
  isolation tests pin both via hit counters. Cross-replica peer fetch
  degrades to a miss against unsalted peers — colder, never wrong.
- HOT LOAD/EVICT: the stack is a fixed-capacity device tree (capacity
  from ``max_adapters`` / the ``adapter_hbm_bytes`` budget — a shape
  change would recompile the legs); ``load_adapter`` writes a new
  adapter's factors into a free or LRU-evicted index (content-hashed
  identity — a replayed load is a no-op), ``evict_adapter`` refuses
  while any live request references the index, and requests resolve
  adapters BY NAME at enqueue, so an evicted name can never silently
  serve a stale or recycled index. ``load_info`` advertises the resident
  set for tenant-affine routing.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from kubetpu.jobs.lora import _MLP_TARGETS, LoraConfig
from kubetpu.jobs.model import ModelConfig, Params
from kubetpu.jobs.paged import PagedDecodeServer
from kubetpu.jobs.serving import DEFAULT_N_SLOTS, DecodeServer
from kubetpu.jobs.spec_serving import PagedSpeculativeDecodeServer

# the targets _decode_block can apply per-example
_DECODE_TARGETS = ("wq", "wk", "wv", "wo")

# bounded tenant-series cardinality: the first K distinct adapters get
# their own {adapter=} label; the rest aggregate under the overflow bucket
_TENANT_TOPK = 32
_TENANT_OVERFLOW = "_overflow"

_TENANT_METRICS = {
    "req": "kubetpu_tenant_requests_total",
    "tok": "kubetpu_tenant_decode_tokens_total",
    "saved": "kubetpu_tenant_prefill_tokens_saved_total",
}


def stack_adapters(lcfg: LoraConfig, adapters: Sequence[Params]) -> Params:
    """Stack per-adapter LoRA trees (``lora.init_lora_params`` layout) into
    one tree with a leading adapter axis: leaves (N, L, ...). Validation
    runs over the adapters' ACTUAL block keys (not ``lcfg.targets``): a
    stacked target the decode path cannot apply would silently break the
    merged-parity contract. Decode-path multi-LoRA supports the attention
    targets only (the MLP branch lives in the shared ``model._mlp``, which
    has no per-example plumbing)."""
    if not adapters:
        raise ValueError("need at least one adapter")
    keys = sorted(adapters[0]["blocks"])
    targets = {k.rsplit("_", 1)[0] for k in keys}
    bad = sorted(targets - set(_DECODE_TARGETS))
    if bad:
        hint = (
            "cannot be applied per-example in the decode path"
            if set(bad) & set(_MLP_TARGETS)
            else "is not a LoRA attention target"
        )
        raise ValueError(
            f"multi-LoRA serving supports attention targets only; {bad} {hint}"
        )
    for a in adapters[1:]:
        if sorted(a["blocks"]) != keys:
            raise ValueError("adapters disagree on targets")
    return {
        "blocks": {
            k: jnp.stack([a["blocks"][k] for a in adapters]) for k in keys
        }
    }


def adapter_fingerprint(adapter: Params) -> str:
    """Content hash of one adapter tree (``init_lora_params`` layout) —
    the registry/hot-load identity: two byte-identical adapters hash the
    same wherever they were trained, so a replayed or re-routed load is
    recognized as already-resident instead of double-loading."""
    h = hashlib.sha256()
    for k in sorted(adapter["blocks"]):
        arr = np.asarray(adapter["blocks"][k])
        h.update(k.encode())
        h.update(repr((arr.shape, str(arr.dtype))).encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:16]


class _MultiLoraHostMixin:
    """The HOST half of multi-tenant serving, shared by every cache
    layout: per-request adapter plumbing (submit/enqueue -> ``_rid_adapter``
    -> ``_bind_slot`` -> the per-slot id array the compiled legs trace),
    the ``_admit_lora``/``_step_lora`` hooks the device legs consume, and
    the bounded-cardinality per-tenant observability series. Subclasses
    call ``_init_multi_lora`` BEFORE their ``super().__init__`` (the base
    constructor may exercise the lora hooks) and ``_init_tenant_obs``
    after it (the obs registry exists then)."""

    def _init_multi_lora(self, lcfg: LoraConfig, lora_stack: Params,
                         n_slots: int) -> None:
        self.n_adapters = next(iter(lora_stack["blocks"].values())).shape[0]
        self._lora_scale = lcfg.scale  # read by the base legs at build time
        self.lora_stack = lora_stack
        self._rid_adapter: dict = {}
        self._submit_adapter: Optional[int] = None
        self._slot_adapter = np.zeros((n_slots,), np.int32)

    def _init_tenant_obs(self) -> None:
        # {kind: {label: counter}} — one series per top-K adapter plus
        # the shared overflow bucket, so a thousand-tenant fleet cannot
        # blow up the scrape with unbounded label cardinality
        self._tenant_counters: Dict[str, dict] = {k: {}
                                                  for k in _TENANT_METRICS}

    # -- request surface ------------------------------------------------------

    def _check_adapter(self, adapter) -> int:
        if not isinstance(adapter, (int, np.integer)):
            raise ValueError(f"adapter must be an index, got {adapter!r}")
        if not 0 <= adapter < self.n_adapters:
            raise ValueError(
                f"adapter {adapter} out of range [0, {self.n_adapters})"
            )
        return int(adapter)

    def _adapter_label(self, aid: int) -> str:
        return str(int(aid))

    def _tenant_counter(self, kind: str, aid: int):
        cache = self._tenant_counters[kind]
        label = self._adapter_label(aid)
        if label not in cache and len(cache) >= _TENANT_TOPK:
            label = _TENANT_OVERFLOW
        if label not in cache:
            # facade over the literal _TENANT_METRICS table above — the
            # names ARE auditable there # ktlint: disable=KTP004
            cache[label] = self.obs.counter(_TENANT_METRICS[kind],
                                            adapter=label)
        return cache[label]

    def submit(self, prompt: List[int], sampling: Optional[dict] = None,
               adapter=0) -> Optional[int]:
        aid = self._check_adapter(adapter)
        self._submit_adapter = aid
        try:
            rid = super().submit(prompt, sampling)
        finally:
            self._submit_adapter = None
        if rid is not None:
            self._tenant_counter("req", aid).inc()
        return rid

    def enqueue(self, prompt: List[int], sampling: Optional[dict] = None,
                adapter=0, ttl: Optional[float] = None) -> int:
        aid = self._check_adapter(adapter)  # validate BEFORE any bookkeeping
        rid = super().enqueue(prompt, sampling, ttl=ttl)
        self._rid_adapter[rid] = aid
        self._tenant_counter("req", aid).inc()
        return rid

    def _bind_slot(self, rid: int, slot: int) -> None:
        # the shared binding hook runs on BOTH admission paths (monolithic
        # _try_admit and the chunked-prefill _begin_prefill), so a chunked
        # multi-LoRA prefill applies the right adapter from chunk one
        if rid not in self._rid_adapter:  # submit path: rid is brand new
            self._rid_adapter[rid] = (
                0 if self._submit_adapter is None else self._submit_adapter
            )
        self._slot_adapter[slot] = self._rid_adapter[rid]
        self._invalidate_dev("adapter")
        super()._bind_slot(rid, slot)

    def _drop_request_state(self, rid: int) -> None:
        # THE adapter-map reclamation point: the base class calls this
        # from pop_result, cancel AND the queue-TTL expiry, so an entry
        # can no longer outlive its request on the paths that never reach
        # pop_result (the Round-22 leak fix; also what makes the paged
        # server's in-use eviction guard sound — a dead rid cannot pin an
        # adapter index forever)
        self._rid_adapter.pop(rid, None)
        super()._drop_request_state(rid)

    def _note_emitted(self, slot: int) -> None:
        super()._note_emitted(slot)
        self._tenant_counter("tok", int(self._slot_adapter[slot])).inc()

    def adapters_in_use(self) -> set:
        """Adapter indices referenced by any live (queued, active, or
        finished-but-unpopped) request — the eviction guard's read."""
        return set(int(a) for a in self._rid_adapter.values())

    # -- the lora hooks the base legs consume ---------------------------------

    def _admit_lora(self, slot: int):
        return self.lora_stack, jnp.int32(self._slot_adapter[slot])

    def _step_lora(self):
        return self.lora_stack, self._dev(
            "adapter", lambda: self._slot_adapter)


class MultiLoraDecodeServer(_MultiLoraHostMixin, DecodeServer):
    """``DecodeServer`` where every request picks an adapter from a shared
    stack: ``submit(prompt, adapter=i)`` / ``enqueue(prompt, adapter=i)``
    (default adapter 0). The per-slot adapter ids are a traced array of
    the compiled step — admission writes an integer, never a recompile."""

    def __init__(self, cfg: ModelConfig, params: Params, lcfg: LoraConfig,
                 lora_stack: Params, **kw) -> None:
        # before super().__init__: the _admit_lora/_step_lora hooks it may
        # exercise during construction read this state (ADVICE r4). n_slots
        # rides kw (this signature has no positional for it).
        self._init_multi_lora(lcfg, lora_stack,
                              kw.get("n_slots", DEFAULT_N_SLOTS))
        super().__init__(cfg, params, **kw)
        assert self._slot_adapter.shape == (self.n_slots,)
        self._init_tenant_obs()


class _PagedMultiLoraMixin(_MultiLoraHostMixin):
    """The PAGED half of multi-tenant serving, shared by the plain paged
    and the speculative multi-LoRA servers: the fixed-capacity hot-load/
    evict adapter directory, adapter-salted prefix-tree keys, per-tenant
    prefill-savings attribution, and the router-facing residency
    advertisement. Subclasses call ``_init_paged_lora`` BEFORE their
    ``super().__init__``."""

    def _init_paged_lora(self, lcfg: LoraConfig, adapters: Sequence[Params],
                         n_slots: int, max_adapters: Optional[int],
                         adapter_hbm_bytes: int) -> None:
        stack = stack_adapters(lcfg, adapters)
        n = len(adapters)
        self._adapter_bytes_each = (
            sum(np.asarray(v).nbytes for v in jax.tree.leaves(stack)) // n)
        cap = int(max_adapters) if max_adapters else n
        if adapter_hbm_bytes > 0:
            by_budget = max(1, int(adapter_hbm_bytes
                                   // max(self._adapter_bytes_each, 1)))
            cap = min(cap, by_budget) if max_adapters else by_budget
        if cap < n:
            raise ValueError(
                f"adapter capacity {cap} (max_adapters/adapter_hbm_bytes) "
                f"cannot hold the {n} initial adapters")
        if cap > n:
            # pad to capacity with zero factors (B == 0 -> zero delta ->
            # the base model): capacity is a SHAPE of the compiled legs,
            # so it is fixed here once — hot-load writes into an index,
            # never reshapes
            stack = {"blocks": {
                k: jnp.concatenate(
                    [v, jnp.zeros((cap - n,) + v.shape[1:], v.dtype)])
                for k, v in stack["blocks"].items()
            }}
        self._init_multi_lora(lcfg, stack, n_slots)
        self._adapter_names: List[Optional[str]] = [None] * cap
        self._resident: Dict[str, int] = {}
        self._adapter_lru = [0] * cap
        # per-index generation, bumped on evict: prefix keys are salted
        # with (gen, index), so a tenant hot-loaded into a RECYCLED index
        # can never warm-start from the previous occupant's cached pages
        self._adapter_gen = [0] * cap
        self._lru_tick = 0
        for i, a in enumerate(adapters):
            name = adapter_fingerprint(a)
            self._adapter_names[i] = name
            self._resident[name] = i

    def _init_adapter_obs(self) -> None:
        self._init_tenant_obs()
        self.obs.gauge_fn("kubetpu_adapters_resident",
                          lambda: len(self._resident))
        self.obs.gauge_fn("kubetpu_adapter_capacity",
                          lambda: self.n_adapters)
        self.obs.gauge_fn("kubetpu_adapter_stack_bytes",
                          lambda: self._adapter_bytes_each * self.n_adapters)
        self._c_adapter_loads = self.obs.counter(
            "kubetpu_adapter_loads_total",
            "adapters hot-loaded into the device stack (replayed loads "
            "of a resident adapter are NOT counted — idempotent)")
        self._c_adapter_evicts = self.obs.counter(
            "kubetpu_adapter_evicts_total",
            "adapters evicted from the device stack (explicit + LRU)")

    # -- adapter directory: hot load / evict ----------------------------------

    def _check_adapter(self, adapter) -> int:
        if isinstance(adapter, str):
            idx = self._resident.get(adapter)
            if idx is None:
                raise ValueError(f"adapter {adapter!r} is not resident")
            return idx
        idx = super()._check_adapter(adapter)
        if self._adapter_names[idx] is None:
            raise ValueError(
                f"adapter index {idx} is empty (never loaded, or evicted)")
        return idx

    def _adapter_label(self, aid: int) -> str:
        name = self._adapter_names[int(aid)]
        return name if name is not None else str(int(aid))

    def _touch_adapter(self, idx: int) -> None:
        self._lru_tick += 1
        self._adapter_lru[idx] = self._lru_tick

    def _bind_slot(self, rid: int, slot: int) -> None:
        super()._bind_slot(rid, slot)
        self._touch_adapter(int(self._slot_adapter[slot]))

    def load_adapter(self, adapter: Params,
                     name: Optional[str] = None) -> str:
        """Hot-load one adapter tree into the device stack and return its
        name (default: the content fingerprint — the wire identity).
        IDEMPOTENT: loading a resident name is a no-op returning the same
        name, so a replayed wire request can never double-load. Under a
        full stack the least-recently-BOUND adapter not referenced by any
        live request is evicted to make room; with every index in use the
        load refuses (RuntimeError — the wire layer's retryable 503).
        A BARRIER-class leg (one host->device factor upload), never
        called from inside ``step()``."""
        name = name or adapter_fingerprint(adapter)
        if name in self._resident:
            self._touch_adapter(self._resident[name])
            return name
        keys = sorted(self.lora_stack["blocks"])
        if sorted(adapter["blocks"]) != keys:
            raise ValueError(
                f"adapter targets {sorted(adapter['blocks'])} do not match "
                f"the stack's {keys}")
        for k in keys:
            want = self.lora_stack["blocks"][k].shape[1:]
            got = np.asarray(adapter["blocks"][k]).shape
            if got != want:
                raise ValueError(
                    f"adapter leaf {k!r} shape {got} != stack's {want}")
        idx = self._free_adapter_index()
        for k in keys:
            self.lora_stack["blocks"][k] = (
                self.lora_stack["blocks"][k]
                .at[idx].set(jnp.asarray(adapter["blocks"][k])))
        self._adapter_names[idx] = name
        self._resident[name] = idx
        self._touch_adapter(idx)
        self._c_adapter_loads.inc()
        self.events.emit("adapter_load", name=name, index=idx,
                         resident=len(self._resident))
        return name

    def _free_adapter_index(self) -> int:
        for i, nm in enumerate(self._adapter_names):
            if nm is None:
                return i
        in_use = self.adapters_in_use()
        in_use.update(int(self._slot_adapter[s])
                      for s in range(self.n_slots) if self.active[s])
        evictable = [i for i, nm in enumerate(self._adapter_names)
                     if nm is not None and i not in in_use]
        if not evictable:
            raise RuntimeError(
                "adapter stack full and every index is referenced by a "
                "live request — retry after requests drain")
        victim = min(evictable, key=lambda i: self._adapter_lru[i])
        self._evict_index(victim, reason="lru")
        return victim

    def _evict_index(self, idx: int, reason: str) -> None:
        name = self._adapter_names[idx]
        self._adapter_names[idx] = None
        self._resident.pop(name, None)
        # retire every prefix key this index ever published: the next
        # occupant salts under gen+1, so the old tenant's cached pages
        # are unreachable (they age out of the tree via its own LRU)
        self._adapter_gen[idx] += 1
        self._c_adapter_evicts.inc()
        self.events.emit("adapter_evict", name=name, index=idx,
                         reason=reason)

    def evict_adapter(self, name: str) -> bool:
        """Evict *name* from the directory (the factors stay in HBM until
        the index is reused — unreachable, since requests resolve names
        through the directory at enqueue). False when not resident (a
        replayed evict is a no-op); RuntimeError while any live request
        references the index (the wire layer's 409 — eviction must never
        yank an adapter out from under an admitted stream)."""
        idx = self._resident.get(name)
        if idx is None:
            return False
        in_use = self.adapters_in_use()
        in_use.update(int(self._slot_adapter[s])
                      for s in range(self.n_slots) if self.active[s])
        if idx in in_use:
            raise RuntimeError(
                f"adapter {name!r} is referenced by a live request")
        self._evict_index(idx, reason="explicit")
        return True

    def resident_adapters(self) -> List[str]:
        """Names of the adapters currently loaded — what ``load_info``
        advertises for tenant-affine routing."""
        return sorted(self._resident)

    def load_info(self) -> dict:
        info = super().load_info()
        info["resident_adapters"] = self.resident_adapters()
        info["adapter_capacity"] = self.n_adapters
        return info

    def check_invariants(self) -> None:
        """Pool oracle + the adapter-directory oracle: every resident
        name owns exactly one stack index, every named index is
        resident, and no live slot points at an unnamed (evicted)
        index — a replayed load that double-occupied the stack, or an
        evict that yanked an admitted stream, fails here."""
        super().check_invariants()
        named = {i for i, n in enumerate(self._adapter_names)
                 if n is not None}
        assert len(self._resident) == len(named), (
            f"directory skew: {len(self._resident)} resident names over "
            f"{len(named)} named indices")
        for name, idx in self._resident.items():
            assert self._adapter_names[idx] == name, (
                f"adapter {name!r} maps to index {idx} which is named "
                f"{self._adapter_names[idx]!r}")
        assert len(set(self._resident.values())) == len(self._resident), (
            "two resident names share a stack index")
        for s in range(self.n_slots):
            if self.active[s]:
                aid = int(self._slot_adapter[s])
                assert 0 <= aid < self.n_adapters
                assert self._adapter_names[aid] is not None, (
                    f"live slot {s} decodes under evicted index {aid}")

    # -- adapter-keyed prefix isolation ---------------------------------------

    def _prefix_tokens(self, prompt: List[int], slot: int) -> List[int]:
        """Salt the prompt with the slot's (generation, adapter id) for
        every prefix-tree touchpoint. Length-preserving (page math
        unchanged); aid+1 keeps even adapter 0 disjoint from any
        unsalted key a peer replica might ship, and the eviction
        generation keeps a RECYCLED index disjoint from its previous
        occupant's keys (gen 0 reduces to the plain aid+1 salt)."""
        aid = int(self._slot_adapter[slot])
        salt = (self._adapter_gen[aid] * self.n_adapters + aid + 1) << 32
        return [salt | (int(t) & 0xFFFFFFFF) for t in prompt]

    def _prefill_start(self, prompt: List[int], slot: int) -> int:
        # match (and host-tier fill) under the ADAPTER-SALTED key: a hit
        # can only map pages whose KV was computed under this adapter's
        # wk/wv deltas — adapter A never warm-starts adapter B
        return super()._prefill_start(self._prefix_tokens(prompt, slot),
                                      slot)

    def _note_admitted(self, slot: int, prompt: List[int]) -> None:
        pending = self._slot_pending_stats[slot]
        super()._note_admitted(slot, prompt)
        # publication key: the tree must file this slot's pages under the
        # adapter that computed them
        self._slot_prompt[slot] = self._prefix_tokens(prompt, slot)
        if pending is not None and pending[1] > 0:
            self._tenant_counter(
                "saved", int(self._slot_adapter[slot])).inc(pending[1])

    # -- live migration -------------------------------------------------------

    def snapshot_slot(self, rid: int, from_page: int = 0,
                      allow_frozen: bool = False) -> dict:
        # the snapshot carries no adapter identity and the target's
        # directory may not hold this tenant — a resumed stream decoding
        # under the WRONG adapter would be a silent cross-tenant leak.
        # The wire layer treats NotImplementedError as a per-stream skip
        # (wait-drain), same as the dense servers.
        raise NotImplementedError(
            "multi-LoRA slots do not migrate — the snapshot carries no "
            "adapter identity; drain instead")

    def restore_slot(self, snap: dict, reason: str = "migrate"):
        # symmetric refusal: an inbound snapshot has no adapter identity,
        # and the landing slot's stale ``_slot_adapter`` entry would
        # silently retarget the stream
        raise NotImplementedError(
            "multi-LoRA replicas do not accept migrated slots — the "
            "snapshot carries no adapter identity")


class PagedMultiLoraDecodeServer(_PagedMultiLoraMixin, PagedDecodeServer):
    """``PagedDecodeServer`` serving N tenants from one packed replica:
    ``submit/enqueue(prompt, adapter=i_or_name)`` picks from the stacked
    device tree; the per-slot ids ride the Round-10 ``_dev`` upload cache
    into the paged legs, so one compiled step (per bucket) serves every
    tenant mix — chunked prefill, kv_int8, prefix hits and the fused
    kernel included, greedy-token-exact vs ``merge_lora`` single-tenant
    decode (pinned by test). See ``_PagedMultiLoraMixin`` for hot-load/
    evict and the adapter-salted prefix-tree rule."""

    def __init__(self, cfg: ModelConfig, params: Params, lcfg: LoraConfig,
                 adapters: Sequence[Params],
                 max_adapters: Optional[int] = None,
                 adapter_hbm_bytes: int = 0, **kw) -> None:
        self._init_paged_lora(lcfg, adapters,
                              kw.get("n_slots", DEFAULT_N_SLOTS),
                              max_adapters, adapter_hbm_bytes)
        super().__init__(cfg, params, **kw)
        assert self._slot_adapter.shape == (self.n_slots,)
        self._init_adapter_obs()


class SpecMultiLoraDecodeServer(_PagedMultiLoraMixin,
                                PagedSpeculativeDecodeServer):
    """Speculative draft+verify rounds over the packed multi-LoRA pool:
    the TARGET's verify chunk applies each slot's adapter (the compiled
    round traces the same (stack, ids) pair as the one-token step), the
    draft stays adapterless — base-model drafts can only lower acceptance,
    never change output, because verification is greedy-exact per tenant.
    Output is token-identical to ``PagedMultiLoraDecodeServer``'s greedy
    stream (pinned by test)."""

    def __init__(self, target_cfg: ModelConfig, draft_cfg: ModelConfig,
                 target_params: Params, draft_params: Params,
                 lcfg: LoraConfig, adapters: Sequence[Params],
                 max_adapters: Optional[int] = None,
                 adapter_hbm_bytes: int = 0, **kw) -> None:
        self._init_paged_lora(lcfg, adapters,
                              kw.get("n_slots", DEFAULT_N_SLOTS),
                              max_adapters, adapter_hbm_bytes)
        super().__init__(target_cfg, draft_cfg, target_params, draft_params,
                         **kw)
        assert self._slot_adapter.shape == (self.n_slots,)
        self._init_adapter_obs()
