"""``RouterServer`` — the prefix-affinity data plane in front of N
serving replicas.

ROADMAP's millions-of-users story: every ``PagedDecodeServer`` replica
has a Round-9 radix tree, but without a router each replica gets
per-replica cache luck and load that ignores capacity. This server
makes cluster-wide decisions per request:

1. **affinity routing**: the tokenized prefix HEAD (first
   ``head_tokens`` ids) consistent-hashes onto the replica ring
   (``hashring``), so requests sharing a system prompt / few-shot
   preamble land where that prefix's KV pages are already warm —
   cluster-wide hit rate instead of luck. ``policy="random"`` is the
   seeded baseline the router storm benches against;
2. **load fallback**: the affinity target is skipped when its last
   ``/load`` snapshot reads overloaded (queue depth at/over
   ``overload_queue_depth``, or paged free pages under
   ``min_free_pages``) — the walk continues down the key's
   deterministic preference order, ending at the least-queued routable
   replica when everyone is busy. Snapshots come from the pool's
   throttled concurrent refresh, never a per-request scrape;
3. **SLO-class admission**: with objectives declared
   (``obs.slo.router_slos`` over the router's FEDERATED /metrics —
   worst-replica percentiles, exactly what the controller does), a
   burning fast window sheds ``shed_classes`` requests (503, counted)
   and parks ``queue_classes`` requests (bounded wait for the burn to
   clear, then 503) while interactive traffic keeps flowing.

Surfaces::

    POST   /generate         {"prompt": [ids], "slo_class"?, "sampling"?,
                              "timeout"?} -> routed reply + "replica"
    POST   /replicas         {"url": ...} -> register (idempotent by URL)
    DELETE /replicas/<name>  forget a replica (drain first — see
                              ``ReplicaAutoscaler`` for the safe order)
    GET    /replicas         pool listing (state, draining, last load)
    GET    /healthz /metrics /slo /events /trace/<id>

``/metrics`` federates every replica's exposition under
``replica="<name>"``; ``/trace/<id>`` stitches the router span with the
replica legs, so one generate renders router -> replica -> serving in
``kubetpu.cli.obs --trace``.

Robustness is the uniform Round-7 contract: the router -> replica leg is
a keyed ``request_json`` POST (retries can never double-admit — the
replica replays its committed tokens), the router's own ``/generate``
honors client ``Idempotency-Key`` headers through the same
``run_idempotent`` dance, and ``faults=`` injects chaos on the router
surface itself. The router holds NO model state — it can restart (or
run replicated) with zero warmup; two routers agree on every routing
key because the ring is seedless ``hashlib``.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import uuid
from collections import Counter, OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from kubetpu.api import utils
from kubetpu.core.metrics import LatencyRecorder
from kubetpu.obs import trace as obs_trace
from kubetpu.obs.events import EventLog
from kubetpu.obs.registry import Registry, install_process_gauges
from kubetpu.obs.slo import Objective, SloEngine
from kubetpu.router.hashring import DEFAULT_HEAD_QUANTUM, \
    DEFAULT_HEAD_TOKENS, HashRing, prefix_head_key
from kubetpu.router.pool import (HEALTHY, SUSPECT, ReplicaPool,
                                 role_compatible)
from kubetpu.wire.httpcommon import (
    IdempotencyCache,
    InflightTracker,
    TRANSIENT_ERRORS,
    check_bearer,
    handle_guarded,
    request_json,
    run_idempotent,
    serve_events_jsonl,
    write_json,
    write_text,
)

DEFAULT_ROUTE_TIMEOUT = 30.0


class RouterServer:
    """Prefix-affinity request router + replica pool owner."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        token: "str | None" = None,
        faults=None,
        policy: str = "affinity",
        head_tokens: int = DEFAULT_HEAD_TOKENS,
        head_quantum: int = DEFAULT_HEAD_QUANTUM,
        vnodes: int = 64,
        overload_queue_depth: int = 4,
        min_free_pages: int = 0,
        load_refresh_s: float = 0.25,
        slos: Optional[List[Objective]] = None,
        slo_interval_s: float = 0.5,
        shed_classes: Tuple[str, ...] = ("batch",),
        queue_classes: Tuple[str, ...] = ("standard",),
        queue_timeout_s: float = 2.0,
        tenant_slo_classes: Optional[Dict[str, str]] = None,
        adapters=None,
        idem_window: float = 300.0,
        suspect_after: int = 2,
        dead_after: int = 5,
        probation_passes: int = 2,
        seed: int = 0,
        **engine_kw,
    ) -> None:
        if policy not in ("affinity", "random"):
            raise ValueError("policy must be 'affinity' or 'random'")
        self.policy = policy
        self.token = token or None
        self.faults = faults
        self.head_tokens = int(head_tokens)
        self.head_quantum = int(head_quantum)
        self.overload_queue_depth = int(overload_queue_depth)
        self.min_free_pages = int(min_free_pages)
        self.load_refresh_s = float(load_refresh_s)
        self.shed_classes = tuple(shed_classes)
        self.queue_classes = tuple(queue_classes)
        self.queue_timeout_s = float(queue_timeout_s)
        # Round-22 multi-tenant control plane: per-tenant SLO classes
        # (adapter name -> class; a request naming an adapter but no
        # slo_class inherits its tenant's) and an optional
        # AdapterRegistry (the content-hashed source of truth behind
        # POST /adapters distribution)
        self.tenant_slo_classes = dict(tenant_slo_classes or {})
        self.adapters = adapters
        self.obs_component = "router"
        self.registry = Registry()
        install_process_gauges(self.registry, "router")
        self.events = EventLog(component="router")
        self.pool = ReplicaPool(
            token=token, suspect_after=suspect_after, dead_after=dead_after,
            probation_passes=probation_passes, registry=self.registry,
            events=self.events)
        self.ring = HashRing(vnodes=vnodes)
        self.idem = IdempotencyCache(ttl=idem_window)
        self._inflight = InflightTracker()
        self._lock = threading.Lock()       # ring membership + throttles
        self._rng = random.Random(seed)     # the "random" baseline policy
        self._last_slo_eval = 0.0
        self._metrics = LatencyRecorder(
            registry=self.registry, metric="kubetpu_router_latency_seconds")
        self._c_routed = self.registry.counter(
            "kubetpu_router_requests_total", outcome="routed")
        self._c_shed = self.registry.counter(
            "kubetpu_router_requests_total", outcome="shed")
        self._c_qtimeout = self.registry.counter(
            "kubetpu_router_requests_total", outcome="queue_timeout")
        self._c_norep = self.registry.counter(
            "kubetpu_router_requests_total", outcome="no_replica")
        self._c_uperr = self.registry.counter(
            "kubetpu_router_requests_total", outcome="upstream_error")
        self._c_fallback = self.registry.counter(
            "kubetpu_router_fallback_total",
            "requests whose affinity target was skipped for load/health")
        self._c_queued = self.registry.counter(
            "kubetpu_router_queued_total",
            "requests parked by SLO-class admission while burning")
        self._c_tenant_affine = self.registry.counter(
            "kubetpu_router_tenant_affine_total",
            "routing decisions narrowed to replicas advertising the "
            "request's adapter resident")
        # -- live migration (Round-16): the mid-stream rid -> replica
        # RE-PIN map. A source replica answering 409-migrated names the
        # new owner; the pin (keyed by the request's downstream
        # idempotency key, epoch-fenced so a stale notice can't repoint
        # a later handoff) makes this attempt — and any client retry of
        # the same logical request — land on the new owner instead of
        # re-running affinity against a replica that no longer holds
        # the stream.
        self._pins: "OrderedDict[str, Tuple[Optional[str], int]]" = \
            OrderedDict()
        self._suspect_handled: set = set()
        self._decode_rr = 0          # round-robin decode-target ties
        # recent decode-target handouts: (monotonic ts, name). The
        # /load snapshots are throttled, so a burst of admissions
        # inside one refresh window would all read the same "emptiest"
        # node — the router charges its own recent assignments on top
        # of the stale snapshot until the next scrape can see them.
        self._recent_decode: "deque" = deque()
        self._c_repin = self.registry.counter(
            "kubetpu_router_repins_total",
            "mid-stream rid->replica re-pins after a 409-migrated "
            "answer")
        self._c_migrate_away = self.registry.counter(
            "kubetpu_router_migrate_away_total",
            "breaker-suspect migrate-away sweeps requested")
        self._c_restart_unpins = self.registry.counter(
            "kubetpu_router_restart_unpins_total",
            "mid-stream pins dropped because their owner replica came "
            "back with a new boot nonce")
        # Round-20: a replica that returns with a NEW boot nonce was
        # hard-killed — its slot table, KV pages, and stream epochs are
        # gone, so any pin naming it points at state that no longer
        # exists. Drop those pins so the keyed client retries re-enter
        # the normal route path and land on a survivor (or the fresh
        # boot); the idempotency key plus epoch fencing make the
        # re-drive safe to replay.
        self.pool.on_restart(self._on_replica_restart)
        self.registry.gauge_fn("kubetpu_router_burning",
                               lambda: 1.0 if self._burning() else 0.0)
        # SLO engine over the FEDERATED scrape (worst replica judged) —
        # evaluated on the background signals loop (throttled to
        # slo_interval_s) and per autoscaler pass; handlers only read
        self.slo: Optional[SloEngine] = (
            SloEngine(slos, registry=self.registry, **engine_kw)
            if slos else None)
        self._slo_interval = float(slo_interval_s)
        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 (stdlib name)
                utils.logf(5, "router: " + fmt, *args)

            def _authorized(self) -> bool:
                if check_bearer(self.headers, router.token):
                    return True
                write_json(self, 401,
                           {"error": "missing or invalid bearer token"})
                return False

            def _body(self) -> dict:
                length = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(length) or b"{}")

            def do_GET(self):  # noqa: N802
                handle_guarded(router, self, self._do_get)

            def _do_get(self):
                path = self.path.split("?")[0]
                if path == "/healthz":
                    write_json(self, 200, {
                        "ok": True,
                        "component": "router",
                        "replicas": len(router.pool.names()),
                    })
                elif not self._authorized():
                    pass  # 401 already sent
                elif path == "/metrics":
                    write_text(self, 200, router.metrics_text())
                elif path == "/slo":
                    write_json(self, 200, {
                        "results": (router.slo.results()
                                    if router.slo is not None else {}),
                        "burning": router._burning(),
                    })
                elif path == "/events":
                    serve_events_jsonl(self, router.events.to_jsonl)
                elif path == "/replicas":
                    write_json(self, 200,
                               {"replicas": router.pool.to_json()})
                elif path == "/adapters":
                    write_json(self, 200, router.adapter_summary())
                elif path.startswith("/trace/"):
                    write_json(self, 200,
                               router.trace(path[len("/trace/"):]))
                else:
                    write_json(self, 404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802
                handle_guarded(router, self, self._do_post)

            def _do_post(self):
                if not self._authorized():
                    return
                if self.path == "/replicas":
                    try:
                        req = self._body()
                        name = router.register_replica(
                            req["url"], name=req.get("name"),
                            role=req.get("role"))
                        write_json(self, 200, {"replica": name})
                    except ValueError as e:
                        # name conflict: the caller's mistake, not an
                        # unreachable replica — 409, never a silent swap
                        write_json(self, 409, {"error": str(e)})
                    except Exception as e:  # noqa: BLE001 — report
                        write_json(self, 502,
                                   {"error": f"registration failed: {e}"})
                    return
                if self.path == "/adapters":
                    try:
                        req = self._body()
                    except ValueError:
                        write_json(self, 400,
                                   {"error": "body is not JSON"})
                        return
                    write_json(self, *router._adapters_post(req))
                    return
                if self.path != "/generate":
                    write_json(self, 404, {"error": f"no route {self.path}"})
                    return
                try:
                    req = self._body()
                except ValueError:
                    write_json(self, 400, {"error": "body is not JSON"})
                    return
                key = self.headers.get("Idempotency-Key")
                run_idempotent(
                    self, router.idem, key,
                    lambda: router._route_request(req, client_key=key),
                )

            def do_DELETE(self):  # noqa: N802
                handle_guarded(router, self, self._do_delete)

            def _do_delete(self):
                if not self._authorized():
                    return
                if self.path.startswith("/replicas/"):
                    name = self.path[len("/replicas/"):]
                    if router.remove_replica(name):
                        write_json(self, 200, {"removed": name})
                    else:
                        write_json(self, 404,
                                   {"error": f"no replica {name!r}"})
                else:
                    write_json(self, 404, {"error": f"no route {self.path}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None
        self._loop_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- membership ----------------------------------------------------------

    def register_replica(self, url: str, name: Optional[str] = None,
                         role: Optional[str] = None) -> str:
        """Register a replica and give it ring arcs. Idempotent at the
        same URL. Ring membership changes ONLY here and in
        ``remove_replica`` — transient health blips cordon via the
        breaker without remapping anyone's prefix buckets. Round-17:
        DECODE-only replicas get NO ring arcs — prompts never route to
        them by affinity (they receive streams over the handoff wire),
        so their arcs would only manufacture fallbacks."""
        name = self.pool.add(url, name=name, role=role)
        # seed a load snapshot FIRST: besides giving the first routed
        # request a view of the newcomer, the /load body resolves the
        # ROLE for explicit-name registrations (probe-free in the
        # pool), and the ring-arc decision below must see it
        self.pool.refresh(0.0)
        if self.pool.role(name) != "decode":
            with self._lock:
                self.ring.add(name)
        return name

    def remove_replica(self, name: str) -> bool:
        with self._lock:
            self.ring.remove(name)
        return self.pool.remove(name)

    # -- routing -------------------------------------------------------------

    def _overloaded(self, load: Optional[dict]) -> bool:
        if load is None:
            return False             # no snapshot yet: don't exile it
        if int(load.get("queue_depth", 0)) >= self.overload_queue_depth:
            return True
        free = load.get("pages_free")
        return free is not None and int(free) < self.min_free_pages

    def _pick(self, prompt: List[int],
              adapter=None) -> Tuple[Optional[str], bool]:
        """(replica name, was_affinity_target) — the routing decision.
        Affinity: walk the key's preference order, skipping unroutable
        and overloaded replicas; everyone overloaded -> least-queued
        routable. Random policy: seeded uniform choice (the bench
        baseline). Round-17: fresh prompts route only to PREFILL-capable
        replicas (role prefill/both) — decode workers receive their
        streams over the handoff wire, not the prompt path. A fleet
        with nothing prefill-capable (a misconfiguration) degrades to
        routing anywhere rather than going dark. Round-22: a request
        naming an *adapter* narrows to TENANT-AFFINE replicas — those
        whose last /load snapshot advertises the adapter resident
        (``resident_adapters``) — so a tenant's requests land where
        their factors (and their salted prefix pages) already live; no
        replica advertising it degrades to the normal walk (the landing
        replica answers 400 unless the adapter is pushed, or the
        request named a stack index)."""
        routable = set(self.pool.routable())
        capable = {n for n in routable
                   if self.pool.role(n) != "decode"}
        if capable:
            routable = capable
        if adapter is not None:
            affine = {
                n for n in routable
                if str(adapter) in ((self.pool.snapshot(n) or {})
                                    .get("resident_adapters") or ())}
            if affine:
                if affine != routable:
                    self._c_tenant_affine.inc()
                routable = affine
        if not routable:
            return None, False
        with self._lock:
            if self.policy == "random":
                return self._rng.choice(sorted(routable)), False
            prefs = self.ring.preference(prefix_head_key(
                prompt, self.head_tokens, self.head_quantum))
        if not prefs:
            return None, False
        # the TRUE affinity target is the unfiltered ring head: landing
        # anywhere else — because the target is cordoned, draining OR
        # overloaded — is a fallback, and the metric must say so
        target = prefs[0]
        prefs = [n for n in prefs if n in routable]
        if not prefs:
            return None, False
        for name in prefs:
            if not self._overloaded(self.pool.snapshot(name)):
                if name != target:
                    self._c_fallback.inc()
                return name, name == target
        # everyone overloaded: least-queued routable still gets the work
        # (the SLO-class gate, not the picker, is the shed decision)
        def depth(n):
            load = self.pool.snapshot(n) or {}
            return int(load.get("queue_depth", 0))

        name = min(prefs, key=depth)
        if name != target:
            self._c_fallback.inc()
        return name, name == target

    def _pick_decode(self, exclude=()) -> Optional[str]:
        """The DECODE-pool placement decision (Round-17): where a
        prefill replica should stream a prompt's KV, chosen at
        admission from the decode pool's load — dedicated decode
        replicas first, then colocated ``both`` nodes; within a tier
        the fewest active slots, then the most free pool pages (the
        page floor is the decode pool's real capacity). None when no
        decode-capable replica is routable — the prefill replica then
        serves the stream itself (colocated degrade)."""
        cands = sorted(n for n in self.pool.routable()
                       if n not in exclude
                       and self.pool.role(n) != "prefill")
        if not cands:
            return None
        now = time.monotonic()
        horizon = max(2.0 * self.load_refresh_s, 0.25)
        with self._lock:
            while (self._recent_decode
                   and now - self._recent_decode[0][0] > horizon):
                self._recent_decode.popleft()
            recent = Counter(n for _t, n in self._recent_decode)
            self._decode_rr += 1
            rot = self._decode_rr % len(cands)

        def key(n):
            load = self.pool.snapshot(n) or {}
            free = load.get("pages_free")
            # occupancy = the stale snapshot PLUS this router's own
            # handouts since (inbound transfers + recent assignments):
            # a burst of admissions inside one refresh window must
            # spread, not clump on whichever node was scraped emptiest
            return (0 if self.pool.role(n) == "decode" else 1,
                    int(load.get("active_slots", 0))
                    + int(load.get("queue_depth", 0))
                    + int(load.get("inbound_transfers", 0))
                    + recent[n],
                    -(int(free) if free is not None else 1 << 30))

        # rotate before the (stable) min so residual LOAD TIES break
        # round-robin across admissions instead of always on the first
        # name
        pick = min(cands[rot:] + cands[:rot], key=key)
        with self._lock:
            self._recent_decode.append((now, pick))
        return pick

    def _prefix_peer(self, prompt: List[int],
                     exclude: str) -> Optional[str]:
        """The cross-replica prefix tier's peer hint (Round-19): the
        first replica in this prompt's ring preference order that is
        routable and not *exclude* — where the affinity policy sent (or
        would have sent) this family's earlier traffic. None under the
        random policy (no affinity structure to exploit) or a
        one-replica fleet."""
        routable = set(self.pool.routable())
        routable.discard(exclude)
        if not routable:
            return None
        with self._lock:
            if self.policy == "random":
                return None
            prefs = self.ring.preference(prefix_head_key(
                prompt, self.head_tokens, self.head_quantum))
        for n in prefs:
            if n in routable:
                return n
        return None

    def _route_request(self, req: dict, client_key: Optional[str] = None):
        """One routed generate -> (code, obj); runs under
        ``run_idempotent`` on the handler thread."""
        prompt = req.get("prompt")
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) for t in prompt)):
            return 400, {"error": "prompt must be a non-empty list of "
                                  "token ids"}
        adapter = req.get("adapter")
        # per-tenant SLO classes (Round-22): a request naming an adapter
        # but no explicit class inherits its tenant's declared class —
        # an explicit slo_class always wins (the operator's override)
        slo_class = req.get("slo_class")
        if slo_class is None and adapter is not None:
            slo_class = self.tenant_slo_classes.get(str(adapter))
        slo_class = str(slo_class or "interactive")
        deadline = time.monotonic() + float(
            req.get("timeout") or DEFAULT_ROUTE_TIMEOUT)
        code, obj = self._admit(slo_class)
        if code is not None:
            return code, obj
        # route timing starts AFTER the admission gate: a queue-parked
        # request's park time is already recorded as queue_wait, and
        # folding it into op=route would make route_p99 judge the
        # gate's own delay — the gate re-triggering itself after the
        # original burn cleared
        t0 = time.perf_counter()
        # up to two candidates: the picked replica, and — when the POST
        # itself fails (it started draining / died between the snapshot
        # and now) — one fresh pick with the pool's updated view. ONE
        # idempotency key covers the whole logical request — derived
        # from the CLIENT's key when it sent one, so even a client-level
        # retry of a keyed request reuses the same downstream key: any
        # re-execution that lands the same replica after an ambiguous
        # failure (admitted, response lost past the retry budget)
        # REPLAYS the committed tokens instead of admitting twice. The
        # residual window is a re-pick landing a DIFFERENT replica —
        # transient double compute that retires and frees its pages,
        # bounded by per-replica dedup being the only state a jax-free
        # router can carry.
        leg_key = ("router-gen-" + (client_key or uuid.uuid4().hex))
        last_err: Optional[str] = None
        # attempts: the affinity pick, one failover re-pick, plus
        # several migrated-stream re-pins — a request must be able to
        # CHASE a stream that hops more than once (drain chains, the
        # migrate-check ping-pong) before its budget gives up
        for attempt in range(6):
            pinned = self._pinned_replica(leg_key)
            if pinned is not None:
                name, affinity = pinned, False
            else:
                name, affinity = self._pick(prompt, adapter=adapter)
            if name is None:
                self._c_norep.inc()
                return 503, {"error": "no routable replica"}
            url = self.pool.url(name)
            if url is None:
                self._unpin(leg_key)
                continue
            payload = {"prompt": prompt,
                       "timeout": max(0.1, deadline - time.monotonic())}
            if req.get("sampling") is not None:
                payload["sampling"] = req["sampling"]
            if adapter is not None:
                payload["adapter"] = adapter
            # Round-17 disaggregated placement: a prompt landing on a
            # DEDICATED prefill replica names its decode target NOW —
            # picked from the decode pool by load/free pages — so the
            # prefill replica can stream KV spans there while later
            # chunks still compute. Never on a pinned (chasing) attempt:
            # the stream is already decoding wherever the pin points.
            if pinned is None and self.pool.role(name) == "prefill":
                decode = self._pick_decode(exclude=(name,))
                if decode is not None:
                    payload["decode_target"] = self.pool.url(decode)
                    payload["decode_name"] = decode
            # Round-19 peer prefix tier: name the ring's next preference
            # owner for this prompt's head key — the replica most likely
            # holding the family's cached KV when the chosen one is cold
            # (an affinity fallback, a scale-out rebalance, a fresh
            # node). Advisory: the replica probes its own tiers first,
            # and a dark or faulted peer degrades to cold prefill. Never
            # on a pinned (chasing) attempt — the stream already exists.
            if pinned is None:
                peer = self._prefix_peer(prompt, exclude=name)
                if peer is not None:
                    peer_url = self.pool.url(peer)
                    if peer_url is not None:
                        payload["prefix_peer"] = peer_url
                        payload["prefix_peer_name"] = peer
            try:
                tup = time.perf_counter()
                body = request_json(
                    url + "/generate", payload, token=self.token,
                    idempotency_key=leg_key,
                    timeout=max(0.1, deadline - time.monotonic()))
                self._metrics.record("upstream",
                                     time.perf_counter() - tup)
            except urllib.error.HTTPError as e:
                detail_obj: dict = {}
                try:
                    detail_obj = json.loads(e.read() or b"{}")
                except Exception:  # noqa: BLE001 — body unreadable
                    pass
                if e.code == 409 and detail_obj.get("migrated"):
                    # the stream moved mid-flight: RE-PIN to the new
                    # owner (epoch-fenced) and retry there — the target
                    # either ADOPTS the restored stream via this same
                    # leg key or serves the request fresh; token-exact
                    # either way
                    self._note_migrated(leg_key, detail_obj["migrated"],
                                        from_replica=name)
                    self._c_repin.inc()
                    self.events.emit(
                        "repin", replica=name,
                        target=detail_obj["migrated"].get("replica"),
                        epoch=detail_obj["migrated"].get("epoch"))
                    continue
                if e.code < 500:
                    # a deterministic CLIENT error (bad sampling params,
                    # oversized prompt) — failing over would just repeat
                    # it and mis-file it as infrastructure trouble;
                    # surface the replica's verdict as-is
                    detail = str(detail_obj.get("error", ""))
                    return e.code, {"error": f"replica {name}: "
                                             f"{detail or f'HTTP {e.code}'}"}
                last_err = f"{name}: HTTP {e.code}"
                # a pinned owner answering 5xx is not serving the pin:
                # drop it so the next attempt re-picks fresh
                self._unpin(leg_key)
                self.pool.refresh(0.0)
                continue
            except TRANSIENT_ERRORS as e:
                last_err = f"{name}: {e}"
                self._unpin(leg_key)
                self.pool.refresh(0.0)
                continue
            self._c_routed.inc()
            self._metrics.record("route", time.perf_counter() - t0)
            self.events.emit("route", replica=name, slo_class=slo_class,
                             affinity=affinity,
                             prompt_tokens=len(prompt),
                             **({"adapter": str(adapter)}
                                if adapter is not None else {}))
            self._unpin(leg_key)     # the stream completed: pin done
            body = dict(body)
            body["replica"] = name
            body["affinity"] = affinity
            return 200, body
        self._c_uperr.inc()
        return 502, {"error": f"upstream generate failed: {last_err}"}

    # -- live migration (Round-16) -------------------------------------------

    def _pinned_replica(self, leg_key: str) -> Optional[str]:
        with self._lock:
            pin = self._pins.get(leg_key)
        if pin is None:
            return None
        name = pin[0]
        if name is None or self.pool.url(name) is None:
            self._unpin(leg_key)
            return None
        return name

    def _unpin(self, leg_key: str) -> None:
        with self._lock:
            self._pins.pop(leg_key, None)

    def _on_replica_restart(self, name: str) -> None:
        """Pool-detected hard restart of *name* (boot nonce changed):
        unpin every mid-stream rid that was bound to it so re-drives
        land on replicas that still hold (or can rebuild) the stream."""
        with self._lock:
            stale = [k for k, pin in self._pins.items() if pin[0] == name]
            for k in stale:
                self._pins.pop(k, None)
        if stale:
            self._c_restart_unpins.inc(len(stale))
            self.events.emit("restart_unpin", replica=name,
                             pins=len(stale))

    def _note_migrated(self, leg_key: str, mig: dict,
                       from_replica: Optional[str] = None) -> None:
        """Record a 409-migrated notice as the request's new owner pin.
        EPOCH-FENCED: a notice at a lower epoch than the recorded pin
        is stale (the stream has since moved again) and must not
        repoint — the at-most-one-active argument's router half. One
        exception: a notice FROM the pinned owner itself always wins —
        the live owner disclaiming the stream is fresher than any
        recorded epoch, and epochs are only comparable within one
        stream lineage (an ambiguous handoff followed by a fresh
        re-admission restarts the lineage at 0, so a strict compare
        would wedge the pin on the old lineage's number)."""
        name = mig.get("replica")
        if not name and mig.get("url"):
            name = self.pool.name_for_url(str(mig["url"]))
        epoch = int(mig.get("epoch", 0))
        with self._lock:
            cur = self._pins.get(leg_key)
            if (cur is not None and epoch < cur[1]
                    and cur[0] != from_replica):
                return
            self._pins[leg_key] = (name, epoch)
            self._pins.move_to_end(leg_key)
            while len(self._pins) > 4096:
                self._pins.popitem(last=False)

    def migrate_away(self, name: str, reason: str = "suspect") -> bool:
        """Ask *name* to hand its in-flight streams to the least-loaded
        OTHER routable replica (a background sweep on the source; this
        call only kicks it). The breaker-suspect policy: a suspect node
        is cordoned but may well still serve — asking it to migrate
        away turns "pray the blackout is transient" into a live
        handoff; if the node is truly dark the POST fails and the
        breaker path continues as before (the honest residue)."""
        src_url = self.pool.url(name)
        # Round-17: migrate targets must be ROLE-compatible — a suspect
        # prefill replica's streams hand off to another prefill (or
        # "both") replica, never a decode-only one; cross-pool handoffs
        # would load a pool that is sized and SLO-judged for other work
        src_role = self.pool.role(name)
        candidates = [n for n in self.pool.routable()
                      if n != name
                      and role_compatible(src_role, self.pool.role(n))]
        if src_url is None or not candidates:
            self.events.emit("migrate_away_skip", replica=name,
                             reason=reason)
            return False

        def depth(n):
            load = self.pool.snapshot(n) or {}
            return (int(load.get("active_slots", 0)),
                    int(load.get("queue_depth", 0)), n)

        target = min(candidates, key=depth)
        target_url = self.pool.url(target)
        if target_url is None:
            return False
        self._c_migrate_away.inc()
        self.events.emit("migrate_away", replica=name, target=target,
                         reason=reason)
        try:
            request_json(
                src_url + "/migrate_out",
                {"target": target_url, "reason": reason, "wait": False},
                token=self.token, timeout=self.pool.scrape_timeout,
                idempotency_key=f"router-mig-away-{uuid.uuid4().hex}")
        except Exception as e:  # noqa: BLE001 — source dark: the pray path
            self.events.emit("migrate_away_failed", replica=name,
                             error=str(e)[:120])
            return False
        return True

    def _check_suspects(self) -> None:
        """Breaker-suspect -> migrate-away, once per suspect episode:
        the signals loop calls this each tick; a replica newly marked
        SUSPECT gets one migrate-away sweep (repeated ticks must not
        re-spam a struggling node), and recovery to HEALTHY re-arms
        it."""
        for name in self.pool.names():
            st = self.pool.state(name)
            if st == SUSPECT and name not in self._suspect_handled:
                self._suspect_handled.add(name)
                self.migrate_away(name, reason="suspect")
            elif st == HEALTHY:
                self._suspect_handled.discard(name)

    def _sync_ring_roles(self) -> None:
        """Drop ring arcs from replicas whose learned role is DECODE:
        the registration-time decision used whatever role was known
        then, and a correction from the first successful /load scrape
        must not leave a decode-only replica owning prefix buckets
        (every prompt hashed there would be a permanent fallback).
        One-shot per correction — removing a member remaps only its
        own arcs, the register/remove-only membership contract's
        amendment clause."""
        stale = [n for n in self.ring.members()
                 if self.pool.role(n) == "decode"]
        if stale:
            with self._lock:
                for n in stale:
                    self.ring.remove(n)

    # -- Round-22: adapter distribution (the control-plane surface) ----------

    def adapter_summary(self) -> dict:
        """Registry names + per-replica residency (from the cached
        /load snapshots — no scrape on this path): what ``GET
        /adapters`` serves and ``cli.obs``'s tenants section renders."""
        resident = {}
        for name in self.pool.names():
            load = self.pool.snapshot(name) or {}
            if "resident_adapters" in load:
                resident[name] = list(load.get("resident_adapters") or ())
        return {
            "registered": (self.adapters.names()
                           if self.adapters is not None else []),
            "resident": resident,
        }

    def _adapters_post(self, req: dict):
        """``POST /adapters`` on the router: distribute a REGISTERED
        adapter to replicas ({"name": ..., "replicas"?: [names]} —
        default: every routable multi-LoRA replica), or evict it
        ({"action": "evict", ...}). Per-replica outcomes are reported,
        never collapsed: a partial push is a fact the operator acts on
        (retry the failures), not an error that hides the successes."""
        if self.adapters is None:
            return 404, {"error": "router has no adapter registry"}
        name = req.get("name")
        if not isinstance(name, str) or not name:
            return 400, {"error": "adapter name required"}
        action = str(req.get("action") or "load")
        if action == "load" and name not in self.adapters.names():
            return 404, {"error": f"no registered adapter {name!r}"}
        want = req.get("replicas")
        targets = ([n for n in want if self.pool.url(n) is not None]
                   if isinstance(want, list) else self.pool.routable())
        results = {}
        for rep in targets:
            url = self.pool.url(rep)
            if url is None:
                continue
            try:
                if action == "evict":
                    body = self.adapters.evict_adapter(url, name,
                                                       token=self.token)
                else:
                    body = self.adapters.push_adapter(url, name,
                                                      token=self.token)
                results[rep] = {"ok": True,
                                "resident": body.get("resident")}
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    continue         # not a multi-LoRA replica: skip
                results[rep] = {"ok": False, "code": e.code}
            except Exception as e:  # noqa: BLE001 — per-replica degrade
                results[rep] = {"ok": False, "error": str(e)[:120]}
        self.events.emit("adapter_distribute", name=name, action=action,
                         replicas=len(results))
        return 200, {"name": name, "action": action, "results": results}

    def _admit(self, slo_class: str):
        """The SLO-class gate: (None, None) to proceed; a (code, obj)
        refusal otherwise. Burning = any declared objective's FAST
        window at/over the engine's burn threshold — the early
        multiwindow signal, deliberately more trigger-happy than
        ``firing`` (which also needs the slow window: paging wants
        proof, load-shedding wants reflexes)."""
        if not self._burning():
            return None, None
        if slo_class in self.shed_classes:
            self._c_shed.inc()
            self.events.emit("shed", slo_class=slo_class)
            return 503, {"error": "shed: SLO fast window burning",
                         "slo_class": slo_class}
        if slo_class in self.queue_classes:
            self._c_queued.inc()
            self.events.emit("queue", slo_class=slo_class)
            tq = time.perf_counter()
            q_deadline = time.monotonic() + self.queue_timeout_s
            while time.monotonic() < q_deadline:
                # the signals loop keeps re-evaluating in the background;
                # a parked request only polls the verdict
                time.sleep(0.02)
                if not self._burning():
                    self._metrics.record("queue_wait",
                                         time.perf_counter() - tq)
                    return None, None
            self._c_qtimeout.inc()
            return 503, {"error": "queue timeout: SLO fast window still "
                                  "burning", "slo_class": slo_class}
        return None, None

    # -- SLO evaluation ------------------------------------------------------

    def evaluate_slos(self, min_interval: float = 0.0) -> Dict[str, dict]:
        """Evaluate the declared objectives over the federated fleet
        scrape (throttled by *min_interval*). The router's evaluation
        window is its traffic plus the autoscaler's reconcile cadence —
        both call here."""
        if self.slo is None:
            return {}
        with self._lock:
            now = time.monotonic()
            if min_interval > 0 and now - self._last_slo_eval < min_interval:
                return self.slo.results()
            self._last_slo_eval = now
        return self.slo.evaluate(self.metrics_text())

    def _burning(self) -> bool:
        if self.slo is None:
            return False
        return any(r.get("burn_fast", 0.0) >= self.slo.burn_threshold
                   for r in self.slo.results().values())

    # -- observability -------------------------------------------------------

    def metrics_text(self) -> str:
        """Router registry federated with every replica's ``/metrics``
        (series relabeled ``replica="<name>"``) — what ``GET /metrics``
        serves and what the SLO engine judges."""
        return self.pool.federate_text(self.registry.render())

    def trace(self, trace_id: str) -> dict:
        """One stitched trace: router spans + every replica's leg."""
        spans = {s["span_id"]: s
                 for s in obs_trace.tracer().spans(trace_id)}
        self.pool.trace(trace_id, spans)
        ordered = sorted(spans.values(), key=lambda s: s["start"])
        return {"trace": trace_id, "spans": ordered}

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def _poll_loop(self) -> None:
        """The background signals loop: fleet /load refresh + SLO
        evaluation over the federated scrape, OFF the request path — a
        dark replica's scrape timeout must inflate a background tick,
        never a routed request's latency (the controller's concurrent-
        scrape lesson, PR 6: observability overhead that trips the very
        latency objective it feeds is self-inflicted load shedding).
        Handlers only read the cached snapshots and the engine's last
        verdicts."""
        interval = max(0.05, min(self.load_refresh_s or 0.25,
                                 self._slo_interval or 0.25))
        while not self._stop.wait(interval):
            try:
                # both halves keep their OWN configured cadence — the
                # tick rate is just the scheduler granularity
                self.pool.refresh(self.load_refresh_s)
                # SLO evaluation keeps its OWN cadence: the federation
                # scrape + parse is the dear half, so a fast load tick
                # must not drag it along (the throttle returns cached
                # verdicts inside slo_interval_s)
                self.evaluate_slos(self._slo_interval)
                # breaker-suspect -> migrate-away (Round-16): the sweep
                # itself runs on the SOURCE replica in the background —
                # this tick only asks, so a slow transfer never stalls
                # the signals loop
                self._check_suspects()
                # Round-17: revoke ring arcs granted on a STALE role
                # (an explicit-name registration whose seed scrape
                # missed defaults to "both"; the replica's own /load
                # word corrects the handle later, but ring membership
                # only changes here) — a decode replica must never
                # keep owning prefix buckets
                self._sync_ring_roles()
            except Exception:  # noqa: BLE001 — the loop survives a bad
                pass           # scrape; next tick retries

    def start(self) -> str:
        self._stop.clear()
        self._loop_thread = threading.Thread(
            target=self._poll_loop, name="kubetpu-router-signals",
            daemon=True)
        self._loop_thread.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="kubetpu-router",
            daemon=True)
        self._thread.start()
        return self.address

    def shutdown(self, timeout: float = 5.0) -> None:
        self._inflight.wait_idle(timeout)
        self._stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5.0)
            self._loop_thread = None
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

