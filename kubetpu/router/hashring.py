"""Prefix-affinity hashing — the routing key and the consistent ring.

The router's whole reason to exist (ROADMAP: the millions-of-users
story) is that prefix-cache hit rate should be a CLUSTER property, not
per-replica luck: two requests sharing a system prompt / few-shot
preamble must land on the replica whose radix tree (Round-9) already
holds that prefix's KV pages. Two pieces make that stable:

- ``prefix_head_key``: the routing key is a digest of the TOKENIZED
  prefix head — the first ``head_tokens`` token ids — not the raw text
  and not the whole prompt. The head is what the radix tree can share
  (same system prompt => same head => same key), while unique tails
  would scatter siblings across the fleet if hashed;
- ``HashRing``: classic consistent hashing with virtual nodes. Each
  replica owns ``vnodes`` points on a 2^64 ring; a key routes to the
  first point clockwise. Adding or removing one replica remaps only the
  arcs that replica owns — ~1/N of the key space — so a scale event
  never cold-starts the whole fleet's prefix caches (pinned by test).
  ``preference(key)`` returns the FULL distinct-replica order from the
  key's position, so load-based fallback walks the same deterministic
  list everywhere.

Digests are ``hashlib`` (process-independent, seed-independent) — a
router restart, or two routers in front of the same fleet, must agree
on every key. Stdlib only; imports nothing from kubetpu.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Optional, Sequence

DEFAULT_HEAD_TOKENS = 32
DEFAULT_HEAD_QUANTUM = 16


def prefix_head_key(tokens: Sequence[int],
                    head_tokens: int = DEFAULT_HEAD_TOKENS,
                    quantum: int = DEFAULT_HEAD_QUANTUM) -> str:
    """Stable routing key for a tokenized prompt: hex digest of the
    cacheable HEAD. Long prompts key on their first *head_tokens* ids —
    prompts sharing a head share a key whatever their tails. A prompt
    that fits ENTIRELY inside the head keys on its page-aligned prefix
    (*quantum* = the paged pool's page size, capped one token short —
    the radix tree's publishable-prefix rule): hashing the unique tail
    token would scatter same-family siblings across the fleet, which is
    exactly the luck this router exists to remove. Prompts with no
    cacheable prefix at all (shorter than a page) key on themselves —
    nothing is shareable, so any stable spread is correct."""
    if head_tokens <= 0:
        raise ValueError("head_tokens must be positive")
    if quantum <= 0:
        raise ValueError("quantum must be positive")
    n = min(len(tokens), head_tokens)
    if n >= len(tokens):
        n = ((len(tokens) - 1) // quantum) * quantum
        if n <= 0:
            n = len(tokens)
    head = ",".join(str(int(t)) for t in tokens[:n])
    return hashlib.sha1(
        b"kubetpu-prefix-head:" + head.encode()).hexdigest()


def _point(label: str) -> int:
    """One ring position in [0, 2^64) from a label digest."""
    return int.from_bytes(
        hashlib.sha1(label.encode()).digest()[:8], "big")


class HashRing:
    """Consistent hash ring over replica names with virtual nodes.

    Not thread-safe by itself — the router mutates it under its own
    lock (membership changes ride registration/removal, never the
    per-request path)."""

    def __init__(self, vnodes: int = 64) -> None:
        if vnodes <= 0:
            raise ValueError("vnodes must be positive")
        self.vnodes = vnodes
        self._points: List[int] = []          # sorted ring positions
        self._owner: Dict[int, str] = {}      # position -> replica name
        self._members: Dict[str, List[int]] = {}

    def __len__(self) -> int:
        return len(self._members)

    def members(self) -> List[str]:
        return sorted(self._members)

    def add(self, name: str) -> None:
        """Idempotent: re-adding an existing member is a no-op (the
        points are a pure function of the name, so re-inserting them
        would change nothing anyway)."""
        if name in self._members:
            return
        pts = []
        for i in range(self.vnodes):
            p = _point(f"kubetpu-ring:{name}#{i}")
            # vanishingly unlikely 64-bit collision: skip the point
            # rather than silently overwrite another member's arc
            if p in self._owner:
                continue
            self._owner[p] = name
            bisect.insort(self._points, p)
            pts.append(p)
        self._members[name] = pts

    def remove(self, name: str) -> None:
        for p in self._members.pop(name, ()):
            del self._owner[p]
            i = bisect.bisect_left(self._points, p)
            if i < len(self._points) and self._points[i] == p:
                self._points.pop(i)

    def lookup(self, key: str) -> Optional[str]:
        """The key's primary owner (None on an empty ring)."""
        pref = self.preference(key, n=1)
        return pref[0] if pref else None

    def preference(self, key: str, n: Optional[int] = None) -> List[str]:
        """Distinct replica names in ring order starting at *key*'s
        position — index 0 is the affinity target, the rest the
        deterministic fallback order (at most *n* names)."""
        if not self._points:
            return []
        want = len(self._members) if n is None else min(n, len(self._members))
        start = bisect.bisect_right(self._points, _point(f"key:{key}"))
        out: List[str] = []
        seen = set()
        for i in range(len(self._points)):
            owner = self._owner[self._points[(start + i) % len(self._points)]]
            if owner not in seen:
                seen.add(owner)
                out.append(owner)
                if len(out) >= want:
                    break
        return out
