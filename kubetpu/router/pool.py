"""``ReplicaPool`` — the router's replica registry, health breaker and
load snapshot cache.

The control plane already knows how to keep a fleet honest (Round-7:
circuit-breaker node health, graceful drain); this module applies the
same discipline to serving replicas behind the data plane:

- **registration**: ``add(url)`` probes ``/healthz`` to learn the
  replica's name (idempotent at the same URL — re-registering is a
  no-op), ``remove(name)`` forgets it;
- **breaker health**: every ``refresh()`` probes ``/load``; the states
  and transitions mirror the controller's breaker
  (healthy -> suspect -> probation -> dead): ``suspect_after``
  consecutive misses cordons the replica out of routing WITHOUT
  forgetting it (a transient blackout costs zero remaps — ring
  membership only changes on register/remove), ``dead_after`` misses
  marks it dead, a success moves suspect to probation and
  ``probation_passes`` consecutive successes restore healthy. Every
  transition lands in the event log;
- **load snapshots**: the ``/load`` body (queue depth, active slots,
  pool free pages, prefix hit rate — ``SlotServerBase.load_info``) is
  cached per replica; ``refresh(min_interval)`` is throttled so the
  per-request routing path reads a fresh-enough snapshot without
  scraping per request. Scrapes run CONCURRENTLY (the controller's
  federation shape): N dark replicas cost one timeout, not N;
- **drain tracking**: ``drain(name)`` POSTs the replica's ``/drain``
  (idempotency-keyed) and marks the handle; ``drained(name)`` reads
  the last snapshot — draining AND idle — which is the autoscaler's
  scale-down-only-after-drain gate;
- **federation**: ``federate_text(own)`` merges every replica's
  ``/metrics`` into one exposition (series relabeled
  ``replica="<name>"``) and ``trace(id)`` stitches replica trace legs
  — the router's ``/metrics`` and ``/trace/<id>`` surfaces.

All scrapes ride the shared retrying client (``request_text`` /
``request_json`` — KTP002), ``NO_RETRY`` for probes (a missed probe is
breaker evidence, not an outage worth backoff).
"""

from __future__ import annotations

import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from kubetpu.obs.events import EventLog
from kubetpu.obs.registry import Registry, federate
from kubetpu.wire.httpcommon import NO_RETRY, request_json, request_text

# breaker states — the controller's strings (wire.controller), repeated
# here so the router package never imports the control plane
HEALTHY = "healthy"
SUSPECT = "suspect"
PROBATION = "probation"
DEAD = "dead"

# serving roles (Round-17 disaggregated prefill/decode)
ROLES = ("prefill", "decode", "both")


def role_compatible(src_role: Optional[str],
                    dst_role: Optional[str]) -> bool:
    """May *dst* take over *src*'s in-flight streams? Same pool or a
    colocated ``"both"`` node — never across dedicated pools: a suspect
    PREFILL replica's streams hand off to another prefill (or both)
    replica, not a decode-only one whose pool is sized and SLO-judged
    for pure decode traffic (and vice versa). Unknown roles read as
    ``"both"`` (the pre-Round-17 fleet)."""
    src = src_role or "both"
    dst = dst_role or "both"
    return dst == "both" or dst == src


class ReplicaHandle:
    """One replica's registration + breaker + last load snapshot."""

    def __init__(self, name: str, url: str, role: str = "both") -> None:
        self.name = name
        self.url = url.rstrip("/")
        self.role = role if role in ROLES else "both"
        self.state = HEALTHY
        self.misses = 0
        self.passes = 0
        self.draining = False
        self.load: Optional[dict] = None
        self.last_seen = 0.0
        # Round-20 boot-nonce fencing: the replica process's per-boot
        # identity (from /healthz and /load). A changed nonce under the
        # same name means the process restarted — its KV cache is gone
        # and any mid-stream state with it.
        self.nonce: Optional[str] = None

    def routable(self) -> bool:
        return self.state in (HEALTHY, PROBATION) and not self.draining

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "url": self.url,
            "role": self.role,
            "state": self.state,
            "draining": self.draining,
            "load": self.load,
        }


class ReplicaPool:
    """Thread-safe replica registry + breaker + snapshot cache."""

    def __init__(
        self,
        token: Optional[str] = None,
        suspect_after: int = 2,
        dead_after: int = 5,
        probation_passes: int = 2,
        scrape_timeout: float = 2.0,
        registry: Optional[Registry] = None,
        events: Optional[EventLog] = None,
    ) -> None:
        if not 1 <= suspect_after <= dead_after:
            raise ValueError("need 1 <= suspect_after <= dead_after")
        self.token = token
        self.suspect_after = suspect_after
        self.dead_after = dead_after
        self.probation_passes = probation_passes
        self.scrape_timeout = scrape_timeout
        self.registry = registry if registry is not None else Registry()
        self.events = events if events is not None else EventLog(
            component="router")
        self._lock = threading.Lock()
        self._replicas: Dict[str, ReplicaHandle] = {}
        self._last_refresh = 0.0
        # Round-20: observers of hard-kill restarts (same name, new
        # boot nonce) — the router drops its mid-stream pins here
        self._restart_cbs: List = []
        self._c_restarts = self.registry.counter(
            "kubetpu_router_replica_restarts_total",
            "replicas seen returning with a NEW boot nonce (cache-wiped)")
        self._c_takeovers = self.registry.counter(
            "kubetpu_router_replica_takeovers_total",
            "same-name re-registrations that took over a dead/restarted "
            "handle")
        for state in (HEALTHY, SUSPECT, PROBATION, DEAD):
            # state ranges over the fixed literal tuple above (KTP004's
            # bounded proof); closure binds the loop variable by default
            self.registry.gauge_fn(
                "kubetpu_router_replicas",
                lambda s=state: self._count_state(s), state=state)

    def _count_state(self, state: str) -> int:
        with self._lock:
            return sum(1 for h in self._replicas.values()
                       if h.state == state)

    # -- restart observation (Round-20) --------------------------------------

    def on_restart(self, cb) -> None:
        """Register ``cb(name)`` to fire when a replica is recognized as
        restarted (same name, new boot nonce) — takeover registrations
        included. Callbacks run outside the pool lock; exceptions are
        swallowed (an observer must not break breaker bookkeeping)."""
        self._restart_cbs.append(cb)

    def _fire_restart(self, name: str) -> None:
        self._c_restarts.inc()
        for cb in list(self._restart_cbs):
            try:
                cb(name)
            except Exception:  # noqa: BLE001 — observers are best-effort
                pass

    # -- membership ----------------------------------------------------------

    def add(self, url: str, name: Optional[str] = None,
            role: Optional[str] = None) -> str:
        """Register a replica by URL; probes ``/healthz`` for its name
        (serving ROLE — Round-17 — and boot nonce — Round-20) unless
        given. Idempotent: the same URL re-registers as the same handle
        (breaker state kept). A DIFFERENT url under an existing name is
        refused — silently swapping the handle would orphan the first
        replica (running, unobserved, undrained) and repoint its ring
        arcs — UNLESS the newcomer is a legitimate restart of the same
        replica: the existing handle is breaker-DEAD, or the probe
        returned a boot nonce the handle doesn't carry. A restart TAKES
        OVER the handle in place (``replica_takeover`` event): the name
        keeps its ring arcs, the breaker walks probation from suspect,
        and restart observers fire so the router drops its mid-stream
        pins."""
        url = url.rstrip("/")
        probed_nonce = None
        if name is None:
            body = request_json(url + "/healthz",
                                timeout=self.scrape_timeout)
            name = body.get("replica") or url
            role = role or body.get("role")
            probed_nonce = body.get("boot_nonce")
        # explicit-name registration stays probe-free: the role
        # defaults to "both" and the replica's own /load word corrects
        # it on the first refresh (the router refreshes right after
        # registering, before granting ring arcs)
        role = role or "both"
        takeover_from = None
        with self._lock:
            existing = self._replicas.get(name)
            if existing is not None:
                if existing.url == url:
                    return name
                restarted = (
                    existing.state == DEAD
                    or (probed_nonce is not None
                        and existing.nonce is not None
                        and probed_nonce != existing.nonce))
                if not restarted:
                    raise ValueError(
                        f"replica name {name!r} is already registered at "
                        f"{existing.url}; remove it before registering "
                        f"{url}")
                takeover_from = existing.url
                existing.url = url
                existing.role = role if role in ROLES else existing.role
                existing.nonce = probed_nonce
                # the restarted process is cache-wiped and unproven: it
                # re-earns routing through probation (the next clean
                # /load probe moves SUSPECT -> PROBATION), and its old
                # load snapshot is meaningless
                existing.state = SUSPECT
                existing.misses = 0
                existing.passes = 0
                existing.load = None
            else:
                h = ReplicaHandle(name, url, role=role)
                h.nonce = probed_nonce
                self._replicas[name] = h
        if takeover_from is not None:
            self._c_takeovers.inc()
            self.events.emit("replica_takeover", replica=name, url=url,
                             old_url=takeover_from)
            self._fire_restart(name)
            return name
        self.events.emit("replica_register", replica=name, url=url,
                         role=role)
        return name

    def remove(self, name: str) -> bool:
        with self._lock:
            gone = self._replicas.pop(name, None)
        if gone is not None:
            self.events.emit("replica_remove", replica=name)
        return gone is not None

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)

    def routable(self) -> List[str]:
        with self._lock:
            return sorted(n for n, h in self._replicas.items()
                          if h.routable())

    def url(self, name: str) -> Optional[str]:
        with self._lock:
            h = self._replicas.get(name)
            return h.url if h is not None else None

    def role(self, name: str) -> Optional[str]:
        """The replica's serving role (``prefill``/``decode``/``both``;
        None for unknown names). Placement, migrate-target selection
        and the per-pool autoscaler all key on this."""
        with self._lock:
            h = self._replicas.get(name)
            return h.role if h is not None else None

    def snapshot(self, name: str) -> Optional[dict]:
        """The last ``/load`` body for *name* (None before the first
        successful refresh)."""
        with self._lock:
            h = self._replicas.get(name)
            return dict(h.load) if h is not None and h.load else None

    def to_json(self) -> List[dict]:
        with self._lock:
            return [h.to_json() for _n, h in sorted(self._replicas.items())]

    # -- health + load refresh -----------------------------------------------

    def refresh(self, min_interval: float = 0.0) -> bool:
        """Scrape every replica's ``/load`` (concurrently), update
        snapshots and breaker states. Throttled: returns False without
        scraping when the last refresh is younger than *min_interval*
        (the per-request routing path passes its staleness budget; the
        autoscaler passes 0 for a fresh view)."""
        with self._lock:
            now = time.monotonic()
            if min_interval > 0 and now - self._last_refresh < min_interval:
                return False
            self._last_refresh = now
            targets = [(h.name, h.url) for h in self._replicas.values()]
        if not targets:
            return True

        def scrape(item):
            name, url = item
            try:
                return name, request_json(
                    url + "/load", token=self.token,
                    timeout=self.scrape_timeout, retry=NO_RETRY)
            except Exception:  # noqa: BLE001 — a miss is breaker evidence
                return name, None

        with ThreadPoolExecutor(max_workers=min(16, len(targets))) as pool:
            results = list(pool.map(scrape, sorted(targets)))
        for name, load in results:
            if load is None:
                self._record_miss(name)
            else:
                self._record_ok(name, load)
        return True

    def _record_miss(self, name: str) -> None:
        with self._lock:
            h = self._replicas.get(name)
            if h is None or h.state == DEAD:
                return
            h.misses += 1
            h.passes = 0
            misses, transition = h.misses, None
            if h.misses >= self.dead_after:
                h.state, transition = DEAD, "replica_dead"
            elif h.state != SUSPECT and h.misses >= self.suspect_after:
                h.state, transition = SUSPECT, "replica_suspect"
        if transition:
            self.events.emit(transition, replica=name, misses=misses)

    def _record_ok(self, name: str, load: dict) -> None:
        restarted = False
        with self._lock:
            h = self._replicas.get(name)
            if h is None:
                return
            # Round-20 boot-nonce fencing: a /load answering under the
            # same name with a NEW nonce is a hard-killed-and-restarted
            # process — its KV cache and in-flight streams are gone.
            # Force the breaker to SUSPECT so the normal ok-path below
            # walks it through probation (never straight back to
            # healthy on the very probe that revealed the restart), and
            # let the restart observers (the router's unpin hook) fire.
            nonce = load.get("boot_nonce")
            if (nonce is not None and h.nonce is not None
                    and nonce != h.nonce):
                restarted = True
                h.state = SUSPECT
                h.passes = 0
            if nonce is not None:
                h.nonce = nonce
            h.load = dict(load)
            if load.get("role") in ROLES:
                h.role = load["role"]     # the replica's own word wins
            # the LOCAL cordon is sticky: pool.drain() promises the
            # router stops routing even when the /drain POST was lost,
            # so a replica still reporting draining=False must not
            # un-cordon the handle (replicas have no un-drain path —
            # only remove/re-add resets it)
            h.draining = h.draining or bool(load.get("draining"))
            h.last_seen = time.time()
            h.misses = 0
            transition = None
            if h.state in (DEAD, SUSPECT):
                # a dead/suspect replica answering again re-earns
                # routing the slow way, like the controller's breaker:
                # through probation, never straight to healthy
                h.state, h.passes = PROBATION, 1
                transition = "replica_probation"
            elif h.state == PROBATION:
                h.passes += 1
                if h.passes >= self.probation_passes:
                    h.state, transition = HEALTHY, "replica_recovered"
        if restarted:
            self.events.emit("replica_restart", replica=name)
            self._fire_restart(name)
        if transition:
            self.events.emit(transition, replica=name)

    # -- drain ---------------------------------------------------------------

    def drain(self, name: str, migrate_to: Optional[str] = None,
              reason: str = "drain") -> bool:
        """Ask *name* to drain (idempotency-keyed POST) and stop routing
        to it. With *migrate_to* (a replica URL) the drain is a LIVE
        HANDOFF: the replica migrates its in-flight streams there
        token-exactly and completes immediately (Round-16) instead of
        waiting out every stream. Returns False for unknown replicas; a
        failed POST still cordons the handle (the router stops sending
        work either way — the replica-side refusal is belt on top of
        braces)."""
        with self._lock:
            h = self._replicas.get(name)
            if h is None:
                return False
            h.draining = True
            url = h.url
        body: dict = {"reason": reason}
        if migrate_to:
            body["migrate_to"] = migrate_to
        try:
            request_json(url + "/drain", body, token=self.token,
                         timeout=self.scrape_timeout,
                         idempotency_key=f"router-drain-{uuid.uuid4().hex}")
        except Exception:  # noqa: BLE001 — cordon held locally regardless
            pass
        return True

    def name_for_url(self, url: str) -> Optional[str]:
        """Registered name owning *url* (None when unknown) — how the
        router resolves a migrated-to target named only by URL."""
        url = url.rstrip("/")
        with self._lock:
            for n, h in self._replicas.items():
                if h.url == url:
                    return n
        return None

    def drained(self, name: str) -> bool:
        """True once the replica's LAST snapshot shows it draining and
        idle — no active slots, nothing queued, no in-flight prefills.
        The autoscaler's remove gate: scale-down completes only here.
        A DEAD victim counts as drained: its streams are already gone,
        and waiting on a snapshot a dead replica can never refresh
        would wedge the scale-down forever."""
        with self._lock:
            h = self._replicas.get(name)
            if h is None:
                return True          # already gone
            if h.state == DEAD:
                return True
            load = h.load
            if not h.draining or load is None:
                return False
        return (int(load.get("active_slots", 1)) == 0
                and int(load.get("queue_depth", 1)) == 0
                and int(load.get("inflight_prefills", 0)) == 0
                # a slot frozen mid-handoff is NOT drained: removing
                # the source before its commit-ack drops the stream
                and int(load.get("migrating_slots", 0)) == 0
                and bool(load.get("draining")))

    def alive(self) -> List[str]:
        """Names whose breaker state is not DEAD — what capacity
        decisions (the autoscaler's max_replicas gate) count; a dead
        handle is evidence, not capacity."""
        with self._lock:
            return sorted(n for n, h in self._replicas.items()
                          if h.state != DEAD)

    def state(self, name: str) -> Optional[str]:
        with self._lock:
            h = self._replicas.get(name)
            return h.state if h is not None else None

    def tier_summary(self) -> dict:
        """Fleet view of the tiered KV cache (Round-19), aggregated
        from the cached ``/load`` snapshots: total host-tier bytes and
        nodes, per-tier hit/fill/spill counts summed across replicas,
        and how many replicas have the tier enabled. The cli's tiering
        line and the operator's budget-sizing loop read this instead of
        scraping N ``/metrics`` expositions."""
        out = {
            "replicas": 0,
            "tiered_replicas": 0,
            "host_bytes": 0,
            "host_nodes": 0,
            "hits": {"hbm": 0, "host": 0, "peer": 0},
            "fills": {"host": 0, "peer": 0},
            "spills": {"host": 0},
        }
        with self._lock:
            loads = [dict(h.load) for h in self._replicas.values()
                     if h.load]
        out["replicas"] = len(loads)
        for load in loads:
            if "tier_host_bytes" not in load:
                continue
            out["tiered_replicas"] += 1
            out["host_bytes"] += int(load.get("tier_host_bytes", 0))
            out["host_nodes"] += int(load.get("tier_host_nodes", 0))
            for key in ("hits", "fills", "spills"):
                for tier, n in (load.get(f"tier_{key}") or {}).items():
                    if tier in out[key]:
                        out[key][tier] += int(n)
        return out

    # -- federation ----------------------------------------------------------

    def federate_text(self, own: str) -> str:
        """*own* exposition merged with every replica's ``/metrics``,
        replica series relabeled ``replica="<name>"``. Failures skip
        that replica and count — federation degrades, never 500s."""
        with self._lock:
            targets = [(h.name, h.url) for h in self._replicas.values()]
        scraped: Dict[str, str] = {}

        def scrape(item):
            name, url = item
            try:
                return name, request_text(
                    url + "/metrics", token=self.token,
                    timeout=self.scrape_timeout, retry=NO_RETRY)
            except Exception:  # noqa: BLE001 — degrade per replica
                self.registry.counter(
                    "kubetpu_router_federation_scrape_errors_total").inc()
                return name, None

        if targets:
            with ThreadPoolExecutor(
                    max_workers=min(16, len(targets))) as pool:
                for name, text in pool.map(scrape, sorted(targets)):
                    if text is not None:
                        scraped[name] = text
        return federate(own, scraped, label="replica")

    def trace(self, trace_id: str, spans: Dict[str, dict]) -> None:
        """Merge every replica's ``/trace/<id>`` leg into *spans*
        (span_id-keyed, first writer wins — in-process fleets share the
        tracer, cross-process ones don't)."""
        with self._lock:
            targets = [(h.name, h.url) for h in self._replicas.values()]
        for _name, url in sorted(targets):
            try:
                body = request_json(
                    f"{url}/trace/{trace_id}", token=self.token,
                    timeout=self.scrape_timeout, retry=NO_RETRY)
                for s in body.get("spans", []):
                    spans.setdefault(s["span_id"], s)
            except Exception:  # noqa: BLE001 — a dark replica loses its
                pass           # leg, not the whole trace
