"""Snapshot wire codec for live KV migration (Round-16).

``PagedDecodeServer.snapshot_slot`` returns a host-side dict whose
``pages`` entry holds numpy arrays (f32 pools: ``k``/``v``; kv_int8
pools: the quantized ``k_q``/``k_s``/``v_q``/``v_s`` pairs AS STORED —
the codec never dequantizes). JSON can't carry them, and one monolithic
body would couple the transfer's fault surface to the snapshot size —
so the wire protocol splits a snapshot into:

- **meta**: the JSON-safe fields plus an ``arrays`` manifest
  (name/dtype/shape per array, in blob order);
- **blob**: every array's raw bytes concatenated in manifest order,
  shipped as base64 CHUNKS of ``chunk_bytes`` each.

``encode_snapshot`` produces (meta, blob); ``decode_snapshot`` is the
exact inverse (length-checked — a short blob means a lost chunk and
must fail loudly, never restore garbage KV). ``blob_chunks`` is the
splitter the replica's ``/migrate_in`` phases ride.

Stdlib + numpy only (the router package stays jax-free); the arrays
cross back into jax land inside ``restore_slot``.
"""

from __future__ import annotations

import base64
from typing import Dict, List, Tuple

import numpy as np

DEFAULT_CHUNK_BYTES = 256 * 1024


def encode_snapshot(snap: dict) -> Tuple[dict, bytes]:
    """Split a slot snapshot into (JSON-safe meta, raw page blob)."""
    meta = {k: v for k, v in snap.items() if k != "pages"}
    specs: List[dict] = []
    parts: List[bytes] = []
    for name in sorted(snap.get("pages", {})):
        arr = np.ascontiguousarray(snap["pages"][name])
        specs.append({"name": name, "dtype": str(arr.dtype),
                      "shape": list(arr.shape)})
        parts.append(arr.tobytes())
    meta["arrays"] = specs
    return meta, b"".join(parts)


def decode_snapshot(meta: dict, blob: bytes) -> dict:
    """Rebuild the snapshot dict ``restore_slot`` consumes. Raises
    ValueError when the blob's length disagrees with the manifest — a
    lost or duplicated chunk must refuse the restore, not scribble
    half a cache."""
    snap = {k: v for k, v in meta.items() if k != "arrays"}
    pages: Dict[str, np.ndarray] = {}
    off = 0
    for spec in meta.get("arrays", ()):
        dt = np.dtype(spec["dtype"])
        n = int(np.prod(spec["shape"], dtype=np.int64)) * dt.itemsize
        if off + n > len(blob):
            raise ValueError(
                f"snapshot blob truncated: need {off + n} bytes for "
                f"{spec['name']!r}, have {len(blob)}")
        pages[spec["name"]] = np.frombuffer(
            blob[off:off + n], dtype=dt).reshape(spec["shape"]).copy()
        off += n
    if off != len(blob):
        raise ValueError(
            f"snapshot blob has {len(blob) - off} trailing bytes — "
            f"manifest and chunks disagree")
    snap["pages"] = pages
    return snap


def blob_chunks(blob: bytes,
                chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> List[bytes]:
    """Split *blob* into wire chunks (always at least one, so the
    commit leg can assert it saw every sequence number even for an
    empty manifest)."""
    if chunk_bytes <= 0:
        raise ValueError("chunk_bytes must be positive")
    if not blob:
        return [b""]
    return [blob[i:i + chunk_bytes]
            for i in range(0, len(blob), chunk_bytes)]


def chunk_b64(chunk: bytes) -> str:
    return base64.b64encode(chunk).decode("ascii")


def chunk_unb64(data: str) -> bytes:
    return base64.b64decode(data.encode("ascii"), validate=True)


# -- page-span streaming (Round-17 disaggregated prefill/decode) --------------
#
# A full-slot snapshot ships each pool array as ONE manifest entry
# ("k"/"v", or the int8 quadruple) — fine when the snapshot exists all at
# once. The disaggregated handoff streams pages AS PREFILL COMPLETES
# THEM, so the blob grows span by span: each completed page range is its
# own set of manifest entries ("k@5" = the k pages starting at logical
# page 5), encoded and chunked independently, appended to the transfer
# in ship order. The commit's manifest lists the spans in exactly that
# order (decode_snapshot follows manifest order, not name order), and
# ``assemble_spans`` stitches them back into the contiguous per-field
# arrays ``restore_slot`` consumes — refusing gaps and overlaps, because
# a hole would restore a slot with missing KV.


def span_name(field: str, start_page: int) -> str:
    """Manifest name for *field*'s pages starting at logical page
    *start_page* (``"k@5"``)."""
    return f"{field}@{int(start_page)}"


def assemble_spans(pages: Dict[str, "np.ndarray"],
                   from_page: int) -> Dict[str, "np.ndarray"]:
    """Stitch span-named arrays back into contiguous per-field arrays
    whose page axis starts at *from_page* (the transfer's
    ``ship_from_page``). Plain (span-free) names pass through untouched
    — the Round-16 full-snapshot path. Raises ValueError on a gap,
    overlap, or mixed plain+span naming for one field."""
    if not any("@" in name for name in pages):
        return dict(pages)
    spans: Dict[str, List[Tuple[int, "np.ndarray"]]] = {}
    for name, arr in pages.items():
        if "@" not in name:
            raise ValueError(
                f"transfer mixes span-named and plain page arrays "
                f"({name!r} next to spans)")
        field, _, start = name.partition("@")
        spans.setdefault(field, []).append((int(start), arr))
    out: Dict[str, "np.ndarray"] = {}
    for field, parts in spans.items():
        parts.sort(key=lambda p: p[0])
        expect = from_page
        for start, arr in parts:
            if start != expect:
                raise ValueError(
                    f"span {field}@{start} does not continue at page "
                    f"{expect} — transfer has a "
                    f"{'gap' if start > expect else 'overlap'}")
            expect = start + arr.shape[1]
        out[field] = (parts[0][1] if len(parts) == 1 else
                      np.concatenate([a for _s, a in parts], axis=1))
    return out


# -- peer prefix fetch (Round-19 tiered KV cache) -----------------------------
#
# The cross-replica tier ships ONE page span per fetch — the requester
# asks the ring's previous preference owner for its cached coverage of a
# cold prompt before cold-prefilling. The span rides the same manifest +
# b64-chunk machinery as a migration transfer (span-named entries,
# length-checked decode, gap/overlap-refusing assembly), folded into a
# single JSON body because a prefix fetch is read-only and at-most-once
# by construction: the exporter mutates nothing, the importer's
# tree-insert consumes nothing it already covers — so a retry (the
# requester keys the POST idempotently anyway) can at worst repeat work,
# never double-commit.


def encode_span_payload(pages: Dict[str, "np.ndarray"], from_page: int,
                        chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> dict:
    """JSON-safe encoding of one stored-layout page-span dict (page axis
    1): span-named manifest + base64 chunks of the concatenated blob.
    ``decode_span_payload`` is the exact inverse."""
    meta, blob = encode_snapshot({"pages": {
        span_name(field, from_page): arr
        for field, arr in pages.items()}})
    return {
        "arrays": meta["arrays"],
        "from_page": int(from_page),
        "chunks": [chunk_b64(c) for c in blob_chunks(blob, chunk_bytes)],
    }


def decode_span_payload(payload: dict) -> Dict[str, "np.ndarray"]:
    """Rebuild the per-field page arrays from an ``encode_span_payload``
    body. Raises ValueError when the chunks disagree with the manifest
    (truncated/duplicated chunk) or spans gap/overlap — a bad fetch must
    degrade to cold prefill, never inject garbage KV."""
    blob = b"".join(chunk_unb64(c) for c in payload.get("chunks", ()))
    snap = decode_snapshot({"arrays": payload.get("arrays", ())}, blob)
    return assemble_spans(snap["pages"], int(payload.get("from_page", 0)))
