"""Adapter registry + distribution — the multi-LoRA control plane
(Round-22).

The data-plane half of thousand-tenant serving lives in
``kubetpu.jobs.multi_lora`` (one packed replica, stacked adapter tree,
per-slot integer retargeting). This module is the fleet half: where
adapter weights LIVE before any replica holds them, and how they move.

- ``AdapterRegistry`` — the controller-side source of truth, shipped
  like checkpoint shards: content-hashed (``adapter_fingerprint``), so
  an adapter's name IS its bytes — registering the same tree twice
  under different paths dedupes, and two registries trained from the
  same artifact agree on every name with no coordination;
- ``encode_adapter``/``decode_adapter`` — the wire codec (per-leaf
  dtype + shape + base64 bytes; at rank 8 an adapter is ~0.1% of the
  base model, so JSON transport is fine and keeps the leg debuggable);
- ``push_adapter``/``evict_adapter`` — the replica legs over
  ``POST /adapters``, idempotency-keyed per (adapter, replica): a
  retried push whose first response was lost REPLAYS, and the replica's
  own load is content-idempotent besides — a replay can never
  double-load (pinned by ``make lora-check`` under injected faults).

The router reads each replica's advertised ``resident_adapters`` (from
the ``/load`` snapshot) for tenant-affine routing — see
``RouterServer._pick``.
"""

from __future__ import annotations

import base64
import threading
import uuid
from typing import Dict, List, Optional

import numpy as np

from kubetpu.jobs.multi_lora import adapter_fingerprint
from kubetpu.wire.httpcommon import request_json

DEFAULT_PUSH_TIMEOUT = 10.0


def encode_adapter(adapter) -> dict:
    """One adapter tree (``init_lora_params`` layout) -> a JSON-safe
    wire object: {"blocks": {leaf: {dtype, shape, data(b64)}}}."""
    out = {}
    for k, v in adapter["blocks"].items():
        arr = np.ascontiguousarray(np.asarray(v))
        out[k] = {
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
            "data": base64.b64encode(arr.tobytes()).decode("ascii"),
        }
    return {"blocks": out}


def decode_adapter(obj: dict) -> dict:
    """Inverse of ``encode_adapter``; raises ``ValueError`` on any
    malformed leaf (the wire handler's 400)."""
    blocks = obj.get("blocks")
    if not isinstance(blocks, dict) or not blocks:
        raise ValueError("adapter payload needs a non-empty blocks map")
    out = {}
    for k, leaf in blocks.items():
        try:
            raw = base64.b64decode(leaf["data"])
            arr = np.frombuffer(raw, dtype=np.dtype(leaf["dtype"]))
            out[k] = arr.reshape([int(d) for d in leaf["shape"]])
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"adapter leaf {k!r} malformed: {e}") from e
    return {"blocks": out}


class AdapterRegistry:
    """Content-hashed adapter store — the fleet's source of truth.

    ``register`` names an adapter by its fingerprint (or an explicit
    alias); the SAME bytes re-register as a no-op, the same alias over
    DIFFERENT bytes refuses (an alias must never silently retarget —
    tenants route by it). Encoded wire payloads are cached per name, so
    pushing one adapter to N replicas encodes once."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._adapters: Dict[str, dict] = {}     # name -> tree
        self._encoded: Dict[str, dict] = {}      # name -> wire payload
        self._digest: Dict[str, str] = {}        # name -> fingerprint

    def register(self, adapter, name: Optional[str] = None) -> str:
        fp = adapter_fingerprint(adapter)
        name = name or fp
        with self._lock:
            have = self._digest.get(name)
            if have is not None:
                if have != fp:
                    raise ValueError(
                        f"adapter name {name!r} is already registered "
                        f"with different content")
                return name
            self._adapters[name] = adapter
            self._digest[name] = fp
        return name

    def get(self, name: str):
        with self._lock:
            a = self._adapters.get(name)
        if a is None:
            raise KeyError(f"no registered adapter {name!r}")
        return a

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._adapters)

    def __len__(self) -> int:
        with self._lock:
            return len(self._adapters)

    def encoded(self, name: str) -> dict:
        with self._lock:
            enc = self._encoded.get(name)
            if enc is None:
                a = self._adapters.get(name)
                if a is None:
                    raise KeyError(f"no registered adapter {name!r}")
                enc = encode_adapter(a)
                self._encoded[name] = enc
        return enc

    # -- replica legs --------------------------------------------------------

    def push_adapter(self, replica_url: str, name: str,
                     token: Optional[str] = None,
                     timeout: float = DEFAULT_PUSH_TIMEOUT) -> dict:
        """Hot-load the registered adapter *name* into one replica over
        ``POST /adapters``. The idempotency key is per ATTEMPT (the
        ``migrate_rid`` spelling): retries inside ``request_json`` reuse
        it, so a lost response REPLAYS the committed answer — while a
        later, separate push after an intervening evict re-executes
        under a fresh key instead of replaying a stale verdict.
        At-most-once residency is the replica's job either way (its
        load is content-idempotent), not the key's. Raises on a
        definitive wire refusal."""
        return request_json(
            replica_url.rstrip("/") + "/adapters",
            {"action": "load", "name": name,
             "adapter": self.encoded(name)},
            token=token, timeout=timeout,
            idempotency_key=f"adapter-load-{name}-{uuid.uuid4().hex[:8]}")

    def evict_adapter(self, replica_url: str, name: str,
                      token: Optional[str] = None,
                      timeout: float = DEFAULT_PUSH_TIMEOUT) -> dict:
        """Evict *name* from one replica. 409 (adapter pinned by a live
        request) raises ``urllib.error.HTTPError`` — eviction under
        pressure must wait for the stream, never yank it. Per-attempt
        key, like the push leg — the replica's evict is name-idempotent
        (False when already gone), so a re-executed retry is
        harmless."""
        return request_json(
            replica_url.rstrip("/") + "/adapters",
            {"action": "evict", "name": name},
            token=token, timeout=timeout,
            idempotency_key=f"adapter-evict-{name}-{uuid.uuid4().hex[:8]}")
