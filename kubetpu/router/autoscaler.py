"""``ReplicaAutoscaler`` — SLO-driven replica scaling behind the router.

The Round-11 signal layer computes the judgment (burn rates, federated
percentiles, pool pressure); this loop ACTS on it. One reconcile pass
(``poll_once``) reads the federated signals the ISSUE names — worst
replica queue-wait p99 and TTFT p50, pool free-page fraction, the
router's SLO fast-window burn — and folds them into a hot/cold verdict
with HYSTERESIS:

- **scale up** after ``up_after`` CONSECUTIVE hot passes (a single
  slow scrape must not buy hardware): ``launcher()`` is called (the
  operator's replica factory — boots a server, returns its URL) and
  the newcomer registers with the router, earning its ring arcs (which
  remaps only ~1/N prefix buckets — the hashring contract);
- **scale down** is MIGRATE -> DRAIN -> REMOVE (Round-16):
  ``down_after`` consecutive cold passes pick the least-loaded
  routable victim, hand its in-flight streams live to the least-loaded
  survivor (token-exact slot handoff — ``scale_down_migrate`` event),
  and drain it (routing stops immediately). Only when the victim's
  ``/load`` reads drained-and-idle is it removed from the ring and
  handed to ``terminator`` — a scale-down never drops a live stream
  AND never waits out a long one;
- **cooldown** after any action (``cooldown_s``) so a scale event's
  own disruption (warmup, cache cold start) can't trigger the next.

Every decision is an event (``scale_up`` -> ... -> ``drain`` ->
``scale_down``) in the router's event log — the ordering the
acceptance test pins — plus counters/gauges on the router registry.

The loop runs wherever the operator wants: call ``poll_once()`` from
your own scheduler, or ``start(interval)`` for the built-in daemon
thread. Stdlib only; no model state, no device work.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from kubetpu.router.pool import DEAD
from kubetpu.router.server import RouterServer


@dataclass(frozen=True)
class ScalePolicy:
    """The autoscaler's knobs. Thresholds compare against the WORST
    replica (ceilings) / the fleet aggregate (floors) — one degraded
    replica is a capacity problem even when the mean looks fine."""

    min_replicas: int = 1
    max_replicas: int = 4
    up_after: int = 3            # consecutive hot passes before scale-up
    down_after: int = 6          # consecutive cold passes before drain
    cooldown_s: float = 10.0     # quiet time after any scale action
    # hot when ANY of these trips (or the router's SLO fast window burns)
    queue_wait_p99_ms: float = 500.0
    ttft_p50_ms: float = 1000.0
    min_free_page_frac: float = 0.1
    queue_depth: int = 4         # fleet-total queued requests
    # cold when ALL of: queues empty, occupancy under this, not burning
    cold_active_frac: float = 0.25

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        if self.up_after < 1 or self.down_after < 1:
            raise ValueError("up_after/down_after must be >= 1")


class ReplicaAutoscaler:
    """Reconcile the replica count against the federated signals."""

    def __init__(
        self,
        router: RouterServer,
        launcher: Callable[[], str],
        policy: ScalePolicy = ScalePolicy(),
        terminator: Optional[Callable[[str, str], None]] = None,
    ) -> None:
        """*launcher*: boots one replica, returns its URL (raises on
        failure — the pass records the error and retries next time).
        *terminator*: called with (name, url) AFTER a drained victim is
        removed, so the operator can reclaim the process/chips."""
        self.router = router
        self.launcher = launcher
        self.terminator = terminator
        self.policy = policy
        self.events = router.events
        self._lock = threading.Lock()
        self._hot = 0
        self._cold = 0
        self._victim: Optional[str] = None     # name mid-drain
        self._victim_url: Optional[str] = None
        self._cooldown_until = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        reg = router.registry
        self._c_ups = reg.counter(
            "kubetpu_autoscaler_scale_ups_total")
        self._c_downs = reg.counter(
            "kubetpu_autoscaler_scale_downs_total")
        self._c_errors = reg.counter(
            "kubetpu_autoscaler_launch_errors_total")
        self._g_last = reg.gauge(
            "kubetpu_autoscaler_last_scale_ts",
            "wall-clock time of the last completed scale action")
        reg.gauge_fn("kubetpu_autoscaler_replicas",
                     lambda: len(router.pool.names()))
        reg.gauge_fn("kubetpu_autoscaler_hot_passes",
                     lambda: self._hot)
        reg.gauge_fn("kubetpu_autoscaler_cold_passes",
                     lambda: self._cold)

    # -- signals -------------------------------------------------------------

    def signals(self) -> dict:
        """The federated decision inputs, from the pool's ``/load``
        snapshots + the router's SLO engine: worst-replica queue-wait
        p99 and TTFT p50, fleet queue depth, occupancy, the tightest
        pool free-page fraction, and the burn bit."""
        loads = [self.router.pool.snapshot(n)
                 for n in self.router.pool.routable()]
        loads = [ld for ld in loads if ld]
        out = {
            # ALIVE capacity, not registrations: a dead handle must not
            # hold the max_replicas gate shut while the fleet burns
            "replicas": len(self.router.pool.alive()),
            "routable": len(self.router.pool.routable()),
            "burning": self.router._burning(),
            "queue_depth": sum(int(ld.get("queue_depth", 0))
                               for ld in loads),
            "queue_wait_p99_ms": max(
                (float(ld.get("queue_wait_p99_ms", 0.0)) for ld in loads),
                default=0.0),
            "ttft_p50_ms": max(
                (float(ld.get("ttft_p50_ms", 0.0)) for ld in loads),
                default=0.0),
        }
        active = sum(int(ld.get("active_slots", 0)) for ld in loads)
        slots = sum(int(ld.get("n_slots", 0)) for ld in loads)
        out["active_frac"] = (active / slots) if slots else 0.0
        fracs = [int(ld["pages_free"]) / max(1, int(ld["pool_pages"]))
                 for ld in loads
                 if ld.get("pages_free") is not None
                 and ld.get("pool_pages")]
        out["free_page_frac"] = min(fracs) if fracs else 1.0
        return out

    def _hot_cold(self, sig: dict):
        p = self.policy
        hot = (sig["burning"]
               or sig["queue_wait_p99_ms"] > p.queue_wait_p99_ms
               or sig["ttft_p50_ms"] > p.ttft_p50_ms
               or sig["free_page_frac"] < p.min_free_page_frac
               or sig["queue_depth"] >= p.queue_depth)
        cold = (not hot
                and sig["queue_depth"] == 0
                and sig["active_frac"] < p.cold_active_frac)
        return hot, cold

    # -- one reconcile pass --------------------------------------------------

    def poll_once(self) -> dict:
        """One reconcile pass: refresh signals, advance the hysteresis
        counters, maybe act. Returns {signals, hot, cold, action} for
        operators/tests."""
        self.router.pool.refresh(0.0)
        self.router.evaluate_slos(0.0)
        with self._lock:
            cur_victim = self._victim
        # reap DEAD replicas (breaker-confirmed gone): their streams
        # are lost either way, and a dead registration would otherwise
        # pin ring arcs and the max_replicas gate forever. The current
        # drain victim is left for _finish_scale_down, which owns its
        # scale_down event and terminator call.
        for name in self.router.pool.names():
            if name != cur_victim and self.router.pool.state(name) == DEAD:
                self.router.remove_replica(name)
                self.events.emit("reap", replica=name)
        sig = self.signals()
        hot, cold = self._hot_cold(sig)
        p = self.policy
        now = time.monotonic()
        with self._lock:
            self._hot = self._hot + 1 if hot else 0
            self._cold = self._cold + 1 if cold else 0
            hot_n, cold_n = self._hot, self._cold
            victim = self._victim
            in_cooldown = now < self._cooldown_until
        action = None
        if victim is not None:
            # a drain in flight FINISHES regardless of temperature: the
            # victim is already cordoned, leaving it half-drained helps
            # no one. (A fleet gone hot mid-drain scales back up next
            # pass — the counters keep counting.)
            action = self._finish_scale_down(victim)
        elif sig["replicas"] < p.min_replicas:
            # FLOOR healing, before cooldown and without hysteresis: a
            # reaped/crashed fleet below min_replicas produces no hot
            # signals (no traffic -> no latency samples, SLIs absent),
            # so waiting for heat would leave "no routable replica"
            # outages standing forever. A failed launch counts an error
            # and retries next pass.
            action = self._scale_up(sig)
        elif in_cooldown:
            pass
        elif (hot_n >= p.up_after
                and sig["replicas"] < p.max_replicas):
            action = self._scale_up(sig)
        elif (cold_n >= p.down_after
                and sig["routable"] > p.min_replicas):
            action = self._begin_scale_down(sig)
        return {"signals": sig, "hot": hot, "cold": cold,
                "action": action}

    def _scale_up(self, sig: dict) -> Optional[str]:
        try:
            url = self.launcher()
            name = self.router.register_replica(url)
        except Exception as e:  # noqa: BLE001 — record, retry next pass
            self._c_errors.inc()
            self.events.emit("scale_error", error=str(e))
            return None
        self._c_ups.inc()
        self._g_last.set(time.time())
        self.events.emit("scale_up", replica=name, url=url,
                         replicas=sig["replicas"] + 1,
                         reason=self._reason(sig))
        with self._lock:
            self._hot = 0
            self._cooldown_until = time.monotonic() + self.policy.cooldown_s
        return f"scale_up:{name}"

    def _begin_scale_down(self, sig: dict) -> Optional[str]:
        # least-loaded routable victim: fewest active slots, then
        # shallowest queue — the cheapest drain
        names = self.router.pool.routable()
        if len(names) <= self.policy.min_replicas:
            return None

        def load_key(n):
            ld = self.router.pool.snapshot(n) or {}
            return (int(ld.get("active_slots", 0)),
                    int(ld.get("queue_depth", 0)), n)

        victim = min(names, key=load_key)
        url = self.router.pool.url(victim)
        # Round-16: scale-down is migrate -> drain -> remove. The
        # victim's in-flight streams hand off live to the least-loaded
        # SURVIVOR, so removal never waits out a long stream (and the
        # drain-timeout backstop never has to cancel one). With no
        # survivor to take them (shouldn't happen above min_replicas,
        # but stay honest) the drain falls back to waiting.
        survivors = [n for n in names if n != victim]
        target = min(survivors, key=load_key) if survivors else None
        target_url = (self.router.pool.url(target)
                      if target is not None else None)
        if target_url is not None:
            self.events.emit("scale_down_migrate", replica=victim,
                             target=target)
        self.router.pool.drain(victim, migrate_to=target_url,
                               reason="scale_down")
        self.events.emit("drain", replica=victim, reason="scale_down")
        with self._lock:
            self._cold = 0
            self._victim = victim
            self._victim_url = url
        return f"drain:{victim}"

    def _finish_scale_down(self, victim: str) -> Optional[str]:
        if not self.router.pool.drained(victim):
            return None            # still finishing in-flight work
        with self._lock:
            url = self._victim_url
            self._victim = None
            self._victim_url = None
        self.router.remove_replica(victim)
        self._c_downs.inc()
        self._g_last.set(time.time())
        self.events.emit("scale_down", replica=victim,
                         replicas=len(self.router.pool.names()))
        if self.terminator is not None and url is not None:
            try:
                self.terminator(victim, url)
            except Exception as e:  # noqa: BLE001 — reclaim best-effort
                self.events.emit("scale_error", error=str(e))
        with self._lock:
            self._cooldown_until = time.monotonic() + self.policy.cooldown_s
        return f"scale_down:{victim}"

    @staticmethod
    def _reason(sig: dict) -> str:
        if sig["burning"]:
            return "slo_burn"
        if sig["queue_depth"]:
            return "queue_depth"
        if sig["free_page_frac"] < 1.0:
            return "pool_pressure"
        return "latency"

    # -- daemon loop ---------------------------------------------------------

    def start(self, interval: float = 1.0) -> None:
        """Run ``poll_once`` every *interval* seconds on a daemon
        thread until ``shutdown``."""
        self._stop.clear()

        def run():
            while not self._stop.wait(interval):
                try:
                    self.poll_once()
                except Exception as e:  # noqa: BLE001 — the loop survives
                    self._c_errors.inc()
                    self.events.emit("scale_error", error=str(e))

        self._thread = threading.Thread(
            target=run, name="kubetpu-autoscaler", daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
