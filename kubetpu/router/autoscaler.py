"""``ReplicaAutoscaler`` — SLO-driven replica scaling behind the router.

The Round-11 signal layer computes the judgment (burn rates, federated
percentiles, pool pressure); this loop ACTS on it. One reconcile pass
(``poll_once``) reads the federated signals the ISSUE names — worst
replica queue-wait p99 and TTFT p50, pool free-page fraction, the
router's SLO fast-window burn — and folds them into a hot/cold verdict
with HYSTERESIS:

- **scale up** after ``up_after`` CONSECUTIVE hot passes (a single
  slow scrape must not buy hardware): ``launcher()`` is called (the
  operator's replica factory — boots a server, returns its URL) and
  the newcomer registers with the router, earning its ring arcs (which
  remaps only ~1/N prefix buckets — the hashring contract);
- **scale down** is MIGRATE -> DRAIN -> REMOVE (Round-16):
  ``down_after`` consecutive cold passes pick the least-loaded
  routable victim, hand its in-flight streams live to the least-loaded
  ROLE-COMPATIBLE survivor (token-exact slot handoff —
  ``scale_down_migrate`` event), and drain it (routing stops
  immediately). Only when the victim's ``/load`` reads
  drained-and-idle is it removed from the ring and handed to
  ``terminator`` — a scale-down never drops a live stream AND never
  waits out a long one;
- **cooldown** after any action (``cooldown_s``) so a scale event's
  own disruption (warmup, cache cold start) can't trigger the next.

**Round-17, disaggregated fleets:** replicas carry a serving role
(``prefill`` / ``decode`` / ``both``), and the autoscaler reconciles
each role POOL independently from its OWN saturation signals — the
two halves of a disaggregated topology saturate on different things:

- the **prefill** pool is admission-bound: queue-wait p99, TTFT p50,
  fleet queue depth, the router's burn bit;
- the **decode** pool is stream-bound: inter-token latency p99 and the
  pool free-page floor (prompts never queue there — its queue/TTFT
  signals are structurally silent and must not gate scaling);
- ``both`` (colocated) replicas form the legacy pool with the original
  combined criteria — an undecomposed fleet scales exactly as before.

Each pool keeps its own hysteresis counters, cooldown, drain victim and
``ScalePolicy`` (``policies={"prefill": ..., "decode": ...}`` overrides
the shared default per role). ``launcher`` may optionally accept the
pool's role (``launcher(role) -> url``) so a scale-up boots a replica
of the starving kind; a zero-arg launcher keeps working for colocated
fleets.

Every decision is an event (``scale_up`` -> ... -> ``drain`` ->
``scale_down``, each carrying its pool's role) in the router's event
log — the ordering the acceptance test pins — plus counters/gauges on
the router registry.

The loop runs wherever the operator wants: call ``poll_once()`` from
your own scheduler, or ``start(interval)`` for the built-in daemon
thread. Stdlib only; no model state, no device work.
"""

from __future__ import annotations

import inspect
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from kubetpu.router.pool import DEAD, role_compatible
from kubetpu.router.server import RouterServer


@dataclass(frozen=True)
class ScalePolicy:
    """The autoscaler's knobs. Thresholds compare against the WORST
    replica (ceilings) / the fleet aggregate (floors) — one degraded
    replica is a capacity problem even when the mean looks fine."""

    min_replicas: int = 1
    max_replicas: int = 4
    up_after: int = 3            # consecutive hot passes before scale-up
    down_after: int = 6          # consecutive cold passes before drain
    cooldown_s: float = 10.0     # quiet time after any scale action
    # hot when ANY of these trips (or the router's SLO fast window burns)
    queue_wait_p99_ms: float = 500.0
    ttft_p50_ms: float = 1000.0
    min_free_page_frac: float = 0.1
    queue_depth: int = 4         # fleet-total queued requests
    # decode-pool hot ceiling (Round-17): worst replica inter-token
    # latency — the signal a pure-decode pool actually saturates on.
    # ALIGN this with any declared ITL SLO threshold: the router's
    # burn bit is fleet-global and (per the Round-17 spec) drives only
    # the prefill/both pools, so a burning ITL objective TIGHTER than
    # this knob would scale the wrong pool while decode stays put
    itl_p99_ms: float = 250.0
    # cold when ALL of: queues empty, occupancy under this, not burning
    cold_active_frac: float = 0.25
    # Round-18 vChips: the chip share each scale-up boots — passed to
    # ``launcher(role, frac)`` launchers so the autoscaler can scale
    # DENSITY (packed fractional replicas) and not just replica count;
    # 1.0 keeps whole-chip replicas and the legacy launcher shapes
    vchip_frac: float = 1.0
    # Round-20 crash tolerance: when the breaker confirms a replica
    # DEAD and the reap removes it, immediately boot a replacement —
    # bypassing cooldown and hysteresis, which exist to damp LOAD
    # noise (a hard kill is not noise) — as long as the pool stays
    # under max_replicas. The Round-19 peer prefix tier warms the
    # newcomer from the survivors' caches, so it joins warm not cold.
    crash_replace: bool = True

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        if self.up_after < 1 or self.down_after < 1:
            raise ValueError("up_after/down_after must be >= 1")
        if not 0.0 < self.vchip_frac <= 1.0:
            raise ValueError("vchip_frac must be in (0, 1]")


class ReplicaAutoscaler:
    """Reconcile each role pool's replica count against its federated
    signals."""

    def __init__(
        self,
        router: RouterServer,
        launcher: Callable[..., str],
        policy: ScalePolicy = ScalePolicy(),
        terminator: Optional[Callable[[str, str], None]] = None,
        policies: Optional[Dict[str, ScalePolicy]] = None,
    ) -> None:
        """*launcher*: boots one replica, returns its URL (raises on
        failure — the pass records the error and retries next time).
        May accept the pool's role (``launcher(role)``) so a
        disaggregated fleet scales the starving kind, and additionally
        the vChip share (``launcher(role, frac)``, Round-18) so a
        scale-up boots a PACKED fractional replica sized to the pool's
        ``vchip_frac`` policy; zero-arg launchers keep the colocated
        whole-chip behavior — a one-arg launcher must never be handed a
        share it would silently drop. *terminator*: called with
        (name, url) AFTER a drained victim is removed, so the operator
        can reclaim the process/chips. *policies*: per-role
        ``ScalePolicy`` overrides (missing roles use *policy*)."""
        self.router = router
        self.launcher = launcher
        self.terminator = terminator
        self.policy = policy
        self.policies = dict(policies or {})
        try:
            sig = inspect.signature(launcher)
            nargs = 0
            var_positional = False
            frac_capable = False
            for p in sig.parameters.values():
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
                    nargs += 1
                    if nargs == 2:
                        # the share goes to a second positional only when
                        # the launcher clearly declared it: a REQUIRED
                        # second parameter, or one named for the share. A
                        # legacy `launcher(role, port_base=9000)` worked
                        # pre-Round-18 by being called with one arg —
                        # feeding 1.0 into its defaulted extra would
                        # silently misconfigure the replica.
                        frac_capable = (
                            p.default is p.empty
                            or p.name in ("frac", "vchip_frac", "share",
                                          "milli")
                        )
                elif p.kind == p.VAR_POSITIONAL:
                    var_positional = True
            if var_positional and nargs < 1:
                # a bare *args launcher keeps the legacy one-arg call
                # (it predates the share; silently handing it a second
                # positional would break `def launcher(*a): boot(*a)`
                # wrappers around one-parameter factories) — declare
                # (role, frac) explicitly to receive the share
                nargs = 1
            self._launcher_nargs = (
                2 if nargs >= 2 and frac_capable else min(nargs, 1))
        except (TypeError, ValueError):
            self._launcher_nargs = 0
        self.events = router.events
        self._lock = threading.Lock()
        self._known_pools: set = set()
        self._hot: Dict[str, int] = {}
        self._cold: Dict[str, int] = {}
        self._victim: Dict[str, str] = {}        # pool -> name mid-drain
        self._victim_url: Dict[str, str] = {}
        self._cooldown_until: Dict[str, float] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        reg = router.registry
        self._c_ups = reg.counter(
            "kubetpu_autoscaler_scale_ups_total")
        self._c_downs = reg.counter(
            "kubetpu_autoscaler_scale_downs_total")
        self._c_errors = reg.counter(
            "kubetpu_autoscaler_launch_errors_total")
        self._g_last = reg.gauge(
            "kubetpu_autoscaler_last_scale_ts",
            "wall-clock time of the last completed scale action")
        reg.gauge_fn("kubetpu_autoscaler_replicas",
                     lambda: len(router.pool.names()))
        # collect-time reads under the lock: poll_once grows these
        # dicts when a pool first appears, and a concurrent scrape
        # iterating bare .values() would raise dictionary-changed-size
        reg.gauge_fn("kubetpu_autoscaler_hot_passes",
                     lambda: self._peak(self._hot))
        reg.gauge_fn("kubetpu_autoscaler_cold_passes",
                     lambda: self._peak(self._cold))

    def _peak(self, counters: Dict[str, int]) -> int:
        with self._lock:
            return max(counters.values(), default=0)

    # -- pools ---------------------------------------------------------------

    def _pool_keys(self) -> List[str]:
        """The role pools to reconcile this pass: one per distinct role
        among ALIVE replicas (every replica belongs to exactly its own
        role's pool), any pool whose drain victim is still resolving,
        every role with a declared per-role policy, and every pool
        whose last replica this autoscaler itself REAPED
        (``_known_pools``) — a dedicated pool that crashed away must
        keep reconciling, or its ``min_replicas`` floor-heal could
        never fire and a disagg fleet that lost its whole decode pool
        would silently degrade to colocated forever. Pools emptied by
        OPERATOR removals never enter ``_known_pools`` (re-colocating
        a fleet must not fight the autoscaler), and an entry is
        discharged the moment the pool has an alive member again. An
        empty fleet with no history reads as the legacy ``both`` pool
        so floor-healing has somewhere to scale."""
        pool = self.router.pool
        alive = {pool.role(n) or "both" for n in pool.alive()}
        with self._lock:
            self._known_pools -= alive
            keys = (alive | set(self._victim) | set(self.policies)
                    | self._known_pools)
        return sorted(keys) if keys else ["both"]

    def _policy_for(self, key: str) -> ScalePolicy:
        return self.policies.get(key, self.policy)

    def _pool_names(self, key: str, names: List[str]) -> List[str]:
        pool = self.router.pool
        return [n for n in names if (pool.role(n) or "both") == key]

    # -- signals -------------------------------------------------------------

    def signals(self, role: Optional[str] = None) -> dict:
        """The federated decision inputs for one role pool (None = the
        whole fleet), from the pool's ``/load`` snapshots + the
        router's SLO engine: worst-replica queue-wait p99, TTFT p50 and
        ITL p99, fleet queue depth, occupancy, the tightest pool
        free-page fraction, and the burn bit."""
        pool = self.router.pool
        alive = pool.alive()
        routable = pool.routable()
        if role is not None:
            alive = self._pool_names(role, alive)
            routable = self._pool_names(role, routable)
        loads = [pool.snapshot(n) for n in routable]
        loads = [ld for ld in loads if ld]
        out = {
            # ALIVE capacity, not registrations: a dead handle must not
            # hold the max_replicas gate shut while the fleet burns
            "replicas": len(alive),
            "routable": len(routable),
            "burning": self.router._burning(),
            "queue_depth": sum(int(ld.get("queue_depth", 0))
                               for ld in loads),
            "queue_wait_p99_ms": max(
                (float(ld.get("queue_wait_p99_ms", 0.0)) for ld in loads),
                default=0.0),
            "ttft_p50_ms": max(
                (float(ld.get("ttft_p50_ms", 0.0)) for ld in loads),
                default=0.0),
            "itl_p99_ms": max(
                (float(ld.get("itl_p99_ms", 0.0)) for ld in loads),
                default=0.0),
        }
        active = sum(int(ld.get("active_slots", 0)) for ld in loads)
        slots = sum(int(ld.get("n_slots", 0)) for ld in loads)
        out["active_frac"] = (active / slots) if slots else 0.0
        fracs = [int(ld["pages_free"]) / max(1, int(ld["pool_pages"]))
                 for ld in loads
                 if ld.get("pages_free") is not None
                 and ld.get("pool_pages")]
        out["free_page_frac"] = min(fracs) if fracs else 1.0
        return out

    def _hot_cold(self, key: str, sig: dict):
        """Per-pool temperature (Round-17): each role saturates on its
        own signals — judging a decode pool by queue depth (always 0)
        or a prefill pool by ITL (structurally tiny: one same-step
        sample per stream before handoff) would read permanently
        cold/hot regardless of real load."""
        p = self._policy_for(key)
        if key == "prefill":
            hot = (sig["burning"]
                   or sig["queue_wait_p99_ms"] > p.queue_wait_p99_ms
                   or sig["ttft_p50_ms"] > p.ttft_p50_ms
                   or sig["queue_depth"] >= p.queue_depth)
        elif key == "decode":
            hot = (sig["itl_p99_ms"] > p.itl_p99_ms
                   or sig["free_page_frac"] < p.min_free_page_frac)
        else:
            hot = (sig["burning"]
                   or sig["queue_wait_p99_ms"] > p.queue_wait_p99_ms
                   or sig["ttft_p50_ms"] > p.ttft_p50_ms
                   or sig["free_page_frac"] < p.min_free_page_frac
                   or sig["queue_depth"] >= p.queue_depth)
        cold = (not hot
                and sig["queue_depth"] == 0
                and sig["active_frac"] < p.cold_active_frac)
        return hot, cold

    # -- one reconcile pass --------------------------------------------------

    def poll_once(self) -> dict:
        """One reconcile pass over every role pool: refresh signals,
        advance each pool's hysteresis counters, maybe act. Returns
        {signals, pools, hot, cold, action, actions} — ``signals`` /
        ``hot`` / ``cold`` describe the FIRST pool (the whole fleet
        when colocated, the legacy shape), ``pools`` carries every
        pool's verdict, ``action`` the first action taken (``actions``
        all of them: independent pools may both act in one pass)."""
        self.router.pool.refresh(0.0)
        self.router.evaluate_slos(0.0)
        actions: List[str] = []
        with self._lock:
            cur_victims = set(self._victim.values())
        # reap DEAD replicas (breaker-confirmed gone): their streams
        # are lost either way, and a dead registration would otherwise
        # pin ring arcs and the max_replicas gate forever. A current
        # drain victim is left for _finish_scale_down, which owns its
        # scale_down event and terminator call.
        for name in self.router.pool.names():
            if (name not in cur_victims
                    and self.router.pool.state(name) == DEAD):
                # remember the reaped replica's pool: if this was its
                # last member, the pool must keep reconciling so the
                # floor-heal can restore it (crash-reap only — operator
                # removals go through remove_replica directly and must
                # not be fought)
                role = self.router.pool.role(name) or "both"
                with self._lock:
                    self._known_pools.add(role)
                self.router.remove_replica(name)
                self.events.emit("reap", replica=name)
                replaced = self._crash_replace(role, reaped=name)
                if replaced is not None:
                    actions.append(replaced)
        pools: Dict[str, dict] = {}
        now = time.monotonic()
        keys = self._pool_keys()
        for key in keys:
            p = self._policy_for(key)
            sig = self.signals(role=key)
            hot, cold = self._hot_cold(key, sig)
            with self._lock:
                self._hot[key] = self._hot.get(key, 0) + 1 if hot else 0
                self._cold[key] = (self._cold.get(key, 0) + 1
                                   if cold else 0)
                hot_n, cold_n = self._hot[key], self._cold[key]
                victim = self._victim.get(key)
                in_cooldown = now < self._cooldown_until.get(key, 0.0)
            action = None
            if victim is not None:
                # a drain in flight FINISHES regardless of temperature:
                # the victim is already cordoned, leaving it
                # half-drained helps no one. (A pool gone hot mid-drain
                # scales back up next pass — the counters keep
                # counting.)
                action = self._finish_scale_down(key, victim)
            elif sig["replicas"] < p.min_replicas:
                # FLOOR healing, before cooldown and without
                # hysteresis: a reaped/crashed pool below min_replicas
                # produces no hot signals (no traffic -> no latency
                # samples, SLIs absent), so waiting for heat would
                # leave "no routable replica" outages standing forever.
                # A failed launch counts an error and retries next
                # pass.
                action = self._scale_up(key, sig)
            elif in_cooldown:
                pass
            elif (hot_n >= p.up_after
                    and sig["replicas"] < p.max_replicas):
                action = self._scale_up(key, sig)
            elif (cold_n >= p.down_after
                    and sig["routable"] > p.min_replicas):
                action = self._begin_scale_down(key, sig)
            pools[key] = {"signals": sig, "hot": hot, "cold": cold,
                          "action": action}
            if action is not None:
                actions.append(action)
        first = pools[keys[0]] if pools else {
            "signals": {}, "hot": False, "cold": False}
        return {"signals": first["signals"], "hot": first["hot"],
                "cold": first["cold"], "pools": pools,
                "action": actions[0] if actions else None,
                "actions": actions}

    def _crash_replace(self, key: str, reaped: str) -> Optional[str]:
        """Reap follow-up (Round-20): a breaker-confirmed crash just
        took a replica out of pool *key* — boot its replacement NOW
        instead of waiting for the pool to reheat through hysteresis
        or fall under the ``min_replicas`` floor. Bounded by
        ``max_replicas``; a failed launch counts a ``scale_error`` and
        the pool re-heals through the usual floor/heat paths. Returns
        the ``scale_up:`` action so the poll reports it."""
        p = self._policy_for(key)
        if not p.crash_replace:
            return None
        sig = self.signals(role=key)
        if sig["replicas"] >= p.max_replicas:
            return None
        action = self._scale_up(key, sig)
        if action is not None:
            self.events.emit("crash_replace", role=key, reaped=reaped,
                             replacement=action.split(":", 1)[1])
        return action

    def _scale_up(self, key: str, sig: dict) -> Optional[str]:
        if key not in ("both", None) and self._launcher_nargs < 1:
            # a zero-arg launcher cannot boot a DEDICATED role replica:
            # launching anyway would register a "both" node, leave this
            # pool at zero, and the floor-heal would buy hardware every
            # pass forever — fail loudly instead
            self._c_errors.inc()
            self.events.emit(
                "scale_error", role=key,
                error=f"pool {key!r} needs replicas but the launcher "
                      f"takes no role — pass launcher(role)")
            return None
        frac = self._policy_for(key).vchip_frac
        if frac < 1.0 and self._launcher_nargs < 2:
            # Round-18: a fractional policy with a launcher that cannot
            # receive the share would silently boot WHOLE-chip replicas
            # — the fleet would look packed in config while stranding
            # 1-frac of every chip. Fail loudly, like the role case.
            self._c_errors.inc()
            self.events.emit(
                "scale_error", role=key,
                error=f"pool {key!r} wants vchip_frac={frac} but the "
                      f"launcher takes no share — pass "
                      f"launcher(role, frac)")
            return None
        try:
            if self._launcher_nargs >= 2:
                url = self.launcher(key, frac)
            elif self._launcher_nargs == 1:
                url = self.launcher(key)
            else:
                url = self.launcher()
            name = self.router.register_replica(url)
            got = self.router.pool.role(name) or "both"
            if key not in ("both", None) and got != key:
                # the launcher booted the WRONG kind: keeping it would
                # grow the fleet while this pool stays empty (the
                # floor-heal would then launch again, unbounded) —
                # treat it as a failed launch and roll it back
                self.router.remove_replica(name)
                if self.terminator is not None:
                    self.terminator(name, url)
                raise RuntimeError(
                    f"launcher({key!r}) returned a replica with role "
                    f"{got!r}")
        except Exception as e:  # noqa: BLE001 — record, retry next pass
            self._c_errors.inc()
            self.events.emit("scale_error", error=str(e), role=key)
            return None
        self._c_ups.inc()
        self._g_last.set(time.time())
        self.events.emit("scale_up", replica=name, url=url, role=key,
                         replicas=sig["replicas"] + 1,
                         reason=self._reason(key, sig))
        with self._lock:
            self._hot[key] = 0
            self._cooldown_until[key] = (time.monotonic()
                                         + self._policy_for(key).cooldown_s)
        return f"scale_up:{name}"

    def _begin_scale_down(self, key: str, sig: dict) -> Optional[str]:
        # least-loaded routable victim IN THIS POOL: fewest active
        # slots, then shallowest queue — the cheapest drain
        names = self._pool_names(key, self.router.pool.routable())
        if len(names) <= self._policy_for(key).min_replicas:
            return None

        def load_key(n):
            ld = self.router.pool.snapshot(n) or {}
            return (int(ld.get("active_slots", 0)),
                    int(ld.get("queue_depth", 0)), n)

        victim = min(names, key=load_key)
        url = self.router.pool.url(victim)
        # Round-16: scale-down is migrate -> drain -> remove. The
        # victim's in-flight streams hand off live to the least-loaded
        # ROLE-COMPATIBLE survivor (Round-17: a prefill victim's
        # streams go to another prefill or "both" replica, never a
        # decode-only one), so removal never waits out a long stream
        # (and the drain-timeout backstop never has to cancel one).
        # With no compatible survivor the drain falls back to waiting.
        pool = self.router.pool
        survivors = [n for n in pool.routable()
                     if n != victim
                     and role_compatible(pool.role(victim),
                                         pool.role(n))]
        target = min(survivors, key=load_key) if survivors else None
        target_url = pool.url(target) if target is not None else None
        if target_url is not None:
            self.events.emit("scale_down_migrate", replica=victim,
                             target=target, role=key)
        pool.drain(victim, migrate_to=target_url, reason="scale_down")
        self.events.emit("drain", replica=victim, reason="scale_down",
                         role=key)
        with self._lock:
            self._cold[key] = 0
            self._victim[key] = victim
            self._victim_url[key] = url
        return f"drain:{victim}"

    def _finish_scale_down(self, key: str, victim: str) -> Optional[str]:
        if not self.router.pool.drained(victim):
            return None            # still finishing in-flight work
        with self._lock:
            url = self._victim_url.pop(key, None)
            self._victim.pop(key, None)
        self.router.remove_replica(victim)
        self._c_downs.inc()
        self._g_last.set(time.time())
        self.events.emit("scale_down", replica=victim, role=key,
                         replicas=len(self.router.pool.names()))
        if self.terminator is not None and url is not None:
            try:
                self.terminator(victim, url)
            except Exception as e:  # noqa: BLE001 — reclaim best-effort
                self.events.emit("scale_error", error=str(e))
        with self._lock:
            self._cooldown_until[key] = (time.monotonic()
                                         + self._policy_for(key).cooldown_s)
        return f"scale_down:{victim}"

    def _reason(self, key: str, sig: dict) -> str:
        p = self._policy_for(key)
        if key == "decode":
            if sig["itl_p99_ms"] > p.itl_p99_ms:
                return "itl"
            return "pool_pressure"
        if sig["burning"]:
            return "slo_burn"
        if sig["queue_depth"]:
            return "queue_depth"
        if sig["free_page_frac"] < 1.0:
            return "pool_pressure"
        return "latency"

    # -- daemon loop ---------------------------------------------------------

    def start(self, interval: float = 1.0) -> None:
        """Run ``poll_once`` every *interval* seconds on a daemon
        thread until ``shutdown``."""
        self._stop.clear()

        def run():
            while not self._stop.wait(interval):
                try:
                    self.poll_once()
                except Exception as e:  # noqa: BLE001 — the loop survives
                    self._c_errors.inc()
                    self.events.emit("scale_error", error=str(e))

        self._thread = threading.Thread(
            target=run, name="kubetpu-autoscaler", daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
