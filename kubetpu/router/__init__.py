"""``kubetpu.router`` — the prefix-affinity data plane (Round-14).

Three cooperating parts in front of N serving replicas:

- :mod:`kubetpu.router.server` — ``RouterServer``, the HTTP request
  router: consistent-hash on the tokenized prefix head
  (:mod:`kubetpu.router.hashring`), load-based fallback from each
  replica's ``/load`` snapshot, SLO-class admission (shed / queue while
  the fast window burns);
- :mod:`kubetpu.router.replica` / :mod:`kubetpu.router.pool` —
  ``ReplicaServer`` (a slot server's wire surface: idempotent
  ``POST /generate``, graceful drain) and ``ReplicaPool``
  (registration, breaker health, snapshots, federation);
- :mod:`kubetpu.router.autoscaler` — ``ReplicaAutoscaler``, the
  reconcile loop scaling the replica set from the federated signals
  with hysteresis and migrate-then-drain scale-down;
- :mod:`kubetpu.router.migration` — the snapshot wire codec for live
  KV migration (Round-16): meta + chunked blob encoding for the
  ``POST /migrate_in`` transfer, plus the page-SPAN naming the
  Round-17 disaggregated prefill->decode handoff streams over the
  same phases.

Round-17 layers DISAGGREGATED serving on top: replicas carry a role
(``prefill`` / ``decode`` / ``both`` — ``ReplicaServer(role=...)``),
the router places prompts on the prefill pool by affinity and picks a
decode target by load at admission, prefill replicas stream completed
KV spans to their decode target while later chunks still compute, and
the autoscaler reconciles each role pool independently. All-"both"
fleets behave exactly as before — the topology is opt-in.

Deliberately light: stdlib + ``kubetpu.obs`` + ``kubetpu.wire`` only —
importing the router NEVER imports jax (the router process holds no
model state and routes for accelerator fleets it doesn't run on).
"""

from kubetpu.router.autoscaler import ReplicaAutoscaler, ScalePolicy
from kubetpu.router.hashring import HashRing, prefix_head_key
from kubetpu.router.migration import decode_snapshot, encode_snapshot
from kubetpu.router.pool import ReplicaPool, role_compatible
from kubetpu.router.replica import ReplicaServer
from kubetpu.router.server import RouterServer

__all__ = [
    "HashRing",
    "ReplicaAutoscaler",
    "ReplicaPool",
    "ReplicaServer",
    "RouterServer",
    "ScalePolicy",
    "decode_snapshot",
    "encode_snapshot",
    "prefix_head_key",
    "role_compatible",
]
