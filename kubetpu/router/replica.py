"""``ReplicaServer`` — one serving replica's wire surface for the data
plane.

The slot servers (``DecodeServer`` / ``PagedDecodeServer`` and friends)
are in-process objects; ``obs.exporter.MetricsServer`` gave them a
read-only scrape surface, but nothing could *send them work* over the
wire. This server is that missing half — the leg the affinity router
(``kubetpu.router.server``) POSTs to:

    GET  /healthz    -> {"ok": true, "replica": <name>,
                         "draining": <bool>}  (open, liveness)
    GET  /load       -> ``server.load_info()`` + draining flag: the
                        CHEAP routing signal (queue depth, active
                        slots, pool free pages, prefix-cache hit rate)
                        the router polls instead of parsing /metrics
    GET  /metrics    -> Prometheus text of the serving registry
                        (latency summaries, pool gauges, prefix
                        counters, and this server's replica counters)
    GET  /slo        -> the replica's declared-SLO verdicts (JSON)
    GET  /events     -> replica + serving event logs, merged JSONL
    GET  /trace/<id> -> finished spans of one trace (the replica leg of
                        a stitched router trace)
    POST /generate   -> {"prompt": [ids], "sampling": {...}?,
                        "timeout": s?} -> {"rid", "tokens", "emitted"}
                        — synchronous generate: enqueue, wait for the
                        step loop to finish the request, return
                        prompt + emitted tokens. A stream that MIGRATED
                        away mid-generate answers 409 with the new
                        owner ({"migrated": {replica, rid, epoch}}) so
                        the router can re-pin and retry there
    POST /drain      -> stop accepting generates (503); in-flight
                        requests run to completion — or, with
                        {"migrate_to": url}, are handed off live and
                        the drain completes immediately
    POST /migrate_out-> {"target": url, "reason"?, "wait"?} — snapshot
                        every migratable stream and hand it to the
                        target replica (the breaker-suspect and
                        drain-escalation leg)
    POST /migrate_in -> the chunked snapshot transfer (Round-16):
                        phase "begin" (meta + chunk count) -> "chunk"*N
                        (base64 blob slices) -> "commit" (restore +
                        adoption). Every phase POST carries an
                        Idempotency-Key derived from the stream's
                        (origin, rid, epoch), so a lost response
                        REPLAYS — a retry can never double-restore;
                        the commit additionally EPOCH-FENCES per
                        (origin, rid): a stale or duplicate handoff
                        generation is refused 409, keeping at most one
                        copy of a stream active fleet-wide

Robustness (the Round-7 contract, uniformly):

- **idempotent generate**: a ``Idempotency-Key``-carrying POST is
  deduped through a bounded replay window (``run_idempotent``). A
  router retry whose first response was truncated mid-write gets the
  committed tokens REPLAYED — never a second admission, so a lost
  response can never double-allocate slots/pool pages (pinned by
  ``make router-check`` under injected partial faults);
- **graceful drain**: ``drain()`` refuses NEW generates with 503 while
  requests already admitted (or waiting on the handler) complete —
  the autoscaler's scale-down path depends on this (drain first,
  remove only once ``/load`` reads idle). ``drain(migrate_to=url)``
  upgrades the wait to a LIVE HANDOFF: every in-flight stream
  snapshots to the target token-exactly and the drain completes as
  fast as the wire, not as slow as the longest stream.
  ``drain_timeout_s`` bounds the no-migration wait: past it, remaining
  streams either escalate to migration (a target was named) or cancel
  with a ``drain_timeout`` event — scale-down never wedges behind one
  long-max_tokens stream;
- **at-most-one-active migration**: the source retires a migrated slot
  only after the target's commit-ack; an AMBIGUOUS outcome (transport
  dead past the retry budget) finishes the stream as migrated rather
  than resuming — the target may have committed, and a resumed copy
  would double-run the stream. Only a DEFINITIVE refusal (an HTTP
  error answer) unfreezes and resumes locally;
- **fault injection**: ``faults=FaultInjector(...)`` chaos-tests the
  surface like every other wire server.

Threading: the slot servers are NOT thread-safe, so one condition
variable serializes everything that touches the serving object — the
background step loop (``_poll_loop``: step while work exists, sleep
while idle) and the handler-side enqueue/result reads. Handlers block
on the condition between polls, so a finishing request wakes its waiter
within one step. This is the honest single-replica spelling: the
serving hot loop already runs one step at a time; the lock adds a
handler's enqueue (host-side bookkeeping, microseconds) to that serial
order, never a device wait.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import uuid
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from kubetpu.api import utils
from kubetpu.obs import trace as obs_trace
from kubetpu.obs.events import EventLog, merge_events
from kubetpu.router.migration import (
    DEFAULT_CHUNK_BYTES,
    assemble_spans,
    blob_chunks,
    chunk_b64,
    chunk_unb64,
    decode_snapshot,
    decode_span_payload,
    encode_snapshot,
    encode_span_payload,
    span_name,
)
from kubetpu.wire.httpcommon import (
    IdempotencyCache,
    InflightTracker,
    RetryPolicy,
    check_bearer,
    handle_guarded,
    request_json,
    run_idempotent,
    serve_events_jsonl,
    write_json,
    write_text,
)

DEFAULT_GENERATE_TIMEOUT = 30.0
DEFAULT_MIGRATE_TIMEOUT = 20.0
# staging slots for inbound chunked transfers: stale entries (a source
# that died mid-ship) are reaped after this many seconds
MIGRATE_STAGING_TTL = 60.0


class ReplicaServer:
    """Serve one slot server (``SlotServerBase`` contract) to the
    router data plane."""

    def __init__(
        self,
        server,
        name: str,
        host: str = "127.0.0.1",
        port: int = 0,
        token: "str | None" = None,
        faults=None,
        idem_window: float = 300.0,
        idle_wait: float = 0.005,
        drain_timeout_s: Optional[float] = None,
        migrate_timeout: float = DEFAULT_MIGRATE_TIMEOUT,
        migrate_chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        role: str = "both",
        handoff_workers: int = 2,
    ) -> None:
        """*server*: the serving object (enqueue/step/finished/
        pop_result/load_info — ``SlotServerBase`` and every subclass).
        *idle_wait*: step-loop sleep while the server is idle (bounds
        enqueue-to-first-step latency when work arrives).
        *drain_timeout_s*: bound on a no-migration drain's wait for
        natural stream end — past it, remaining streams escalate to
        migration (when a target was named) or cancel with a
        ``drain_timeout`` event, so scale-down never wedges behind one
        long-max_tokens stream. None = wait forever (the pre-Round-16
        behavior).
        *role* (Round-17 disaggregated serving): ``"prefill"`` makes
        this replica a PREFILL worker — a routed generate carrying a
        ``decode_target`` admits + chunk-prefills here, STREAMS its
        completed page-aligned KV spans to that decode replica while
        later chunks are still computing, and hands the stream off on
        first token (the decode replica emits every token). ``"decode"``
        advertises a decode worker (the router stops sending it fresh
        prompts); ``"both"`` (default) is today's colocated behavior —
        the topology is opt-in, and a role is ADVISORY for routing:
        every replica remains a full server (a refused handoff resumes
        locally)."""
        if role not in ("prefill", "decode", "both"):
            raise ValueError("role must be 'prefill', 'decode' or 'both'")
        self.role = role
        if int(handoff_workers) < 1:
            raise ValueError("handoff_workers must be >= 1")
        self.handoff_workers = int(handoff_workers)
        self.server = server
        self.name = name
        # Round-20 boot-nonce fencing: a fresh identity every process
        # boot, advertised in /healthz and /load. The pool compares it
        # across probes — a same-name replica answering with a NEW nonce
        # is a hard-killed-and-restarted (cache-wiped) process, and the
        # router unpins its mid-stream rids for re-drive on survivors.
        self.boot_nonce = uuid.uuid4().hex
        self.token = token or None
        self.faults = faults
        self.idem = IdempotencyCache(ttl=idem_window)
        self.obs_component = f"replica:{name}"
        self.events = EventLog(component=self.obs_component)
        self.draining = False
        self._inflight = InflightTracker()
        self._cv = threading.Condition()
        self._running = False
        self._idle_wait = float(idle_wait)
        if drain_timeout_s is not None and drain_timeout_s < 0:
            raise ValueError("drain_timeout_s must be >= 0 (None = wait)")
        self.drain_timeout_s = drain_timeout_s
        self.migrate_timeout = float(migrate_timeout)
        if int(migrate_chunk_bytes) <= 0:
            raise ValueError("migrate_chunk_bytes must be positive")
        self.migrate_chunk_bytes = int(migrate_chunk_bytes)
        # -- live-migration state (all under self._cv: the handlers, the
        # step loop and the drain-migrate thread share it):
        # rid -> the generate leg's Idempotency-Key (shipped in the
        # snapshot meta so the TARGET can adopt a router retry of the
        # same logical request into the restored stream)
        self._gen_keys: dict = {}
        # gen key -> restored local rid, installed at migrate-in commit
        # and consumed by the first /generate carrying that key
        # (bounded: an orphaned handoff whose retry never arrives must
        # not leak an entry per stream forever)
        self._adopted: "OrderedDict[str, int]" = OrderedDict()
        # gen key -> migrated-away info: a retry of a migrated request
        # must deterministically re-learn the new owner (409), never
        # re-admit here (run_idempotent only replays 200s)
        self._migrated_keys: "OrderedDict[str, dict]" = OrderedDict()
        # inbound chunked transfers: (origin, rid, epoch) -> staging
        self._mig_staging: dict = {}
        # the EPOCH FENCE: (origin, rid) -> highest committed epoch; a
        # commit at <= that epoch is a duplicate/stale handoff and is
        # refused — at most one copy of a stream ever goes active
        self._mig_epochs: "OrderedDict[tuple, int]" = OrderedDict()
        self._drain_migrate: Optional[str] = None
        self._drain_deadline: Optional[float] = None
        self._drain_thread: Optional[threading.Thread] = None
        # -- Round-17 disaggregated handoffs (prefill role only): rid ->
        # streaming-transfer state machine, driven by the handoff loop
        # thread; all mutation under self._cv
        self._handoffs: dict = {}
        self._handoff_thread: Optional[threading.Thread] = None
        # the pipelining proof: KV bytes shipped BEFORE the prefill
        # finished vs total handoff bytes (gauge below)
        self._handoff_early_bytes = 0
        self._handoff_bytes = 0
        # role is a federatable fact: the router's cli summary counts
        # per-role replicas from this series (value is always 1)
        self.server.obs.gauge("kubetpu_serving_role", role=role).set(1.0)
        self.server.obs.gauge_fn("kubetpu_handoffs_inflight",
                                 lambda: len(self._handoffs))
        self.server.obs.gauge_fn(
            "kubetpu_handoff_overlap_frac",
            lambda: (self._handoff_early_bytes / self._handoff_bytes
                     if self._handoff_bytes else 0.0))
        # replica wire counters land on the SERVING registry so one
        # /metrics scrape carries both (the router federates it whole)
        for key in ("requests", "replays", "errors", "adopted"):
            # key ranges over the fixed literal tuple above — KTP004's
            # bounded-f-string proof expands and validates every name
            self.server.obs.counter(f"kubetpu_replica_generate_{key}_total")
        replica = self

        def bump(key: str) -> None:
            # callers pass literals from the pre-registered set above
            # ktlint: disable=KTP004
            replica.server.obs.counter(
                f"kubetpu_replica_generate_{key}_total").inc()

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 (stdlib name)
                utils.logf(5, "replica %s: " + fmt, replica.name, *args)

            def _authorized(self) -> bool:
                if check_bearer(self.headers, replica.token):
                    return True
                write_json(self, 401,
                           {"error": "missing or invalid bearer token"})
                return False

            def do_GET(self):  # noqa: N802
                handle_guarded(replica, self, self._do_get)

            def _do_get(self):
                path = self.path.split("?")[0]
                if path == "/healthz":
                    write_json(self, 200, {
                        "ok": True,
                        "replica": replica.name,
                        "role": replica.role,
                        "draining": replica.draining,
                        "boot_nonce": replica.boot_nonce,
                    })
                elif not self._authorized():
                    pass  # 401 already sent
                elif path == "/load":
                    write_json(self, 200, replica.load())
                elif path == "/metrics":
                    write_text(self, 200, replica.server.metrics_text())
                elif path == "/slo":
                    slo = getattr(replica.server, "slo", None)
                    write_json(self, 200, {
                        "replica": replica.name,
                        "results": slo.results() if slo is not None else {},
                    })
                elif path == "/events":
                    serve_events_jsonl(self, replica.render_events)
                elif path.startswith("/trace/"):
                    tid = path[len("/trace/"):]
                    write_json(self, 200, {
                        "trace": tid,
                        "spans": obs_trace.tracer().spans(tid),
                    })
                else:
                    write_json(self, 404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802
                handle_guarded(replica, self, self._do_post)

            def _do_post(self):
                if not self._authorized():
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                except ValueError:
                    write_json(self, 400, {"error": "body is not JSON"})
                    return
                if self.path == "/drain":
                    replica.drain(migrate_to=req.get("migrate_to"),
                                  reason=req.get("reason") or "drain")
                    write_json(self, 200, {"draining": True})
                    return
                if self.path == "/migrate_out":
                    write_json(self, *replica._migrate_out(req))
                    return
                if self.path == "/migrate_in":
                    run_idempotent(
                        self, replica.idem,
                        self.headers.get("Idempotency-Key"),
                        lambda: replica._migrate_in(req),
                    )
                    return
                if self.path == "/prefix_fetch":
                    run_idempotent(
                        self, replica.idem,
                        self.headers.get("Idempotency-Key"),
                        lambda: replica._prefix_fetch(req),
                    )
                    return
                if self.path == "/adapters":
                    run_idempotent(
                        self, replica.idem,
                        self.headers.get("Idempotency-Key"),
                        lambda: replica._adapters(req),
                    )
                    return
                if self.path != "/generate":
                    write_json(self, 404, {"error": f"no route {self.path}"})
                    return

                def replayed():
                    bump("replays")
                    replica.events.emit("generate_replay")

                key = self.headers.get("Idempotency-Key")
                run_idempotent(
                    self, replica.idem, key,
                    lambda: replica._generate(req, key=key),
                    on_replay=replayed,
                )

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None
        self._loop_thread: Optional[threading.Thread] = None

    # -- the generate leg ----------------------------------------------------

    def _generate(self, req: dict, key: Optional[str] = None):
        """One generate execution -> (code, obj); runs on the handler
        thread under ``run_idempotent`` (200 commits into the replay
        window, anything else aborts so a retry re-executes). The
        draining refusal lives HERE, after the replay lookup: a keyed
        retry of an already-committed generate must get its replay even
        mid-drain (replaying mutates nothing). Round-16 additions: a
        keyed retry of a request that MIGRATED away deterministically
        answers 409 with the new owner (never re-admits here), and a
        keyed request whose stream migrated IN is ADOPTED — attached to
        the restored stream instead of admitted fresh (adoption works
        mid-drain too: attaching mutates nothing new)."""
        deadline = time.monotonic() + float(
            req.get("timeout") or DEFAULT_GENERATE_TIMEOUT)
        prompt = req.get("prompt")
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) for t in prompt)):
            return 400, {"error": "prompt must be a non-empty list of "
                                  "token ids"}
        # Round-19 peer prefix tier: before taking the serving lock for
        # admission, try to pull this prompt's cached KV span from the
        # router-named peer (the ring's previous preference owner). The
        # HTTP leg runs OUTSIDE the condition — a slow or dark peer must
        # never stall the step loop — and any failure degrades to cold
        # prefill, so the admission below is untouched either way.
        self._maybe_peer_prefetch(req, prompt, key)
        with self._cv:
            gone = self._migrated_keys.get(key) if key else None
            if gone is not None:
                return 409, {"error": "request migrated",
                             "migrated": dict(gone)}
            adopted = self._adopted.pop(key, None) if key else None
            if adopted is None and key:
                # a retry of a request still LIVE here — its earlier
                # handler timed out (e.g. while the stream was frozen
                # mid-handoff) and run_idempotent aborted the entry, so
                # a naive path would re-ADMIT the same logical request
                # next to its own live stream. Re-attach instead.
                adopted = next(
                    (r for r, k in self._gen_keys.items()
                     if k == key and not self.server.finished(r)), None)
            if adopted is not None:
                rid = adopted
                self.server.obs.counter(
                    "kubetpu_replica_generate_adopted_total",
                    "router retries attached to a migrated-in stream "
                    "instead of admitted fresh").inc()
                self.events.emit("generate_adopt", rid=rid)
            else:
                if self.draining:
                    return 503, {"error": "replica is draining"}
                if not self._running:
                    return 503, {"error": "replica step loop is not "
                                          "running"}
                self.events.emit("generate", prompt_tokens=len(prompt))
                # Round-22 multi-tenant rider: a routed generate may name
                # its adapter (resident name or stack index). Refused
                # up-front on single-tenant servers — a silent drop would
                # serve the base model to a tenant expecting their
                # adapter.
                extra = {}
                if req.get("adapter") is not None:
                    if not hasattr(self.server, "lora_stack"):
                        return 400, {"error": "replica does not serve "
                                              "multi-LoRA"}
                    extra["adapter"] = req["adapter"]
                try:
                    rid = self.server.enqueue(prompt,
                                              sampling=req.get("sampling"),
                                              **extra)
                except ValueError as e:
                    return 400, {"error": str(e)}
                except Exception as e:  # noqa: BLE001 — report, stay up
                    self.server.obs.counter(
                        "kubetpu_replica_generate_errors_total").inc()
                    return 500, {"error": str(e)}
                self.server.obs.counter(
                    "kubetpu_replica_generate_requests_total").inc()
                if key:
                    self._gen_keys[rid] = key
                    self._gc_gen_keys_locked()
                # Round-17: a routed prompt naming a decode target on a
                # PREFILL replica registers a streaming handoff — the
                # handoff loop begins shipping completed KV spans while
                # later prefill chunks still compute. Only FRESH
                # admissions: an adopted/re-attached stream is already
                # decoding (possibly HERE after an earlier handoff was
                # refused) and must not be re-shipped by this leg.
                target = req.get("decode_target")
                if (self.role == "prefill" and isinstance(target, str)
                        and target):
                    self._register_handoff_locked(
                        rid, target, prompt,
                        target_name=req.get("decode_name"))
            self._cv.notify_all()
            while not self.server.finished(rid):
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._running:
                    self.server.cancel(rid)
                    if self.server.finished(rid):
                        self.server.pop_result(rid)
                        self._gen_keys.pop(rid, None)
                    # a stream cancel() REFUSED (frozen mid-handoff)
                    # keeps its key binding: a retry of this key must
                    # RE-ATTACH to the live stream (or learn the 409
                    # after the handoff resolves), never re-admit
                    return 503, {"error": "generate deadline exceeded"
                                 if self._running else "replica stopping"}
                self._cv.wait(timeout=min(remaining, 0.25))
            self._gen_keys.pop(rid, None)
            mig = self.server.migrated_to(rid)
            if mig is not None:
                # the stream lives on elsewhere: remember the verdict
                # per key (a retry must re-learn it, not re-admit) and
                # reclaim local bookkeeping — the target owns the tokens
                if key:
                    self._migrated_keys[key] = dict(mig)
                    self._trim_locked(self._migrated_keys)
                self.server.pop_result(rid)
                return 409, {"error": "request migrated",
                             "migrated": dict(mig)}
            reason = self.server.expire_reason(rid)
            tokens = self.server.pop_result(rid)
        if reason is not None:
            return 503, {"error": f"request expired: {reason}"}
        return 200, {
            "rid": rid,
            "replica": self.name,
            "tokens": tokens,
            "emitted": tokens[len(prompt):],
        }

    # -- Round-19: cross-replica prefix tier ---------------------------------
    #
    # The fleet tier of the tiered KV cache: a replica that misses
    # locally on a routed prompt asks ONE peer — the ring's previous
    # preference owner, named by the router in the generate payload —
    # for the span it has cached, and adopts it before cold-prefilling.
    # The exporter side is read-only (export under the condition, no
    # serving-state mutation), so the exchange is naturally idempotent;
    # the importer's tree-insert consumes nothing it already covers, so
    # a replayed fetch commits at most once. A dark, slow, or faulted
    # peer degrades to cold prefill — the tier can only remove work.

    PEER_FETCH_RETRY = RetryPolicy(attempts=2, deadline=2.0)

    def _prefix_fetch(self, req: dict):
        """``POST /prefix_fetch`` — export this replica's cached
        coverage of ``prompt`` from logical page ``from_page`` on, as an
        ``encode_span_payload`` body -> (code, obj). 404 when the tree
        covers nothing past ``from_page`` (the requester cold-prefills);
        read-only either way."""
        prompt = req.get("prompt")
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) for t in prompt)):
            return 400, {"error": "prompt must be a non-empty list of "
                                  "token ids"}
        try:
            from_page = int(req.get("from_page") or 0)
        except (TypeError, ValueError):
            return 400, {"error": "from_page must be an integer"}
        if from_page < 0:
            return 400, {"error": "from_page must be >= 0"}
        export = getattr(self.server, "export_prefix_span", None)
        if export is None:
            return 404, {"error": "replica has no prefix tier"}
        with self._cv:
            span = export(prompt, from_page=from_page)
        if span is None:
            self.server.obs.counter("kubetpu_peer_prefix_export_total",
                                    result="miss").inc()
            return 404, {"error": "no cached coverage past from_page"}
        self.server.obs.counter("kubetpu_peer_prefix_export_total",
                                result="hit").inc()
        self.events.emit("prefix_export", pages=int(span["n_pages"]),
                         from_page=int(span["from_page"]))
        return 200, {
            "replica": self.name,
            "matched_tokens": int(span["matched_tokens"]),
            "from_page": int(span["from_page"]),
            "n_pages": int(span["n_pages"]),
            "span": encode_span_payload(span["pages"],
                                        int(span["from_page"])),
        }

    # -- Round-22: adapter hot-load/evict ------------------------------------

    def _adapters(self, req: dict):
        """``POST /adapters`` — the multi-LoRA control-plane leg ->
        (code, obj); runs under ``run_idempotent`` (a lost response
        replays). Actions:

        - ``load``: decode the wire adapter + hot-load it into the
          serving stack (content-idempotent — a replayed or re-keyed
          load of a resident adapter is a no-op). 503 when the stack is
          full of in-use adapters (retryable: requests drain), 400 on a
          malformed or mismatched payload;
        - ``evict``: drop the named adapter from the directory. 409
          while a live request references it (eviction must never yank
          an adapter out from under an admitted stream); ``evicted:
          false`` when already gone (replay-idempotent).

        Both answers carry the post-action resident set, so the caller
        (and the router's next /load scrape) sees residency without a
        second round trip."""
        load_fn = getattr(self.server, "load_adapter", None)
        evict_fn = getattr(self.server, "evict_adapter", None)
        if load_fn is None or evict_fn is None:
            return 404, {"error": "replica does not serve multi-LoRA"}
        action = str(req.get("action") or "load")
        if action == "load":
            from kubetpu.router.adapters import decode_adapter
            try:
                adapter = decode_adapter(req.get("adapter") or {})
            except ValueError as e:
                return 400, {"error": str(e)}
            name = req.get("name")
            if name is not None and not isinstance(name, str):
                return 400, {"error": "adapter name must be a string"}
            try:
                with self._cv:
                    out = load_fn(adapter, name=name)
                    resident = self.server.resident_adapters()
            except ValueError as e:
                return 400, {"error": str(e)}
            except RuntimeError as e:
                # every index pinned by live requests: transient — the
                # keyed retry lands after streams drain
                return 503, {"error": str(e)}
            self.events.emit("adapter_wire_load", name=out)
            return 200, {"name": out, "resident": resident,
                         "replica": self.name}
        if action == "evict":
            name = req.get("name")
            if not isinstance(name, str) or not name:
                return 400, {"error": "evict needs an adapter name"}
            try:
                with self._cv:
                    evicted = evict_fn(name)
                    resident = self.server.resident_adapters()
            except RuntimeError as e:
                return 409, {"error": str(e)}
            self.events.emit("adapter_wire_evict", name=name,
                             evicted=bool(evicted))
            return 200, {"evicted": bool(evicted), "resident": resident,
                         "replica": self.name}
        return 400, {"error": f"unknown adapter action {action!r}"}

    def _maybe_peer_prefetch(self, req: dict, prompt: list,
                             key: Optional[str]) -> None:
        """Best-effort pull of *prompt*'s KV span from the peer the
        router named (``prefix_peer`` in the generate payload). Probes
        local coverage under the condition, runs the HTTP leg unlocked
        (local coverage may move meanwhile — ``inject_prefix`` detects
        the hole and refuses), injects under the condition. EVERY
        failure path is a silent degrade to cold prefill."""
        peer = req.get("prefix_peer")
        if not isinstance(peer, str) or not peer:
            return
        inject = getattr(self.server, "inject_prefix", None)
        local_fn = getattr(self.server, "prefix_local_pages", None)
        if inject is None or local_fn is None:
            return
        ps = int(getattr(self.server, "page_size", 0) or 0)
        if ps <= 0:
            return
        # full cached pages a prefill at pos=matched can ever use: the
        # last prompt token is recomputed, hence the -1
        full = (len(prompt) - 1) // ps
        if full <= 0:
            return
        with self._cv:
            local = int(local_fn(prompt))
        if local >= full:
            return                       # already covered locally

        def count(result: str) -> None:
            self.server.obs.counter("kubetpu_peer_prefix_fetch_total",
                                    result=result).inc()

        try:
            resp = request_json(
                peer.rstrip("/") + "/prefix_fetch",
                {"prompt": [int(t) for t in prompt], "from_page": local},
                token=self.token,
                retry=self.PEER_FETCH_RETRY,
                timeout=self.PEER_FETCH_RETRY.deadline,
                idempotency_key=(
                    f"prefix-fetch-{key or uuid.uuid4().hex[:12]}"),
            )
            pages = decode_span_payload(resp["span"])
            matched = int(resp["matched_tokens"])
            from_page = int(resp["from_page"])
        except urllib.error.HTTPError as e:
            count("miss" if e.code == 404 else "degraded")
            if e.code != 404:
                self.events.emit("prefix_fetch_degraded", peer=peer,
                                 code=e.code)
            return
        except Exception as e:  # noqa: BLE001 — any wire/codec failure
            count("degraded")
            self.events.emit("prefix_fetch_degraded", peer=peer,
                             error=str(e)[:120])
            return
        try:
            with self._cv:
                adopted = inject(prompt[:matched], pages,
                                 from_page=from_page)
        except (ValueError, AssertionError) as e:
            count("degraded")
            self.events.emit("prefix_fetch_degraded", peer=peer,
                             error=str(e)[:120])
            return
        count("hit" if adopted else "miss")
        self.events.emit("prefix_fetch", peer=peer, pages=int(adopted),
                         matched_tokens=matched)

    # -- live KV migration (Round-16) ----------------------------------------

    def migrate_rid(self, rid: int, target_url: str,
                    reason: str = "manual") -> bool:
        """Hand ONE in-flight stream to *target_url* token-exactly:
        snapshot + freeze under the condition (the step loop pauses the
        slot, nothing else moves it), ship the snapshot as idempotency-
        keyed begin/chunk*N/commit POSTs (keys derive from the stream's
        (origin, rid, epoch) — a lost response replays, never a second
        restore), and retire the local slot only after the target's
        commit-ack. Outcomes:

        - **committed**: target ack'd — the slot retires as migrated
          (callers get 409 + the new owner);
        - **refused** (definitive HTTP error answer): the slot
          unfreezes and resumes locally, token-exactly;
        - **fenced** (409 fenced): another copy already owns the stream
          at >= this epoch — never resume (at-most-one-active);
        - **ambiguous** (transport dead past the retry budget): the
          target MAY have committed, so resuming could double-run the
          stream — the slot finishes as migrated toward the attempted
          target; a router retry either adopts the restored stream or
          re-admits fresh (token-exact either way).

        Counted as ``kubetpu_migrations_total{reason,result}``."""
        target_url = target_url.rstrip("/")
        with self._cv:
            try:
                snap = self.server.snapshot_slot(rid)
            except (ValueError, NotImplementedError) as e:
                self.events.emit("migrate_skip", rid=rid, error=str(e))
                return False
            self.server.freeze_slot(rid)
            # the stream's generate key: from an attached handler, or —
            # for a migrated-IN stream whose router retry has not landed
            # yet — from the adoption map. It ships in the meta so the
            # key keeps following the stream across EVERY hop.
            gen_key = self._gen_keys.get(rid)
            if gen_key is None:
                gen_key = next((k for k, v in self._adopted.items()
                                if v == rid), None)
        try:
            # from freeze to the wire leg, ANY failure must unfreeze —
            # a raise here would otherwise wedge the stream frozen with
            # no resolution path (no commit, no refusal)
            origin = list(snap.get("origin") or (self.name, rid))
            epoch = int(snap.get("epoch", 0)) + 1
            snap["origin"] = origin
            snap["epoch"] = epoch
            pages = snap["pages"]
            n_live = int(snap["n_live_pages"])
            meta = {k: v for k, v in snap.items() if k != "pages"}
            meta["gen_key"] = gen_key
            meta["reason"] = reason
            meta["source"] = self.name
            tok = {"origin": origin, "epoch": epoch}
            # keys are per ATTEMPT (nonce), not per epoch: retries
            # inside request_json reuse them (lost-response replay),
            # while a fresh migrate_rid call after a REFUSAL re-stages
            # under new keys — an epoch-only key would replay the old
            # begin 200 against deleted staging and spin hopelessly.
            # At-most-once is the commit fence's job (a second commit
            # at the same epoch is refused), not the key's.
            kbase = (f"mig-{origin[0]}-{origin[1]}-e{epoch}-"
                     f"{uuid.uuid4().hex[:8]}")
        except Exception:
            with self._cv:
                self.server.unfreeze_slot(rid)
                self._cv.notify_all()
            raise
        self.events.emit("migrate_begin", rid=rid, target=target_url,
                         reason=reason, epoch=epoch)
        # Outcome classification is PER LEG: only a failure of the
        # COMMIT POST can mask an executed (or still-executing) restore
        # — begin/chunk/encode failures provably left no copy at the
        # target (staging is not a stream; its TTL reaps it), so the
        # source resumes token-exactly. A commit-phase 4xx is a
        # definitive ANSWER of non-commit (restore raised / staging
        # gone); a commit-phase 5xx or transport death is AMBIGUOUS
        # (run_idempotent's in-flight 503 can outlive the retry budget
        # while the restore still runs) and must never resume.
        leg = "begin"
        try:
            with obs_trace.span("migrate.out",
                                component=self.obs_component,
                                reason=reason):
                resp = request_json(
                    target_url + "/migrate_in",
                    {"phase": "begin", "token": tok, "meta": meta},
                    token=self.token, idempotency_key=kbase + "-begin",
                    timeout=self.migrate_timeout)
                # the target's prefix hint: pages it can map read-only
                # from its own cache never cross the wire — ship only
                # the uncached suffix (commit re-checks; a receded
                # match refuses and we resume + re-ship). Encoded ONCE,
                # after the hint, so a warm-target handoff never pays a
                # full-blob copy it then throws away.
                skip = min(max(0, int(resp.get("skip_pages") or 0)),
                           n_live)
                ship = (pages if skip == 0 else
                        {n: a[:, skip:] for n, a in pages.items()})
                enc, blob = encode_snapshot({"pages": ship})
                arrays, ship_from = enc["arrays"], skip
                chunks = blob_chunks(blob, self.migrate_chunk_bytes)
                leg = "chunk"
                for i, chunk in enumerate(chunks):
                    request_json(
                        target_url + "/migrate_in",
                        {"phase": "chunk", "token": tok, "seq": i,
                         "data": chunk_b64(chunk)},
                        token=self.token,
                        idempotency_key=f"{kbase}-c{i}",
                        timeout=self.migrate_timeout)
                leg = "commit"
                ack = request_json(
                    target_url + "/migrate_in",
                    {"phase": "commit", "token": tok,
                     "n_chunks": len(chunks), "arrays": arrays,
                     "ship_from_page": ship_from},
                    token=self.token, idempotency_key=kbase + "-commit",
                    timeout=self.migrate_timeout)
        except urllib.error.HTTPError as e:
            detail = {}
            try:
                detail = json.loads(e.read() or b"{}")
            except Exception:  # noqa: BLE001 — body unreadable/withheld
                pass
            if detail.get("fenced"):
                info = {"replica": detail.get("replica"),
                        "epoch": int(detail.get("epoch", epoch)),
                        "fenced": True}
                with self._cv:
                    self.server.finish_migrated(rid, info)
                    self._note_stream_left_locked(rid, gen_key, info)
                    self._cv.notify_all()
                self._count_migration(reason, "fenced")
                return False
            if e.code < 500 or leg != "commit":
                with self._cv:
                    self.server.unfreeze_slot(rid)
                    self._cv.notify_all()
                self._count_migration(reason, "refused")
                self.events.emit("migrate_refused", rid=rid, code=e.code,
                                 leg=leg,
                                 error=str(detail.get("error", ""))[:120])
                return False
            return self._migrate_ambiguous(rid, gen_key, target_url,
                                           epoch, reason,
                                           f"HTTP {e.code} on commit")
        except Exception as e:  # noqa: BLE001 — transport death
            if leg != "commit":
                # no commit POST was ever sent: the target cannot hold
                # a copy — resume, don't sacrifice the stream
                with self._cv:
                    self.server.unfreeze_slot(rid)
                    self._cv.notify_all()
                self._count_migration(reason, "refused")
                self.events.emit("migrate_refused", rid=rid, code=0,
                                 leg=leg, error=str(e)[:120])
                return False
            return self._migrate_ambiguous(rid, gen_key, target_url,
                                           epoch, reason, str(e))
        info = {"replica": ack.get("replica"), "rid": ack.get("rid"),
                "epoch": epoch}
        with self._cv:
            self.server.finish_migrated(rid, info)
            self._note_stream_left_locked(rid, gen_key, info)
            self._cv.notify_all()
        self.server.obs.counter(
            "kubetpu_migration_bytes_shipped_total",
            "snapshot blob bytes shipped over /migrate_in").inc(len(blob))
        self._count_migration(reason, "committed")
        self.events.emit("migrate_commit", rid=rid,
                         target=ack.get("replica"), epoch=epoch)
        return True

    def _count_migration(self, reason: str, result: str) -> None:
        self.server.obs.counter("kubetpu_migrations_total",
                                reason=reason, result=result).inc()

    def _migrate_ambiguous(self, rid: int, gen_key: Optional[str],
                           target_url: str, epoch: int, reason: str,
                           err: str) -> bool:
        """A commit whose outcome is unknowable (transport death or a
        5xx that can mask a still-executing restore): the stream
        finishes as migrated toward the attempted target — resuming
        could double-run it, and at-most-one-active beats finishing
        here. The router retry adopts the restored copy or re-computes
        fresh; token-exact either way."""
        info = {"replica": None, "url": target_url, "epoch": epoch,
                "ambiguous": True}
        with self._cv:
            self.server.finish_migrated(rid, info)
            self._note_stream_left_locked(rid, gen_key, info)
            self._cv.notify_all()
        self._count_migration(reason, "ambiguous")
        self.events.emit("migrate_ambiguous", rid=rid, error=err[:120])
        return False

    @staticmethod
    def _trim_locked(od: OrderedDict, cap: int = 4096) -> None:
        """Caller holds ``self._cv``: FIFO-evict the oldest entries
        past *cap* — the one spelling of every bounded map's policy."""
        while len(od) > cap:
            od.popitem(last=False)

    def _gc_gen_keys_locked(self) -> None:
        """Caller holds ``self._cv``: drop generate-key entries whose
        rid is no longer unfinished (adopted-but-never-attached streams
        that completed naturally) once the map grows past the cap."""
        if len(self._gen_keys) > 4096:
            live = set(self.server.unfinished_rids())
            for r in [r for r in self._gen_keys if r not in live]:
                del self._gen_keys[r]

    def _note_stream_left_locked(self, rid: int, gen_key: Optional[str],
                                 info: dict) -> None:
        """Caller holds ``self._cv``. A stream just left this replica:
        retire its key bookkeeping and record the 409 verdict per key,
        so ANY later visit with that key — an attached handler's retry,
        or a router attempt chasing a multi-hop stream that was adopted
        here but never attached — deterministically re-learns the new
        owner instead of re-admitting (the at-most-one-active ledger
        depends on this surviving every hop). *gen_key* is the caller's
        already-resolved key — ``migrate_rid`` resolves it once through
        both the attached and adopted maps before the wire leg."""
        self._gen_keys.pop(rid, None)
        if gen_key is not None:
            self._adopted.pop(gen_key, None)
            self._migrated_keys[gen_key] = dict(info)
            self._trim_locked(self._migrated_keys)

    def migrate_all(self, target_url: str,
                    reason: str = "manual") -> "tuple[int, int]":
        """Migrate every currently-migratable stream to *target_url*
        -> (committed, not_committed)."""
        with self._cv:
            rids = self.server.migratable_rids()
        done = failed = 0
        for rid in rids:
            if self.migrate_rid(rid, target_url, reason=reason):
                done += 1
            else:
                failed += 1
        return done, failed

    def _migrate_out(self, req: dict):
        """``POST /migrate_out`` — the policy layer's push-button:
        snapshot every migratable stream toward ``target``. ``wait``
        (default true) runs inline and returns counts; false kicks a
        background sweep (the router's breaker-suspect path, which
        must not stall its signals loop on a slow transfer)."""
        target = req.get("target")
        if not isinstance(target, str) or not target:
            return 400, {"error": "target url required"}
        reason = str(req.get("reason") or "manual")
        if req.get("wait", True):
            done, failed = self.migrate_all(target, reason=reason)
            return 200, {"migrated": done, "failed": failed}
        with self._cv:
            pending = len(self.server.migratable_rids())
        threading.Thread(
            target=self.migrate_all, args=(target, reason),
            name=f"kubetpu-replica-migrate-out-{self.name}",
            daemon=True).start()
        return 200, {"started": pending}

    # -- Round-17: disaggregated prefill -> decode streaming handoff ---------
    #
    # The prefill role's whole point: a routed generate carrying a
    # ``decode_target`` admits + chunk-prefills HERE, but every token is
    # emitted at the decode replica. The transfer rides the Round-16
    # begin/chunk/commit wire path with the SAME per-(origin, rid,
    # epoch) idempotency keys and commit-only retirement — what changes
    # is WHEN bytes move: completed page-aligned KV spans ship while
    # later prefill chunks are still computing (each span is its own
    # manifest entry, ``migration.span_name``), so by first token only
    # the tail pages + request meta remain. The state machine:
    #
    #   begin    POST the prompt + identity; learn the target's prefix
    #            hint (cached pages never cross the wire)
    #   stream   while mid-prefill: gather pages below the progress
    #            mark (page-aligned chunk starts make them FINAL) and
    #            append them as wire chunks
    #   commit   the step loop freezes the slot at its first migratable
    #            boundary (zero extra decode steps on the prefill side);
    #            the handoff loop snapshots the TAIL (from_page = what
    #            already shipped), ships it, and commits with the full
    #            request meta. Outcomes mirror ``migrate_rid``:
    #            commit-ack retires (finish_migrated -> callers chase
    #            the 409 to the decode replica, where the gen key
    #            ADOPTS the restored stream), a definitive refusal
    #            unfreezes and resumes locally (the colocated-degrade
    #            safety net), an ambiguous commit never resumes.

    def _register_handoff_locked(self, rid: int, target: str,
                                 prompt: list,
                                 target_name: Optional[str] = None) -> None:
        """Caller holds ``self._cv`` (the _generate admission branch)."""
        self._handoffs[rid] = {
            "rid": rid,
            "target": target.rstrip("/"),
            "target_name": target_name,
            "state": "begin",
            "prompt": [int(t) for t in prompt],
            # locally-born stream: this handoff is generation 1 of the
            # (this replica, rid) lineage — the target's fence compares
            "tok": {"origin": [self.name, rid], "epoch": 1},
            "epoch": 1,
            # per-ATTEMPT nonce like migrate_rid's: at-most-once lives
            # in the commit fence, not the key
            "kbase": (f"dis-{self.name}-{rid}-e1-"
                      f"{uuid.uuid4().hex[:8]}"),
            "seq": 0,
            "manifest": [],
            "skip": 0,
            # pages CAPTURED off the device (host copies, taken under
            # the step loop's own lock hold so a fast prefill can never
            # outrun the capture) vs pages actually SENT on the wire
            "captured": 0,
            "spans": [],           # [(lo, hi, pages-dict)] awaiting send
            "early_pages": 0,
            "frozen": False,
        }
        self.events.emit("handoff_intent", rid=rid,
                         target=target_name or target)

    def _page_fields(self) -> tuple:
        """Manifest field order for one page span — matches the stored
        pool layout ``snapshot_slot`` ships."""
        return (("k_q", "k_s", "v_q", "v_s")
                if getattr(self.server, "kv_int8", False) else ("k", "v"))

    def _count_handoff(self, result: str) -> None:
        self.server.obs.counter(
            "kubetpu_handoffs_total",
            "disaggregated prefill->decode stream handoffs by outcome",
            result=result).inc()

    def _advance_handoffs_locked(self) -> None:
        """Caller holds ``self._cv`` (the step loop, right after a
        step). Two duties, both cheap enough to ride the loop:

        - CAPTURE newly completed page spans of mid-prefill handoff
          streams (a host copy of a few pages). Riding the step's own
          lock hold makes the pipelining deterministic: a prefill that
          outruns the wire can never outrun the capture, so the spans
          genuinely ship from work completed while later chunks compute
          — the wire sends happen on the handoff loop thread,
          overlapped with the following steps;
        - FREEZE every handoff stream the moment it becomes migratable
          (first token materialized, prefill done), so the prefill
          replica never decodes past the snapshot point."""
        if not self._handoffs:
            return
        progress = getattr(self.server, "prefill_progress", None)
        gather = getattr(self.server, "snapshot_pages", None)
        ps = int(getattr(self.server, "page_size", 0) or 0)
        ready = None
        for rid, h in self._handoffs.items():
            if h["frozen"]:
                continue
            if progress is not None and gather is not None and ps:
                prog = progress(rid)
                if prog is not None:
                    stable = min(prog[0] // ps,
                                 len(h["prompt"]) // ps)
                    if stable > h["captured"]:
                        try:
                            h["spans"].append(
                                (h["captured"], stable,
                                 gather(rid, h["captured"], stable)))
                            h["captured"] = stable
                        except (ValueError, NotImplementedError):
                            pass   # ships with the commit tail instead
                    continue       # mid-prefill: not migratable yet
            if ready is None:
                ready = set(self.server.migratable_rids())
            if rid in ready:
                self.server.freeze_slot(rid)
                h["frozen"] = True

    def _handoff_loop(self) -> None:
        """Drive every in-flight handoff: one bounded action per rid
        per round (a begin POST, one page span's chunks, or the
        tail+commit), rounds fanned over a small worker pool
        (``handoff_workers``) — each action is mostly wire wait, and a
        frozen stream makes no progress ANYWHERE until its commit-ack,
        so serializing N commits costs the Nth stream N x the wire
        latency of dead frozen time. Per-rid ordering is preserved
        (one action per rid per round, rounds joined). Wire work runs
        OUTSIDE the condition — the step loop keeps prefilling other
        slots while bytes move, which is the pipelining."""
        from concurrent.futures import ThreadPoolExecutor

        pool = ThreadPoolExecutor(
            max_workers=self.handoff_workers,
            thread_name_prefix=f"kubetpu-handoff-{self.name}")
        try:
            while True:
                with self._cv:
                    if not self._running:
                        return
                    rids = list(self._handoffs)
                    if not rids:
                        self._cv.wait(timeout=0.05)
                        continue
                if len(rids) == 1 or self.handoff_workers == 1:
                    # no any()-short-circuit: EVERY rid gets its action
                    progressed = any(
                        [bool(self._handoff_pass_safe(rid))
                         for rid in rids])
                else:
                    # list() BEFORE any(): the round must JOIN — a
                    # short-circuited map iterator would let the next
                    # round start while this round's slow passes still
                    # run, racing two passes of one rid on its chunk
                    # sequence (caught by disagg-check as a
                    # missing-chunk refusal)
                    progressed = any(
                        list(pool.map(self._handoff_pass_safe, rids)))
                if not progressed:
                    with self._cv:
                        if self._running and self._handoffs:
                            self._cv.wait(timeout=0.002)
        finally:
            pool.shutdown(wait=False)

    def _handoff_pass_safe(self, rid: int) -> bool:
        """``_handoff_pass`` with the loop's survival guarantee: ANY
        unexpected exception aborts that one handoff (stream unfrozen,
        resumes locally — the pre-commit failure spelling) instead of
        killing the streamer thread. A dead streamer would be a
        fleet-wide black hole: the step loop keeps freezing every new
        handoff stream at its first token with nothing left to ship,
        commit, or thaw them, while /healthz keeps reporting
        healthy."""
        try:
            return bool(self._handoff_pass(rid))
        except Exception as e:  # noqa: BLE001 — the loop must survive
            with self._cv:
                h = self._handoffs.get(rid)
                if h is not None:
                    self._handoff_abort_locked(
                        rid, h, f"unexpected: {type(e).__name__}: {e}")
            return False

    def _handoff_pass(self, rid: int) -> bool:
        """One bounded action for *rid*'s handoff -> True if anything
        moved (wire bytes or a state transition). Spans captured by the
        step loop drain first (in page order); the commit fires only
        once the stream is frozen AND every captured span is on the
        wire."""
        snap = span = None
        with self._cv:
            h = self._handoffs.get(rid)
            if h is None:
                return False
            if self.server.finished(rid):
                # completed (or canceled) locally before the handoff
                # could commit — a short stream can outrun its own
                # transfer; the caller already has the tokens
                self._handoffs.pop(rid, None)
                self._count_handoff("skipped")
                self.events.emit("handoff_skip", rid=rid,
                                 reason="finished_locally")
                return False
            if h["state"] == "stream":
                if h["spans"]:
                    span = h["spans"].pop(0)
                elif h["frozen"]:
                    try:
                        snap = self.server.snapshot_slot(
                            rid,
                            from_page=max(h["captured"], h["skip"]),
                            allow_frozen=True)
                    except (ValueError, NotImplementedError) as e:
                        return self._handoff_abort_locked(
                            rid, h, f"snapshot: {e}")
        if h["state"] == "begin":
            return self._handoff_begin(rid, h)
        if span is not None:
            return self._handoff_send_span(rid, h, span[0], span[1],
                                           span[2], early=True)
        if snap is not None:
            return self._handoff_commit(rid, h, snap)
        return False

    def _handoff_begin(self, rid: int, h: dict) -> bool:
        """The begin leg: ship the prompt + identity, learn how many
        leading pages the target can map from its own prefix cache —
        those never cross the wire."""
        with self._cv:
            gen_key = self._gen_keys.get(rid)
        meta = {"prompt": h["prompt"], "reason": "disagg",
                "source": self.name}
        if gen_key:
            meta["gen_key"] = gen_key
        try:
            resp = request_json(
                h["target"] + "/migrate_in",
                {"phase": "begin", "token": h["tok"], "meta": meta},
                token=self.token, idempotency_key=h["kbase"] + "-begin",
                timeout=self.migrate_timeout)
        except Exception as e:  # noqa: BLE001 — target dark or refusing
            with self._cv:
                return self._handoff_abort_locked(rid, h,
                                                  f"begin: {e}")
        ps = int(getattr(self.server, "page_size", 1) or 1)
        cap = max(0, (len(h["prompt"]) - 1) // ps)
        h["skip"] = min(max(0, int(resp.get("skip_pages") or 0)), cap)
        h["state"] = "stream"
        self.events.emit("handoff_begin", rid=rid, target=h["target"],
                         skip_pages=h["skip"])
        return True

    def _handoff_send_span(self, rid: int, h: dict, from_page: int,
                           to_page: int, pages: dict,
                           early: bool) -> bool:
        """Append one page span to the transfer: manifest entries in
        field order, bytes as sequenced wire chunks. *early* spans are
        the pipelining — pages captured while later prefill chunks were
        still computing. Pages below the target's prefix hint are
        sliced off (a span captured before the begin answer arrived may
        cover pages the target already holds warm — they never cross
        the wire)."""
        lo = max(from_page, h["skip"])
        if to_page <= lo:
            return True            # entirely covered by the warm hint
        parts = []
        manifest = []
        for field in self._page_fields():
            arr = np.ascontiguousarray(pages[field][:, lo - from_page:])
            manifest.append({"name": span_name(field, lo),
                             "dtype": str(arr.dtype),
                             "shape": list(arr.shape)})
            parts.append(arr.tobytes())
        blob = b"".join(parts)
        try:
            for piece in blob_chunks(blob, self.migrate_chunk_bytes):
                request_json(
                    h["target"] + "/migrate_in",
                    {"phase": "chunk", "token": h["tok"],
                     "seq": h["seq"], "data": chunk_b64(piece)},
                    token=self.token,
                    idempotency_key=f"{h['kbase']}-c{h['seq']}",
                    timeout=self.migrate_timeout)
                h["seq"] += 1
        except Exception as e:  # noqa: BLE001 — pre-commit: resume is safe
            with self._cv:
                return self._handoff_abort_locked(rid, h, f"chunk: {e}")
        h["manifest"].extend(manifest)
        with self._cv:
            # plain-int accumulators shared across concurrent handoff
            # workers: += is a read-modify-write, so take the lock
            self._handoff_bytes += len(blob)
            if early:
                self._handoff_early_bytes += len(blob)
        self.server.obs.counter(
            "kubetpu_migration_bytes_shipped_total",
            "snapshot blob bytes shipped over /migrate_in").inc(len(blob))
        if early:
            h["early_pages"] += to_page - lo
            self.server.obs.counter(
                "kubetpu_handoff_pages_streamed_total",
                "KV pages captured+shipped while later prefill chunks "
                "were still computing — the pipelining proof").inc(
                    to_page - lo)
        return True

    def _handoff_commit(self, rid: int, h: dict, snap: dict) -> bool:
        """Ship the tail span + the full request meta, then commit.
        Outcome classification is ``migrate_rid``'s: only a commit-POST
        failure can mask an executed restore — tail-chunk failures
        provably left no live copy (staging is not a stream) and resume
        locally; the commit 200 is the retirement ack."""
        n_live = int(snap["n_live_pages"])
        with self._cv:
            gen_key = self._gen_keys.get(rid)
        meta = {k: v for k, v in snap.items() if k != "pages"}
        meta.update(origin=h["tok"]["origin"], epoch=h["epoch"],
                    gen_key=gen_key, reason="disagg", source=self.name)
        target_label = h.get("target_name") or h["target"]
        with obs_trace.span("disagg.handoff",
                            component=self.obs_component,
                            target=target_label):
            tail_from = max(h["captured"], h["skip"])
            if (n_live > tail_from and not self._handoff_send_span(
                    rid, h, tail_from, n_live, snap["pages"],
                    early=False)):
                return False        # aborted (and unfrozen) inside
            try:
                ack = request_json(
                    h["target"] + "/migrate_in",
                    {"phase": "commit", "token": h["tok"],
                     "n_chunks": h["seq"], "arrays": h["manifest"],
                     "ship_from_page": h["skip"], "meta": meta},
                    token=self.token,
                    idempotency_key=h["kbase"] + "-commit",
                    timeout=self.migrate_timeout)
            except urllib.error.HTTPError as e:
                detail = {}
                try:
                    detail = json.loads(e.read() or b"{}")
                except Exception:  # noqa: BLE001 — body unreadable
                    pass
                if detail.get("fenced"):
                    info = {"replica": detail.get("replica"),
                            "epoch": int(detail.get("epoch", h["epoch"])),
                            "fenced": True}
                    with self._cv:
                        self.server.finish_migrated(rid, info)
                        self._note_stream_left_locked(rid, gen_key, info)
                        self._handoffs.pop(rid, None)
                        self._cv.notify_all()
                    self._count_handoff("fenced")
                    return True
                if e.code < 500:
                    # definitive refusal: the restore raised / staging
                    # gone — resume locally, token-exact (the colocated
                    # degrade)
                    with self._cv:
                        self.server.unfreeze_slot(rid)
                        self._handoffs.pop(rid, None)
                        self._cv.notify_all()
                    self._count_handoff("refused")
                    self.events.emit("handoff_refused", rid=rid,
                                     code=e.code,
                                     error=str(detail.get("error",
                                                          ""))[:120])
                    return True
                return self._handoff_ambiguous(rid, h, gen_key,
                                               f"HTTP {e.code} on commit")
            except Exception as e:  # noqa: BLE001 — transport death
                return self._handoff_ambiguous(rid, h, gen_key, str(e))
            info = {"replica": ack.get("replica"),
                    "rid": ack.get("rid"), "epoch": h["epoch"]}
            with self._cv:
                self.server.finish_migrated(rid, info)
                self._note_stream_left_locked(rid, gen_key, info)
                self._handoffs.pop(rid, None)
                self._cv.notify_all()
            self._count_handoff("committed")
            # emitted INSIDE the span so the event captures the
            # handoff's trace id — disagg-check stitches source and
            # target spans through it
            self.events.emit("handoff_commit", rid=rid,
                             target=ack.get("replica"),
                             epoch=h["epoch"],
                             early_pages=h["early_pages"],
                             pages=n_live - h["skip"])
        return True

    def _handoff_ambiguous(self, rid: int, h: dict,
                           gen_key: Optional[str], err: str) -> bool:
        """A commit whose outcome is unknowable: the target may hold a
        live copy, so the stream finishes as migrated toward it — the
        router retry adopts the restored stream or recomputes fresh
        (at-most-one-active beats resuming here)."""
        info = {"replica": None, "url": h["target"],
                "epoch": h["epoch"], "ambiguous": True}
        with self._cv:
            self.server.finish_migrated(rid, info)
            self._note_stream_left_locked(rid, gen_key, info)
            self._handoffs.pop(rid, None)
            self._cv.notify_all()
        self._count_handoff("ambiguous")
        self.events.emit("handoff_ambiguous", rid=rid, error=err[:120])
        return True

    def _handoff_abort_locked(self, rid: int, h: dict, err) -> bool:
        """Caller holds ``self._cv``. Pre-commit failure: no copy can
        exist at the target (begin/chunk legs only stage), so the
        stream RESUMES here — prefill continues / decode proceeds
        locally, the colocated-degrade safety net."""
        if h.get("frozen"):
            self.server.unfreeze_slot(rid)
        self._handoffs.pop(rid, None)
        self._cv.notify_all()
        self._count_handoff("aborted")
        self.events.emit("handoff_abort", rid=rid, error=str(err)[:120])
        return False

    def _migrate_in(self, req: dict):
        """One phase of the inbound chunked transfer -> (code, obj);
        runs under ``run_idempotent`` (every phase POST is keyed by the
        source, so a lost response replays instead of re-executing)."""
        phase = req.get("phase")
        tok = req.get("token") or {}
        origin = tok.get("origin") or (None, None)
        try:
            key = (str(origin[0]), int(origin[1]), int(tok.get("epoch")))
        except (TypeError, ValueError, IndexError):
            return 400, {"error": "migrate token must carry "
                                  "origin [replica, rid] + epoch"}
        if phase == "begin":
            meta = req.get("meta")
            if not isinstance(meta, dict):
                return 400, {"error": "begin needs a meta object"}
            # prefix NEGOTIATION: advertise how many leading prompt
            # pages this server can map read-only from its own cache —
            # the source ships only the suffix, so matched pages never
            # cross the wire. A hint, not a promise: the commit-time
            # match is re-checked and a receded one refuses.
            skip = 0
            hint = getattr(self.server, "migration_prefix_hint", None)
            if hint is not None and isinstance(meta.get("prompt"), list):
                try:
                    skip = int(hint(meta["prompt"]))
                except Exception:  # noqa: BLE001 — a hint must never
                    skip = 0       # fail a transfer; 0 = ship it all
            with self._cv:
                now = time.monotonic()
                for stale in [k for k, st in self._mig_staging.items()
                              if now - st["ts"] > MIGRATE_STAGING_TTL]:
                    del self._mig_staging[stale]
                self._mig_staging[key] = {"meta": meta, "chunks": {},
                                          "ts": now}
            return 200, {"staged": True, "skip_pages": skip}
        if phase == "chunk":
            seq = req.get("seq")
            try:
                data = chunk_unb64(req.get("data") or "")
            except (ValueError, TypeError):
                return 400, {"error": "chunk data is not base64"}
            with self._cv:
                st = self._mig_staging.get(key)
                if st is None:
                    # definitive: without staging a retry cannot help —
                    # the source resumes the stream locally
                    return 409, {"error": "no staging for this transfer "
                                          "(begin missing or expired)"}
                if not isinstance(seq, int) or seq < 0:
                    return 400, {"error": f"chunk seq {seq!r} invalid"}
                st["chunks"][seq] = data
                st["ts"] = time.monotonic()
            return 200, {"staged": seq}
        if phase == "commit":
            return self._migrate_commit(key, req)
        return 400, {"error": f"unknown migrate phase {phase!r}"}

    def _migrate_commit(self, key: tuple, req: dict):
        """The restore leg: fence the epoch, rebuild the snapshot
        (the commit carries the shipped-array manifest + chunk count —
        they depend on the begin-phase prefix hint, so the source only
        knows them now), resume decode, adopt the generate key. The 200
        here IS the commit-ack the source retires on; it lands in the
        idempotency window, so a retry after a lost ack replays — never
        a second restore (the migrate-check counter assert)."""
        n = req.get("n_chunks")
        arrays = req.get("arrays")
        if not isinstance(n, int) or n < 1 or not isinstance(arrays, list):
            return 400, {"error": "commit needs n_chunks >= 1 + the "
                                  "shipped-array manifest"}
        with self._cv:
            st = self._mig_staging.get(key)
            if st is None:
                return 409, {"error": "no staging for this transfer"}
            missing = [i for i in range(n) if i not in st["chunks"]]
            if missing:
                return 409, {"error": f"transfer incomplete: missing "
                                      f"chunks {missing[:4]}"}
            okey = (key[0], key[1])
            fence = self._mig_epochs.get(okey)
            if fence is not None and key[2] <= fence:
                self.server.obs.counter(
                    "kubetpu_migrations_fenced_total",
                    "stale/duplicate handoff generations refused by "
                    "the epoch fence").inc()
                self.events.emit("migrate_fenced",
                                 origin=f"{okey[0]}/{okey[1]}",
                                 epoch=key[2], fence=fence)
                return 409, {"error": "stale migration epoch",
                             "fenced": True, "replica": self.name,
                             "epoch": fence}
            if self.draining:
                # a draining target would just hand the stream onward;
                # refuse so the source resumes or the policy re-picks
                return 503, {"error": "replica is draining"}
            # the Round-17 streaming handoff only knows the FULL request
            # state at commit time (emitted tokens, position, sampler
            # state all moved while spans streamed), so the commit may
            # carry a meta update that merges over the begin phase's
            extra = req.get("meta") if isinstance(req.get("meta"),
                                                  dict) else {}
            gk = extra.get("gen_key") or st["meta"].get("gen_key")
            if gk and (gk in self._adopted
                       or gk in self._gen_keys.values()):
                # the router already RE-ADMITTED this logical request
                # here (an earlier ambiguous attempt): a restore now
                # would start a second active copy the epoch fence
                # cannot see (a fresh admission carries no origin).
                # Definitive refusal — the source must never resume
                # either (it classified the attempt ambiguous already
                # or will treat this as refused-with-fence-semantics).
                self.server.obs.counter(
                    "kubetpu_migrations_in_total",
                    result="refused").inc()
                return 409, {"error": "stream already active here "
                                      "under this generate key",
                             "fenced": True, "replica": self.name,
                             "epoch": key[2]}
            try:
                blob = b"".join(st["chunks"][i] for i in range(n))
                meta = dict(st["meta"])
                meta.update(extra)
                meta["arrays"] = arrays
                snap = decode_snapshot(meta, blob)
                ship_from = int(req.get("ship_from_page", 0) or 0)
                # a streamed transfer's pages arrive as ordered SPANS
                # (migration.span_name); stitch them back into the
                # contiguous per-field arrays restore_slot consumes —
                # a gap or overlap refuses here, never restores holes
                snap["pages"] = assemble_spans(snap["pages"], ship_from)
                snap["ship_from_page"] = ship_from
                rid = self.server.restore_slot(
                    snap, reason=str(meta.get("reason", "migrate")))
            except (ValueError, NotImplementedError) as e:
                del self._mig_staging[key]
                self.server.obs.counter(
                    "kubetpu_migrations_in_total", result="refused").inc()
                return 400, {"error": f"restore refused: {e}"}
            if rid is None:
                # transient capacity shortfall: the source's keyed retry
                # lands after a slot / pool pages free up
                return 503, {"error": "no capacity for migrated stream"}
            del self._mig_staging[key]
            self._mig_epochs[okey] = key[2]
            # refresh recency: a long-lived frequently-migrating stream
            # must not be the FIRST fence evicted just because its
            # lineage is old (that would re-open the double-restore
            # window the fence closes)
            self._mig_epochs.move_to_end(okey)
            self._trim_locked(self._mig_epochs)
            if gk:
                self._adopted[gk] = rid
                self._trim_locked(self._adopted)
                # the key follows the stream: a FURTHER hop must ship
                # it onward even if no handler ever attaches here
                self._gen_keys[rid] = gk
                self._gc_gen_keys_locked()
                # a stream RETURNING here (A->B->A) must shed the stale
                # migrated-away verdict, or _generate keeps answering
                # 409 with the OLD lower-epoch owner forever
                self._migrated_keys.pop(gk, None)
            self._cv.notify_all()
        self.server.obs.counter(
            "kubetpu_migrations_in_total",
            "inbound migrations by outcome", result="committed").inc()
        return 200, {"rid": rid, "replica": self.name, "epoch": key[2]}

    # -- observability -------------------------------------------------------

    def load(self) -> dict:
        """The routing-signal snapshot: ``server.load_info()`` (host
        counters only — no device sync, no reservoir sort beyond the
        bounded percentile reads) plus this wire layer's flags."""
        info = dict(self.server.load_info())
        info["replica"] = self.name
        info["role"] = self.role
        info["draining"] = self.draining
        info["boot_nonce"] = self.boot_nonce
        # GIL-atomic len reads, like the server's own host counters —
        # the load snapshot is advisory, never a synchronized view
        info["inflight_handoffs"] = len(self._handoffs)
        # staged INBOUND transfers: streams about to land in this
        # pool's slots — the decode-target picker counts them so a
        # burst of handoffs spreads instead of clumping on whichever
        # node's /load snapshot was scraped before the burst
        info["inbound_transfers"] = len(self._mig_staging)
        return info

    def render_events(self, kind: Optional[str] = None,
                      limit: Optional[int] = None) -> str:
        evs = merge_events({
            self.obs_component: self.events,
            "serving": self.server.events,
        }, limit=None)
        if kind is not None:
            evs = [e for e in evs if e.get("kind") == kind]
        if limit is not None and limit >= 0:
            evs = evs[-limit:] if limit else []
        return "".join(json.dumps(e) + "\n" for e in evs)

    # -- step loop -----------------------------------------------------------

    def _poll_loop(self) -> None:
        """Drive the serving object: step while any request is in
        flight, sleep (bounded) while idle. Every touch of the serving
        object happens under the condition — the handlers' enqueue and
        result reads interleave between steps, never during one. The
        drain-timeout check rides the same loop: a bounded drain whose
        deadline passed cancels what's left instead of wedging."""
        while True:
            with self._cv:
                if not self._running:
                    return
                self._check_drain_timeout_locked()
                # sleep when a step would advance nothing: idle, OR the
                # only remaining work is frozen mid-handoff (stepping a
                # frozen-only server is a busy no-op spin for the whole
                # wire transfer)
                runnable = getattr(self.server, "_runnable", None)
                if self.server._idle() or (runnable is not None
                                           and not runnable()):
                    self._cv.wait(timeout=self._idle_wait)
                    continue
                self.server.step()
                # Round-17: capture completed KV spans + pause handoff
                # streams AT the step boundary (the wire work runs on
                # the handoff loop thread, overlapped with later steps)
                self._advance_handoffs_locked()
                self._cv.notify_all()
            # yield OUTSIDE the condition when a KV transfer is in
            # flight (outbound handoffs here / inbound staging on a
            # decode target): a busy step loop re-acquires the lock
            # faster than notified waiters wake, starving the handoff
            # streamer and the transfer handlers for hundreds of
            # milliseconds — one scheduler yield per step lets a parked
            # thread actually take the lock. Transfer-free replicas
            # skip it: the yield costs ~ms of TTFT per admission
            # (pinned by the bench gate's router_ttft_p50_ms ratchet)
            # and buys nothing without a transfer to unblock.
            if self._handoffs or self._mig_staging:
                time.sleep(0)

    def _check_drain_timeout_locked(self) -> None:
        """Caller holds ``self._cv``. A draining replica past its
        ``drain_timeout_s`` with streams still in flight CANCELS them
        (each expires with reason ``drain_timeout`` — their callers get
        a retryable 503, and retries land elsewhere via the router).
        When a migrate target was named, the migrate loop had the same
        window to move them — the deadline is the hard bound either
        way, so scale-down can never wait out a long-max_tokens
        stream."""
        if (not self.draining or self._drain_deadline is None
                or time.monotonic() < self._drain_deadline
                or self.server._idle()):
            return
        unresolved = False
        for rid in self.server.unfinished_rids():
            if self.server.cancel_expired(rid, "drain_timeout"):
                self.events.emit("drain_timeout", rid=rid)
            elif not self.server.finished(rid):
                # a frozen (mid-handoff) stream refuses cancel — its
                # transfer resolves it; keep the deadline ARMED so the
                # next tick sweeps whatever a refusal resumed
                unresolved = True
        if not unresolved:
            self._drain_deadline = None
        self._cv.notify_all()

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> str:
        """Serve + start the step loop (both daemon threads); returns
        the bound address."""
        with self._cv:
            self._running = True
        self._loop_thread = threading.Thread(
            target=self._poll_loop, name=f"kubetpu-replica-{self.name}",
            daemon=True)
        self._loop_thread.start()
        if self.role == "prefill":
            self._handoff_thread = threading.Thread(
                target=self._handoff_loop,
                name=f"kubetpu-replica-handoff-{self.name}", daemon=True)
            self._handoff_thread.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"kubetpu-replica-http-{self.name}", daemon=True)
        self._thread.start()
        return self.address

    def drain(self, migrate_to: Optional[str] = None,
              reason: str = "drain") -> None:
        """Refuse NEW generates (503); admitted and handler-waiting
        requests run to completion — the step loop keeps stepping until
        the server goes idle. With *migrate_to*, in-flight streams are
        HANDED OFF live to that replica instead (token-exact; their
        callers learn the new owner via 409) — the drain completes as
        fast as the wire, not as slow as the longest stream.
        ``drain_timeout_s`` arms the cancel backstop either way."""
        with self._cv:
            if not self.draining:
                self.events.emit("drain", replica=self.name,
                                 reason=reason)
            self.draining = True
            if migrate_to:
                self._drain_migrate = migrate_to.rstrip("/")
            if (self.drain_timeout_s is not None
                    and self._drain_deadline is None):
                self._drain_deadline = (time.monotonic()
                                        + self.drain_timeout_s)
            if (self._drain_migrate is not None
                    and (self._drain_thread is None
                         or not self._drain_thread.is_alive())):
                # created AND started under the cv: two racing drain
                # POSTs must never both .start() one Thread object
                # (start() returns before the target body needs the cv)
                self._drain_thread = threading.Thread(
                    target=self._drain_migrate_loop,
                    args=(self._drain_migrate, reason),
                    name=f"kubetpu-replica-drain-migrate-{self.name}",
                    daemon=True)
                self._drain_thread.start()
            self._cv.notify_all()

    def _drain_migrate_loop(self, target_url: str, reason: str) -> None:
        """Hand every in-flight stream to the drain's migrate target
        until this replica is idle. Loops because queued requests
        surface as migratable only once freed slots admit them and
        their first token lands; bounded so a target refusing
        everything cannot spin forever (the drain-timeout cancel is the
        final word). The target is RE-READ each pass: a re-issued drain
        naming a different target (the first one died) must redirect
        the remaining streams, not keep shipping to a corpse."""
        deadline = time.monotonic() + max(
            30.0, 2.0 * (self.drain_timeout_s or 0.0))
        while time.monotonic() < deadline:
            with self._cv:
                if not self._running or self.server._idle():
                    return
                pending = bool(self.server.migratable_rids())
                target_url = self._drain_migrate or target_url
            if not pending:
                time.sleep(0.01)
                continue
            done, failed = self.migrate_all(target_url, reason=reason)
            # nothing committed (an unmigratable server, or a target
            # refusing everything): back off instead of spamming
            # per-stream attempts every couple of milliseconds — the
            # drain-timeout cancel remains the hard bound
            time.sleep(0.25 if done == 0 and failed else 0.002)

    def shutdown(self, graceful: bool = True, timeout: float = 10.0) -> None:
        """Stop the server. ``graceful`` drains, waits (bounded) for the
        serving object to go idle and for in-flight HTTP requests to
        finish; False simulates abrupt death (chaos tests)."""
        if graceful:
            self.drain()
            deadline = time.monotonic() + timeout
            with self._cv:
                while (not self.server._idle()
                       and time.monotonic() < deadline):
                    self._cv.wait(timeout=0.05)
            self._inflight.wait_idle(timeout)
        with self._cv:
            self._running = False
            drain_thread, self._drain_thread = self._drain_thread, None
            self._cv.notify_all()
        if drain_thread is not None:
            drain_thread.join(timeout=5.0)
        if self._handoff_thread is not None:
            self._handoff_thread.join(timeout=5.0)
            self._handoff_thread = None
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5.0)
            self._loop_thread = None
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
