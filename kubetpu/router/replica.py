"""``ReplicaServer`` — one serving replica's wire surface for the data
plane.

The slot servers (``DecodeServer`` / ``PagedDecodeServer`` and friends)
are in-process objects; ``obs.exporter.MetricsServer`` gave them a
read-only scrape surface, but nothing could *send them work* over the
wire. This server is that missing half — the leg the affinity router
(``kubetpu.router.server``) POSTs to:

    GET  /healthz    -> {"ok": true, "replica": <name>,
                         "draining": <bool>}  (open, liveness)
    GET  /load       -> ``server.load_info()`` + draining flag: the
                        CHEAP routing signal (queue depth, active
                        slots, pool free pages, prefix-cache hit rate)
                        the router polls instead of parsing /metrics
    GET  /metrics    -> Prometheus text of the serving registry
                        (latency summaries, pool gauges, prefix
                        counters, and this server's replica counters)
    GET  /slo        -> the replica's declared-SLO verdicts (JSON)
    GET  /events     -> replica + serving event logs, merged JSONL
    GET  /trace/<id> -> finished spans of one trace (the replica leg of
                        a stitched router trace)
    POST /generate   -> {"prompt": [ids], "sampling": {...}?,
                        "timeout": s?} -> {"rid", "tokens", "emitted"}
                        — synchronous generate: enqueue, wait for the
                        step loop to finish the request, return
                        prompt + emitted tokens
    POST /drain      -> stop accepting generates (503); in-flight
                        requests run to completion

Robustness (the Round-7 contract, uniformly):

- **idempotent generate**: a ``Idempotency-Key``-carrying POST is
  deduped through a bounded replay window (``run_idempotent``). A
  router retry whose first response was truncated mid-write gets the
  committed tokens REPLAYED — never a second admission, so a lost
  response can never double-allocate slots/pool pages (pinned by
  ``make router-check`` under injected partial faults);
- **graceful drain**: ``drain()`` refuses NEW generates with 503 while
  requests already admitted (or waiting on the handler) complete —
  the autoscaler's scale-down path depends on this (drain first,
  remove only once ``/load`` reads idle);
- **fault injection**: ``faults=FaultInjector(...)`` chaos-tests the
  surface like every other wire server.

Threading: the slot servers are NOT thread-safe, so one condition
variable serializes everything that touches the serving object — the
background step loop (``_poll_loop``: step while work exists, sleep
while idle) and the handler-side enqueue/result reads. Handlers block
on the condition between polls, so a finishing request wakes its waiter
within one step. This is the honest single-replica spelling: the
serving hot loop already runs one step at a time; the lock adds a
handler's enqueue (host-side bookkeeping, microseconds) to that serial
order, never a device wait.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kubetpu.api import utils
from kubetpu.obs import trace as obs_trace
from kubetpu.obs.events import EventLog, merge_events
from kubetpu.wire.httpcommon import (
    IdempotencyCache,
    InflightTracker,
    check_bearer,
    handle_guarded,
    run_idempotent,
    serve_events_jsonl,
    write_json,
    write_text,
)

DEFAULT_GENERATE_TIMEOUT = 30.0


class ReplicaServer:
    """Serve one slot server (``SlotServerBase`` contract) to the
    router data plane."""

    def __init__(
        self,
        server,
        name: str,
        host: str = "127.0.0.1",
        port: int = 0,
        token: "str | None" = None,
        faults=None,
        idem_window: float = 300.0,
        idle_wait: float = 0.005,
    ) -> None:
        """*server*: the serving object (enqueue/step/finished/
        pop_result/load_info — ``SlotServerBase`` and every subclass).
        *idle_wait*: step-loop sleep while the server is idle (bounds
        enqueue-to-first-step latency when work arrives)."""
        self.server = server
        self.name = name
        self.token = token or None
        self.faults = faults
        self.idem = IdempotencyCache(ttl=idem_window)
        self.obs_component = f"replica:{name}"
        self.events = EventLog(component=self.obs_component)
        self.draining = False
        self._inflight = InflightTracker()
        self._cv = threading.Condition()
        self._running = False
        self._idle_wait = float(idle_wait)
        # replica wire counters land on the SERVING registry so one
        # /metrics scrape carries both (the router federates it whole)
        for key in ("requests", "replays", "errors"):
            # key ranges over the fixed literal tuple above — KTP004's
            # bounded-f-string proof expands and validates every name
            self.server.obs.counter(f"kubetpu_replica_generate_{key}_total")
        replica = self

        def bump(key: str) -> None:
            # callers pass literals from the pre-registered set above
            # ktlint: disable=KTP004
            replica.server.obs.counter(
                f"kubetpu_replica_generate_{key}_total").inc()

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 (stdlib name)
                utils.logf(5, "replica %s: " + fmt, replica.name, *args)

            def _authorized(self) -> bool:
                if check_bearer(self.headers, replica.token):
                    return True
                write_json(self, 401,
                           {"error": "missing or invalid bearer token"})
                return False

            def do_GET(self):  # noqa: N802
                handle_guarded(replica, self, self._do_get)

            def _do_get(self):
                path = self.path.split("?")[0]
                if path == "/healthz":
                    write_json(self, 200, {
                        "ok": True,
                        "replica": replica.name,
                        "draining": replica.draining,
                    })
                elif not self._authorized():
                    pass  # 401 already sent
                elif path == "/load":
                    write_json(self, 200, replica.load())
                elif path == "/metrics":
                    write_text(self, 200, replica.server.metrics_text())
                elif path == "/slo":
                    slo = getattr(replica.server, "slo", None)
                    write_json(self, 200, {
                        "replica": replica.name,
                        "results": slo.results() if slo is not None else {},
                    })
                elif path == "/events":
                    serve_events_jsonl(self, replica.render_events)
                elif path.startswith("/trace/"):
                    tid = path[len("/trace/"):]
                    write_json(self, 200, {
                        "trace": tid,
                        "spans": obs_trace.tracer().spans(tid),
                    })
                else:
                    write_json(self, 404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802
                handle_guarded(replica, self, self._do_post)

            def _do_post(self):
                if not self._authorized():
                    return
                if self.path == "/drain":
                    replica.drain()
                    write_json(self, 200, {"draining": True})
                    return
                if self.path != "/generate":
                    write_json(self, 404, {"error": f"no route {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                except ValueError:
                    write_json(self, 400, {"error": "body is not JSON"})
                    return

                def replayed():
                    bump("replays")
                    replica.events.emit("generate_replay")

                run_idempotent(
                    self, replica.idem,
                    self.headers.get("Idempotency-Key"),
                    lambda: replica._generate(req),
                    on_replay=replayed,
                )

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None
        self._loop_thread: Optional[threading.Thread] = None

    # -- the generate leg ----------------------------------------------------

    def _generate(self, req: dict):
        """One generate execution -> (code, obj); runs on the handler
        thread under ``run_idempotent`` (200 commits into the replay
        window, anything else aborts so a retry re-executes). The
        draining refusal lives HERE, after the replay lookup: a keyed
        retry of an already-committed generate must get its replay even
        mid-drain (replaying mutates nothing)."""
        deadline = time.monotonic() + float(
            req.get("timeout") or DEFAULT_GENERATE_TIMEOUT)
        prompt = req.get("prompt")
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) for t in prompt)):
            return 400, {"error": "prompt must be a non-empty list of "
                                  "token ids"}
        with self._cv:
            if self.draining:
                return 503, {"error": "replica is draining"}
            if not self._running:
                return 503, {"error": "replica step loop is not running"}
            self.events.emit("generate", prompt_tokens=len(prompt))
            try:
                rid = self.server.enqueue(prompt,
                                          sampling=req.get("sampling"))
            except ValueError as e:
                return 400, {"error": str(e)}
            except Exception as e:  # noqa: BLE001 — report, stay up
                self.server.obs.counter(
                    "kubetpu_replica_generate_errors_total").inc()
                return 500, {"error": str(e)}
            self.server.obs.counter(
                "kubetpu_replica_generate_requests_total").inc()
            self._cv.notify_all()
            while not self.server.finished(rid):
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._running:
                    self.server.cancel(rid)
                    if self.server.finished(rid):
                        self.server.pop_result(rid)
                    return 503, {"error": "generate deadline exceeded"
                                 if self._running else "replica stopping"}
                self._cv.wait(timeout=min(remaining, 0.25))
            reason = self.server.expire_reason(rid)
            tokens = self.server.pop_result(rid)
        if reason is not None:
            return 503, {"error": f"request expired: {reason}"}
        return 200, {
            "rid": rid,
            "replica": self.name,
            "tokens": tokens,
            "emitted": tokens[len(prompt):],
        }

    # -- observability -------------------------------------------------------

    def load(self) -> dict:
        """The routing-signal snapshot: ``server.load_info()`` (host
        counters only — no device sync, no reservoir sort beyond the
        bounded percentile reads) plus this wire layer's flags."""
        info = dict(self.server.load_info())
        info["replica"] = self.name
        info["draining"] = self.draining
        return info

    def render_events(self, kind: Optional[str] = None,
                      limit: Optional[int] = None) -> str:
        evs = merge_events({
            self.obs_component: self.events,
            "serving": self.server.events,
        }, limit=None)
        if kind is not None:
            evs = [e for e in evs if e.get("kind") == kind]
        if limit is not None and limit >= 0:
            evs = evs[-limit:] if limit else []
        return "".join(json.dumps(e) + "\n" for e in evs)

    # -- step loop -----------------------------------------------------------

    def _poll_loop(self) -> None:
        """Drive the serving object: step while any request is in
        flight, sleep (bounded) while idle. Every touch of the serving
        object happens under the condition — the handlers' enqueue and
        result reads interleave between steps, never during one."""
        while True:
            with self._cv:
                if not self._running:
                    return
                if self.server._idle():
                    self._cv.wait(timeout=self._idle_wait)
                    continue
                self.server.step()
                self._cv.notify_all()

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> str:
        """Serve + start the step loop (both daemon threads); returns
        the bound address."""
        with self._cv:
            self._running = True
        self._loop_thread = threading.Thread(
            target=self._poll_loop, name=f"kubetpu-replica-{self.name}",
            daemon=True)
        self._loop_thread.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"kubetpu-replica-http-{self.name}", daemon=True)
        self._thread.start()
        return self.address

    def drain(self) -> None:
        """Refuse NEW generates (503); admitted and handler-waiting
        requests run to completion — the step loop keeps stepping until
        the server goes idle."""
        with self._cv:
            if not self.draining:
                self.events.emit("drain", replica=self.name)
            self.draining = True
            self._cv.notify_all()

    def shutdown(self, graceful: bool = True, timeout: float = 10.0) -> None:
        """Stop the server. ``graceful`` drains, waits (bounded) for the
        serving object to go idle and for in-flight HTTP requests to
        finish; False simulates abrupt death (chaos tests)."""
        if graceful:
            self.drain()
            deadline = time.monotonic() + timeout
            with self._cv:
                while (not self.server._idle()
                       and time.monotonic() < deadline):
                    self._cv.wait(timeout=0.05)
            self._inflight.wait_idle(timeout)
        with self._cv:
            self._running = False
            self._cv.notify_all()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5.0)
            self._loop_thread = None
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
