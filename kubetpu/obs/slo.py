"""Declarative SLOs with multi-window burn rates — the judgment layer
over the Round-8 metrics spine, and (Round-14) the decision surface
the prefix-affinity router / autoscaler consumes: ``router_slos()``
is the canned set ``kubetpu.router.RouterServer`` evaluates over its
federated scrape to shed/queue by SLO class, and whose fast-window
burn the autoscaler folds into its hot signal.

The registry records *what happened*; an SLO says *whether that is
acceptable* and *how fast the error budget is burning*. One
``Objective`` declares a service-level indicator as a selector over
Prometheus-shaped samples (metric name + label subset, optionally a
percentile for summary metrics, optionally a ratio) plus the threshold
that makes an evaluation "good":

    Objective("ttft_p95", metric="kubetpu_serving_latency_seconds",
              labels={"op": "ttft"}, percentile=95, threshold=0.25)
    Objective("pool_floor", metric="kubetpu_serving_pages_free",
              threshold=4, op=">=", reduce="min")
    Objective("availability", metric="kubetpu_nodes",
              labels={"state": "healthy"}, ratio_of="kubetpu_nodes",
              threshold=0.99, op=">=")

``SloEngine.evaluate`` runs every objective against a snapshot source —
a live ``Registry``, raw Prometheus exposition text (the controller's
already-federated fleet scrape), or a pre-parsed sample list — and
feeds each verdict into two ring-buffered windows (fast, default 5 min;
slow, default 1 h). The burn rate of a window is the SRE spelling:

    burn(window) = bad_fraction(window) / (1 - target)

i.e. how many times faster than "exactly spending the budget" the
objective is failing; sustained total violation of a target-0.99
objective reads 100. ``firing`` requires BOTH windows over the
``burn_threshold`` (default 14.4, the classic fast-page multiwindow
rule): the fast window makes a fresh outage visible within one
evaluation window, and makes recovery visible the moment recent
evaluations go good again, while the slow window keeps one blip from
paging — once there is an hour of history for it to weigh; at cold
start a totally-violating first evaluation fires immediately (there is
no evidence of health to hold the page back).

Percentile SLIs and recovery — the part naive snapshotting gets wrong:
a cumulative reservoir's p95 never recovers after an incident (the bad
samples sit in the reservoir forever). Against a LIVE registry the
engine therefore evaluates percentiles over a WINDOWED view: it ring-
buffers per-evaluation reservoir cursors and, while the histogram is
below its reservoir cap (where the reservoir is an exact append-only
log), computes the percentile over only the observations that arrived
inside the fast window. Past the cap the reservoir starts replacing and
the engine falls back to the full-reservoir estimate (slow-moving, but
never wrong about the long run). Against exposition TEXT (fleet
federation) only the rendered quantiles exist, so the nearest rendered
quantile is used as-is — documented degradation, not a silent lie.

Evaluations render as gauges on the bound registry so any scrape (and
``kubetpu.cli.obs slo``) sees them:

    kubetpu_slo_value{slo=...}            latest SLI value
    kubetpu_slo_threshold{slo=...}
    kubetpu_slo_ok{slo=...}               1 good / 0 violating
    kubetpu_slo_data{slo=...}             0 = SLI absent: value/ok above
                                          are the last definite verdict,
                                          stale, not current health
    kubetpu_slo_burn_rate{slo=...,window="fast"|"slow"}
    kubetpu_slo_firing{slo=...}
    kubetpu_slo_evaluations_total{slo=...} / kubetpu_slo_violations_total

Stdlib only; imports nothing from kubetpu outside ``obs``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

from kubetpu.obs.registry import Histogram, Registry, parse_prometheus_text

FAST_WINDOW = 300.0      # 5 min
SLOW_WINDOW = 3600.0     # 1 h
BURN_THRESHOLD = 14.4    # the SRE fast-page multiwindow constant


@dataclass(frozen=True)
class Objective:
    """One declarative SLO. ``op`` is the GOOD comparison: ``"<="`` for
    ceilings (latency), ``">="`` for floors (free pages, availability).
    ``target`` is the fraction of evaluations that must be good — the
    error budget is ``1 - target``. ``reduce`` folds multiple matching
    samples (a federated fleet scrape): "sum", "min", "max", "first"."""

    name: str
    metric: str
    threshold: float
    labels: Dict[str, str] = field(default_factory=dict)
    percentile: Optional[float] = None   # summary metrics only
    op: str = "<="
    target: float = 0.99
    ratio_of: Optional[str] = None       # denominator metric (summed)
    reduce: str = "sum"
    description: str = ""

    def __post_init__(self):
        if self.op not in ("<=", ">="):
            raise ValueError("op must be '<=' or '>='")
        if not 0.0 < self.target < 1.0:
            raise ValueError("target must be in (0, 1)")
        if self.reduce not in ("sum", "min", "max", "first"):
            raise ValueError("reduce must be sum/min/max/first")
        if self.percentile is not None and not 0 < self.percentile < 100:
            raise ValueError("percentile must be in (0, 100)")

    def good(self, value: float) -> bool:
        return value <= self.threshold if self.op == "<=" \
            else value >= self.threshold


def _pct_of(sorted_buf: List[float], p: float) -> float:
    """Nearest-rank percentile of a pre-sorted list (the repo-wide
    ``Histogram.percentile`` convention); 0.0 when empty."""
    if not sorted_buf:
        return 0.0
    idx = min(len(sorted_buf) - 1,
              max(0, int(round(p / 100.0 * (len(sorted_buf) - 1)))))
    return sorted_buf[idx]


class _Track:
    """Per-objective mutable state: the (t, ok) verdict ring (pruned at
    the slow horizon, so the deque IS the slow window), an incremental
    bad-verdict count over it (the slow burn must not rescan an hour of
    1 Hz evaluations every step), and, for live-registry percentile
    SLIs, the reservoir cursors."""

    __slots__ = ("verdicts", "bad", "cursors")

    def __init__(self) -> None:
        self.verdicts: deque = deque()       # (t, ok: bool)
        self.bad = 0                         # bad verdicts in the deque
        self.cursors: deque = deque()        # (t, reservoir length)


class SloEngine:
    """Evaluate declared objectives over snapshots; keep burn windows."""

    def __init__(
        self,
        objectives: List[Objective],
        registry: Optional[Registry] = None,
        fast_window: float = FAST_WINDOW,
        slow_window: float = SLOW_WINDOW,
        burn_threshold: float = BURN_THRESHOLD,
    ) -> None:
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names in {names}")
        for o in objectives:
            # total violation burns at 1/(1-target); a threshold above
            # that can NEVER fire — a silently dead page is worse than a
            # loud config error
            if burn_threshold > 1.0 / (1.0 - o.target) + 1e-9:
                raise ValueError(
                    f"objective {o.name!r}: burn_threshold "
                    f"{burn_threshold} is unreachable at target "
                    f"{o.target} (max burn {1.0 / (1.0 - o.target):.1f})")
        self.objectives = list(objectives)
        self.registry = registry
        self.fast_window = float(fast_window)
        self.slow_window = float(slow_window)
        self.burn_threshold = float(burn_threshold)
        self._lock = threading.Lock()
        self._tracks: Dict[str, _Track] = {o.name: _Track()
                                           for o in objectives}
        self._last: Dict[str, dict] = {}
        self._last_auto = 0.0

    # -- value resolution ----------------------------------------------------

    def _find_histogram(self, reg: Registry,
                        obj: Objective) -> Optional[Histogram]:
        for name, labels, kind, inst in reg.snapshot():
            if (name == obj.metric and kind == "summary"
                    and all(dict(labels).get(k) == str(v)
                            for k, v in obj.labels.items())):
                return inst
        return None

    def _windowed_percentile(self, obj: Objective, hist: Histogram,
                             now: float) -> float:
        """Percentile over the observations that arrived inside the fast
        window — exact while the reservoir is below cap (append-only);
        falls back to the full-reservoir estimate past it."""
        track = self._tracks[obj.name]
        count, buf = hist.tail()
        if count > len(buf):
            return _pct_of(sorted(buf), obj.percentile)   # past cap
        start = 0
        for t, cur_len in track.cursors:
            if t <= now - self.fast_window:
                start = cur_len       # latest cursor at/before window start
            else:
                break
        track.cursors.append((now, len(buf)))
        # only fast-window lookups read cursors: keep the newest one at
        # or before the window start plus everything after it
        while (len(track.cursors) > 2
               and track.cursors[1][0] <= now - self.fast_window):
            track.cursors.popleft()
        if start >= len(buf):
            return None     # no observations inside the window: the SLI
            # is ABSENT (no verdict, burn decays), never "0.0 = perfect"
        return _pct_of(sorted(buf[start:]), obj.percentile)

    @staticmethod
    def _match(samples, metric: str, want: Dict[str, str],
               need_quantile: bool = False):
        out = []
        for name, labels, value in samples:
            if name != metric:
                continue
            if not all(labels.get(k) == str(v) for k, v in want.items()):
                continue
            if need_quantile != ("quantile" in labels):
                continue
            out.append((labels, value))
        return out

    def _resolve(self, obj: Objective, source, now: float, samples_of):
        """The objective's SLI value from *source* (live Registry or a
        parsed sample list), or None when the series is absent.
        *samples_of* lazily yields the parsed sample view of the source,
        computed at most once per evaluation — a registry render sorts
        every reservoir, far too dear to repeat per objective."""
        if isinstance(source, Registry) and obj.percentile is not None:
            hist = self._find_histogram(source, obj)
            if hist is None or hist.count == 0:
                return None
            return self._windowed_percentile(obj, hist, now)
        samples = samples_of()
        if obj.percentile is not None:
            cands = self._match(samples, obj.metric, obj.labels,
                                need_quantile=True)
            if not cands:
                return None
            want_q = obj.percentile / 100.0
            # a federated scrape carries one summary PER REPLICA (extra
            # component/node labels): pick the nearest rendered quantile
            # within each series, then judge the WORST replica — max for
            # ceilings, min for floors — so one degraded replica can't
            # hide behind a healthy one that happens to parse first
            groups: Dict[Tuple, List[Tuple[float, float]]] = {}
            for labels, value in cands:
                key = tuple(sorted((k, v) for k, v in labels.items()
                                   if k != "quantile"))
                groups.setdefault(key, []).append(
                    (abs(float(labels["quantile"]) - want_q), value))
            per_series = [min(g)[1] for g in groups.values()]
            return (max(per_series) if obj.op == "<=" else min(per_series))
        cands = self._match(samples, obj.metric, obj.labels)
        if not cands:
            return None
        vals = [v for _, v in cands]
        num = {"sum": sum, "min": min, "max": max,
               "first": lambda xs: xs[0]}[obj.reduce](vals)
        if obj.ratio_of is None:
            return num
        den = sum(v for _, v in self._match(samples, obj.ratio_of, {}))
        if den:
            return num / den
        # 0/0 with the numerator series still rendering is 0% — an
        # all-nodes-dead fleet must read as total violation, not as "no
        # data" (the worst outage cannot be the one that goes silent)
        return 0.0

    # -- burn windows --------------------------------------------------------

    def _burn(self, obj: Objective, track: _Track, now: float,
              window: float) -> float:
        """Bad fraction of the verdicts inside *window*, over budget.
        Verdicts are time-ordered, so the fast window is a reversed scan
        that stops at the window edge; the slow window is the whole
        (slow-horizon-pruned) deque with its incremental bad count —
        neither rescans history that cannot be in view."""
        if window >= self.slow_window:
            n, bad = len(track.verdicts), track.bad
        else:
            n = bad = 0
            for t, ok in reversed(track.verdicts):
                if t <= now - window:
                    break
                n += 1
                bad += not ok
        if not n:
            return 0.0
        return (bad / n) / max(1.0 - obj.target, 1e-9)

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, source=None, now: Optional[float] = None) -> dict:
        """Evaluate every objective against *source* (default: the bound
        registry; or exposition text, or a parsed sample list) at time
        *now* (default wall clock — tests pass synthetic timestamps).
        Returns {objective name: result dict} and refreshes the
        ``kubetpu_slo_*`` gauges on the bound registry."""
        if source is None:
            source = self.registry
            if source is None:
                raise ValueError("no source and no bound registry")
        if isinstance(source, str):
            try:
                source = parse_prometheus_text(source)
            except ValueError:
                source = []          # degraded scrape: series go absent
        now = time.time() if now is None else float(now)
        out: Dict[str, dict] = {}
        parsed: List = []      # one-element lazy cache per evaluation

        def samples_of():
            if not isinstance(source, Registry):
                return source
            if not parsed:
                parsed.append(parse_prometheus_text(source.render()))
            return parsed[0]

        with self._lock:
            for obj in self.objectives:
                track = self._tracks[obj.name]
                value = self._resolve(obj, source, now, samples_of)
                ok: Optional[bool] = None
                if value is not None:
                    ok = obj.good(value)
                    track.verdicts.append((now, ok))
                    track.bad += not ok
                # prune even when the SLI is absent: an outage whose
                # traffic then stops must AGE OUT of the slow window,
                # not freeze burn_slow at 100 over stale verdicts
                while (track.verdicts
                       and track.verdicts[0][0] <= now - self.slow_window):
                    _t, old_ok = track.verdicts.popleft()
                    track.bad -= not old_ok
                burn_fast = self._burn(obj, track, now, self.fast_window)
                burn_slow = self._burn(obj, track, now, self.slow_window)
                firing = (burn_fast >= self.burn_threshold
                          and burn_slow >= self.burn_threshold)
                out[obj.name] = {
                    "value": value,
                    "threshold": obj.threshold,
                    "op": obj.op,
                    "target": obj.target,
                    "ok": ok,
                    "burn_fast": burn_fast,
                    "burn_slow": burn_slow,
                    "firing": firing,
                }
                self._export(obj, out[obj.name])
            self._last = out
            self._last_auto = time.monotonic()
        return out

    def maybe_evaluate(self, interval: float = 1.0, source=None) -> None:
        """Throttled evaluate — the hot-loop spelling (a serving step
        calls this; at most one evaluation per *interval* seconds)."""
        if time.monotonic() - self._last_auto >= interval:
            self.evaluate(source=source)

    def results(self) -> Dict[str, dict]:
        """The last evaluation's results (empty before the first)."""
        with self._lock:
            return dict(self._last)

    def firing(self) -> List[str]:
        """Names of objectives currently firing — the autoscaler's one
        bit per objective."""
        return [n for n, r in self.results().items() if r.get("firing")]

    def _export(self, obj: Objective, res: dict) -> None:
        """Refresh the kubetpu_slo_* gauges (caller holds the lock)."""
        if self.registry is None:
            return
        reg = self.registry
        reg.counter("kubetpu_slo_evaluations_total", slo=obj.name).inc()
        reg.gauge("kubetpu_slo_threshold", slo=obj.name).set(obj.threshold)
        # gauges cannot be un-rendered, so an SLI that has gone absent
        # would leave its last value/ok frozen on every future scrape —
        # the data bit marks them stale instead of letting "no data"
        # impersonate the last definite verdict
        reg.gauge("kubetpu_slo_data", slo=obj.name).set(
            1.0 if res["value"] is not None else 0.0)
        if res["value"] is not None:
            reg.gauge("kubetpu_slo_value", slo=obj.name).set(res["value"])
            reg.gauge("kubetpu_slo_ok", slo=obj.name).set(
                1.0 if res["ok"] else 0.0)
            if not res["ok"]:
                reg.counter("kubetpu_slo_violations_total",
                            slo=obj.name).inc()
        for window, burn in (("fast", res["burn_fast"]),
                             ("slow", res["burn_slow"])):
            reg.gauge("kubetpu_slo_burn_rate", slo=obj.name,
                      window=window).set(burn)
        reg.gauge("kubetpu_slo_firing", slo=obj.name).set(
            1.0 if res["firing"] else 0.0)


# -- canned objective sets ----------------------------------------------------


def serving_slos(
    ttft_p95_s: Optional[float] = None,
    itl_p99_s: Optional[float] = None,
    queue_wait_p99_s: Optional[float] = None,
    min_free_pages: Optional[int] = None,
    target: float = 0.99,
) -> List[Objective]:
    """The serving-replica objective set the ISSUE names — pass only the
    thresholds you care about. Latency SLIs select the Round-8
    ``kubetpu_serving_latency_seconds{op=...}`` histograms; the pool
    floor selects the paged server's free-pages gauge (min-reduced so a
    federated scrape reports the WORST replica)."""
    out: List[Objective] = []
    if ttft_p95_s is not None:
        out.append(Objective(
            "ttft_p95", metric="kubetpu_serving_latency_seconds",
            labels={"op": "ttft"}, percentile=95, threshold=ttft_p95_s,
            target=target, description="time to first token, p95"))
    if itl_p99_s is not None:
        out.append(Objective(
            "itl_p99", metric="kubetpu_serving_latency_seconds",
            labels={"op": "itl"}, percentile=99, threshold=itl_p99_s,
            target=target, description="inter-token latency, p99"))
    if queue_wait_p99_s is not None:
        out.append(Objective(
            "queue_wait_p99", metric="kubetpu_serving_latency_seconds",
            labels={"op": "queue_wait"}, percentile=99,
            threshold=queue_wait_p99_s, target=target,
            description="admission-queue wait, p99"))
    if min_free_pages is not None:
        out.append(Objective(
            "pool_free_pages", metric="kubetpu_serving_pages_free",
            threshold=float(min_free_pages), op=">=", reduce="min",
            target=target, description="paged-pool free-pages floor"))
    return out


def router_slos(
    route_p99_s: Optional[float] = None,
    ttft_p50_s: Optional[float] = None,
    queue_wait_p99_s: Optional[float] = None,
    min_free_pages: Optional[int] = None,
    max_queue_depth: Optional[int] = None,
    target: float = 0.99,
) -> List[Objective]:
    """The data-plane objective set (Round-14): what the
    ``kubetpu.router.RouterServer`` evaluates over its FEDERATED
    ``/metrics`` each refresh — the router's own end-to-end route
    latency plus the WORST replica's serving SLIs (federated percentile
    resolution already judges max-for-ceilings / min-for-floors, so one
    page-starved replica fires the set). The router sheds/queues by SLO
    class while any fast window burns; the autoscaler reads the same
    verdicts to scale."""
    out: List[Objective] = []
    if route_p99_s is not None:
        out.append(Objective(
            "route_p99", metric="kubetpu_router_latency_seconds",
            labels={"op": "route"}, percentile=99, threshold=route_p99_s,
            target=target, description="router end-to-end route, p99"))
    if ttft_p50_s is not None:
        out.append(Objective(
            "fleet_ttft_p50", metric="kubetpu_serving_latency_seconds",
            labels={"op": "ttft"}, percentile=50, threshold=ttft_p50_s,
            target=target,
            description="worst replica time to first token, p50"))
    if queue_wait_p99_s is not None:
        out.append(Objective(
            "fleet_queue_wait_p99",
            metric="kubetpu_serving_latency_seconds",
            labels={"op": "queue_wait"}, percentile=99,
            threshold=queue_wait_p99_s, target=target,
            description="worst replica admission-queue wait, p99"))
    if min_free_pages is not None:
        out.append(Objective(
            "fleet_free_pages", metric="kubetpu_serving_pages_free",
            threshold=float(min_free_pages), op=">=", reduce="min",
            target=target,
            description="tightest replica paged-pool free pages"))
    if max_queue_depth is not None:
        out.append(Objective(
            "fleet_queue_depth", metric="kubetpu_serving_queue_depth",
            threshold=float(max_queue_depth), op="<=", reduce="max",
            target=target,
            description="deepest replica admission queue"))
    return out


def disagg_slos(
    itl_p99_s: Optional[float] = None,
    ttft_p95_s: Optional[float] = None,
    queue_wait_p99_s: Optional[float] = None,
    min_free_pages: Optional[int] = None,
    handoff_success: Optional[float] = None,
    target: float = 0.99,
) -> List[Objective]:
    """The disaggregated-fleet objective set (Round-17), evaluated over
    the router's FEDERATED ``/metrics`` like ``router_slos``. The two
    pools fail differently, so the set watches both halves: the DECODE
    pool's inter-token latency ceiling and free-page floor (decode ITL
    no longer pays for anyone's prompts — this is the number
    disaggregation exists to protect), the PREFILL pool's admission
    queue wait, the client-visible route latency (the router-side
    number that INCLUDES the handoff wire hop — serving-side TTFT is
    recorded at the prefill source and excludes it), and the handoff
    success ratio
    (``kubetpu_handoffs_total{result="committed"}`` over all outcomes
    — a fleet quietly degrading to colocated serving via refused
    handoffs still meets latency SLOs while silently losing the
    topology; this objective makes that visible)."""
    out: List[Objective] = []
    if itl_p99_s is not None:
        out.append(Objective(
            "disagg_itl_p99", metric="kubetpu_serving_latency_seconds",
            labels={"op": "itl"}, percentile=99, threshold=itl_p99_s,
            target=target,
            description="decode-pool inter-token latency, p99 "
                        "(worst replica)"))
    if ttft_p95_s is not None:
        # deliberately the ROUTER's route latency, not the serving
        # ttft histogram: serving records TTFT at the PREFILL source
        # when the first token materializes — BEFORE the freeze/ship/
        # commit/adoption sequence that delivers it — so it
        # structurally excludes exactly the wire latency
        # disaggregation adds. The route op covers pick -> final
        # upstream answer including the 409-chase to the decode
        # replica: the client-visible number.
        out.append(Objective(
            "disagg_route_p95", metric="kubetpu_router_latency_seconds",
            labels={"op": "route"}, percentile=95, threshold=ttft_p95_s,
            target=target,
            description="client-visible routed-request latency incl. "
                        "the handoff hop, p95"))
    if queue_wait_p99_s is not None:
        out.append(Objective(
            "disagg_queue_wait_p99",
            metric="kubetpu_serving_latency_seconds",
            labels={"op": "queue_wait"}, percentile=99,
            threshold=queue_wait_p99_s, target=target,
            description="prefill-pool admission-queue wait, p99"))
    if min_free_pages is not None:
        out.append(Objective(
            "disagg_free_pages", metric="kubetpu_serving_pages_free",
            threshold=float(min_free_pages), op=">=", reduce="min",
            target=target,
            description="tightest decode-pool free-pages floor"))
    if handoff_success is not None:
        out.append(Objective(
            "disagg_handoff_success", metric="kubetpu_handoffs_total",
            labels={"result": "committed"},
            ratio_of="kubetpu_handoffs_total",
            threshold=float(handoff_success), op=">=", target=target,
            description="fraction of prefill->decode handoffs that "
                        "committed"))
    return out


def fleet_slos(
    min_healthy_fraction: float = 0.99,
    schedule_p99_s: Optional[float] = None,
    max_pending_pods: Optional[int] = None,
    target: float = 0.99,
) -> List[Objective]:
    """Controller-level objectives over the federated fleet scrape:
    node availability (healthy / all breaker states), scheduler latency,
    and a pending-queue ceiling."""
    out = [Objective(
        "node_availability", metric="kubetpu_nodes",
        labels={"state": "healthy"}, ratio_of="kubetpu_nodes",
        threshold=min_healthy_fraction, op=">=", target=target,
        description="fraction of nodes breaker-healthy")]
    if schedule_p99_s is not None:
        out.append(Objective(
            "schedule_p99", metric="kubetpu_schedule_latency_seconds",
            labels={"op": "schedule_pod"}, percentile=99,
            threshold=schedule_p99_s, target=target,
            description="pod schedule latency, p99"))
    if max_pending_pods is not None:
        out.append(Objective(
            "pending_pods", metric="kubetpu_pending_pods",
            threshold=float(max_pending_pods), op="<=", target=target,
            description="pods waiting for capacity"))
    return out
