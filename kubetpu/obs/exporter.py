"""``MetricsServer`` — publish any ``Registry`` (and the process tracer)
over HTTP.

The serving replicas (DecodeServer / PagedDecodeServer and friends) are
in-process objects with registries but no wire surface of their own; this
tiny stdlib server is the slot-server wire path: point it at one or more
registries (and, Round-11, event logs) and scrape

    GET /metrics      merged Prometheus text of every attached registry
    GET /healthz      liveness
    GET /trace/<id>   finished spans of one trace from the process tracer
    GET /events       attached event logs as JSON Lines, (ts, seq)-merged;
                      ``?kind=...`` filters, ``?limit=N`` keeps the tail

``kubetpu.cli.obs`` consumes these endpoints; so does the fleet
federation test rig. Threaded, ephemeral-port friendly (port 0), same
lifecycle shape as the wire servers (start/shutdown).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Union

from kubetpu.obs import trace as obs_trace
from kubetpu.obs.events import EventLog, merge_events
from kubetpu.obs.registry import Registry
from kubetpu.wire.httpcommon import (
    serve_events_jsonl,
    write_json,
    write_text,
)


class MetricsServer:
    """Expose named registries at ``/metrics`` + traces at ``/trace/<id>``
    + event logs at ``/events``."""

    def __init__(self, registries: Dict[str, Registry],
                 host: str = "127.0.0.1", port: int = 0,
                 events: Union[EventLog, Dict[str, EventLog],
                               None] = None) -> None:
        """*registries*: {component name -> Registry}; with more than one,
        every series gains a ``component="<name>"`` label via federation
        so two replicas' histograms never collide. *events*: one
        ``EventLog`` (a single replica's ``server.events``) or a
        {component name -> EventLog} map, served merged at /events."""
        self.registries = dict(registries)
        if events is None:
            events = {}
        elif isinstance(events, EventLog):
            events = {next(iter(registries), "replica"): events}
        self.event_logs: Dict[str, EventLog] = dict(events)
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 — quiet
                pass

            def do_GET(self):  # noqa: N802
                url = urllib.parse.urlsplit(self.path)
                if url.path == "/healthz":
                    write_json(self, 200, {"ok": True})
                elif url.path == "/metrics":
                    write_text(self, 200, exporter.render())
                elif url.path == "/events":
                    serve_events_jsonl(self, exporter.render_events)
                elif url.path.startswith("/trace/"):
                    tid = url.path[len("/trace/"):]
                    spans = obs_trace.tracer().spans(tid)
                    write_json(self, 200, {"trace": tid, "spans": spans})
                else:
                    write_json(self, 404, {"error": f"no route {self.path}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    def render(self) -> str:
        from kubetpu.obs.registry import federate

        if len(self.registries) == 1:
            return next(iter(self.registries.values())).render()
        return federate(
            "", {name: reg.render() for name, reg in self.registries.items()},
            label="component",
        )

    def render_events(self, kind: Optional[str] = None,
                      limit: Optional[int] = None) -> str:
        evs = merge_events(self.event_logs, limit=None)
        if kind is not None:
            evs = [e for e in evs if e.get("kind") == kind]
        if limit is not None and limit >= 0:
            evs = evs[-limit:] if limit else []
        return "".join(json.dumps(e) + "\n" for e in evs)

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> str:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="kubetpu-obs-exporter",
            daemon=True,
        )
        self._thread.start()
        return self.address

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
