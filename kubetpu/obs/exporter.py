"""``MetricsServer`` — publish any ``Registry`` (and the process tracer)
over HTTP.

The serving replicas (DecodeServer / PagedDecodeServer and friends) are
in-process objects with registries but no wire surface of their own; this
tiny stdlib server is the slot-server wire path: point it at one or more
registries and scrape

    GET /metrics      merged Prometheus text of every attached registry
    GET /healthz      liveness
    GET /trace/<id>   finished spans of one trace from the process tracer

``kubetpu.cli.obs`` consumes both endpoints; so does the fleet federation
test rig. Threaded, ephemeral-port friendly (port 0), same lifecycle
shape as the wire servers (start/shutdown).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from kubetpu.obs import trace as obs_trace
from kubetpu.obs.registry import Registry
from kubetpu.wire.httpcommon import write_json, write_text


class MetricsServer:
    """Expose named registries at ``/metrics`` + traces at ``/trace/<id>``."""

    def __init__(self, registries: Dict[str, Registry],
                 host: str = "127.0.0.1", port: int = 0) -> None:
        """*registries*: {component name -> Registry}; with more than one,
        every series gains a ``component="<name>"`` label via federation
        so two replicas' histograms never collide."""
        self.registries = dict(registries)
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 — quiet
                pass

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    write_json(self, 200, {"ok": True})
                elif self.path == "/metrics":
                    write_text(self, 200, exporter.render())
                elif self.path.startswith("/trace/"):
                    tid = self.path[len("/trace/"):]
                    spans = obs_trace.tracer().spans(tid)
                    write_json(self, 200, {"trace": tid, "spans": spans})
                else:
                    write_json(self, 404, {"error": f"no route {self.path}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    def render(self) -> str:
        from kubetpu.obs.registry import federate

        if len(self.registries) == 1:
            return next(iter(self.registries.values())).render()
        return federate(
            "", {name: reg.render() for name, reg in self.registries.items()},
            label="component",
        )

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> str:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="kubetpu-obs-exporter",
            daemon=True,
        )
        self._thread.start()
        return self.address

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
