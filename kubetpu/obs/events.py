"""Bounded structured event log — the third leg of the obs spine.

Metrics answer "how much", traces answer "where did this request go";
neither answers "what state changes happened and in what order" — the
question an operator (or the upcoming autoscaler) asks first when a
replica drains, a breaker opens, or a prefix tree starts thrashing. This
module is that answer: a bounded ring of structured EVENTS

    {"ts": <epoch seconds>, "seq": <per-log monotonic int>,
     "kind": "admit" | "retire" | "prefix_hit" | "node_suspect" | ...,
     "component": "serving" | "controller" | "agent:<name>" | None,
     "trace_id": <32-hex or absent>, ...free-form flat fields...}

recorded by the serving lifecycle (admission, retire, queue expiry,
cancel), the paged server's prefix cache (hit/evict/publish), the
adaptive-gamma controller (gamma steps), the control plane (breaker
transitions, drain, registration) and checkpointing (save/restore).

Design rules, mirroring the registry and tracer:

- **bounded**: a deque ring (``capacity``) with a ``dropped`` counter —
  a month-long serving process cannot grow without bound;
- **cheap**: one lock, one dict append; ``emit`` on a hot-ish path
  (admission, retire) costs a dict build — never a device sync or I/O
  on the recording thread's critical path beyond the optional sink
  write;
- **trace-linked**: ``emit`` captures ``obs.trace.current_trace_id()``
  so an event raised inside a wire-propagated span (an allocate, a
  submit) cross-links to its stitched trace;
- **wire-friendly**: ``to_jsonl`` renders the ring as JSON Lines — what
  ``GET /events`` serves on the agent/controller/exporter servers and
  ``validate_events_jsonl`` (the ``make obs-check`` oracle) checks.

Optional JSONL sink: ``set_sink(path)`` tees every event (append); the
process-default log honors ``KUBETPU_EVENT_SINK`` at import, matching
``KUBETPU_TRACE_SINK``.

Stdlib only; imports nothing from kubetpu outside ``obs``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from kubetpu.obs import trace as obs_trace

# keys every event carries (the JSONL schema validate_events_jsonl pins)
REQUIRED_KEYS = ("ts", "seq", "kind")


class EventLog:
    """Bounded ring of structured events + optional JSONL sink."""

    def __init__(self, capacity: int = 4096,
                 component: Optional[str] = None) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self._lock = threading.Lock()
        self._sink_lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._seq = 0
        self.dropped = 0
        self.component = component
        self._sink = None
        self._sink_path: Optional[str] = None

    def emit(self, kind: str, component: Optional[str] = None,
             **fields) -> dict:
        """Record one event; free-form *fields* ride flat in the dict
        (values must be JSON-serializable — coerced to ``str`` when not).
        The current trace id (if a span is active) is captured so the
        event cross-links to its stitched trace. Returns the event."""
        ev: Dict[str, object] = {
            "ts": time.time(),
            "kind": str(kind),
        }
        comp = component or self.component
        if comp:
            ev["component"] = comp
        tid = obs_trace.current_trace_id()
        if tid:
            ev["trace_id"] = tid
        for k, v in fields.items():
            ev[k] = v if isinstance(
                v, (str, int, float, bool, type(None))) else str(v)
        with self._lock:
            ev["seq"] = self._seq
            self._seq += 1
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)
            sink = self._sink
        if sink is not None:
            line = json.dumps(ev) + "\n"
            with self._sink_lock:
                if self._sink is not sink:   # closed/replaced concurrently
                    return ev
                try:
                    sink.write(line)
                    sink.flush()
                except OSError:
                    # a full/unwritable sink must never take the workload
                    # down; the ring keeps recording
                    self._sink = None
                    self._sink_path = None
                    try:
                        sink.close()
                    except OSError:
                        pass
        return ev

    def events(self, kind: Optional[str] = None,
               limit: Optional[int] = None) -> List[dict]:
        """Events oldest-first, optionally filtered by *kind* and
        truncated to the LAST *limit*."""
        with self._lock:
            out = list(self._events)
        if kind is not None:
            out = [e for e in out if e.get("kind") == kind]
        if limit is not None and limit >= 0:
            out = out[-limit:] if limit else []   # [-0:] is everything
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def counts(self) -> Dict[str, int]:
        """{kind: occurrences in the ring} — the compact summary bench
        rows and dashboards want."""
        out: Dict[str, int] = {}
        for e in self.events():
            out[e["kind"]] = out.get(e["kind"], 0) + 1
        return out

    def to_jsonl(self, kind: Optional[str] = None,
                 limit: Optional[int] = None) -> str:
        """The ring as JSON Lines — what ``GET /events`` serves."""
        evs = self.events(kind=kind, limit=limit)
        return "".join(json.dumps(e) + "\n" for e in evs)

    def set_sink(self, path: Optional[str]) -> None:
        """Tee every future event to *path* (append); None closes."""
        new_sink = open(path, "a", encoding="utf-8") if path else None
        with self._sink_lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except OSError:
                    pass
            self._sink_path = path
            self._sink = new_sink

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0


def merge_events(logs: Dict[str, EventLog],
                 limit: Optional[int] = None) -> List[dict]:
    """Merge several components' rings into one (ts, seq)-ordered list,
    stamping each event's ``component`` when the log didn't — the
    exporter's multi-registry sibling for ``GET /events``."""
    out: List[dict] = []
    for name, log in sorted(logs.items()):
        for e in log.events():
            if "component" not in e:
                e = dict(e, component=name)
            out.append(e)
    out.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    if limit is not None and limit >= 0:
        out = out[-limit:] if limit else []
    return out


def validate_events_jsonl(text: str) -> List[str]:
    """Problems found in *text* as an event JSONL stream (empty = valid):
    non-JSON lines, non-object lines, missing/ill-typed required keys.
    The ``make obs-check`` oracle for ``GET /events``."""
    problems: List[str] = []
    for lineno, raw in enumerate(text.splitlines(), 1):
        if not raw.strip():
            continue
        try:
            ev = json.loads(raw)
        except ValueError:
            problems.append(f"line {lineno}: not JSON: {raw[:80]!r}")
            continue
        if not isinstance(ev, dict):
            problems.append(f"line {lineno}: not an object")
            continue
        for key in REQUIRED_KEYS:
            if key not in ev:
                problems.append(f"line {lineno}: missing {key!r}")
        if "ts" in ev and not isinstance(ev["ts"], (int, float)):
            problems.append(f"line {lineno}: ts is not a number")
        if "seq" in ev and not isinstance(ev["seq"], int):
            problems.append(f"line {lineno}: seq is not an int")
        if "kind" in ev and not isinstance(ev["kind"], str):
            problems.append(f"line {lineno}: kind is not a string")
    return problems


# -- process-default log ------------------------------------------------------

_DEFAULT = EventLog()
if os.environ.get("KUBETPU_EVENT_SINK"):
    try:
        _DEFAULT.set_sink(os.environ["KUBETPU_EVENT_SINK"])
    except OSError:
        pass


def event_log() -> EventLog:
    """The process-wide event log — where code without a component-scoped
    log (checkpoint save/restore, CLIs) records. Servers create their OWN
    logs, like registries: in-process test fleets must not interleave."""
    return _DEFAULT
